"""CLI subcommand registry.

Every GUI tab of the reference (server/gui.py:176-205) plus its one legacy CLI
(Old/process_cloud.py:221-236) maps to a subcommand here; each is a thin
wrapper over pipeline/stages.py so CLI, tests, and any GUI share one
implementation.

  reconstruct   tab 1/2  decode + triangulate scan folders -> PLY
  clean         tab 3    background / cluster / radius / statistical chain
  merge-360     tab 4    sequential or pose-graph registration merge
  mesh          tab 5/7  Poisson / surface-nets mesh -> STL or PLY
  calibrate     tab 8    analyze poses, prune by error, stereo solve -> calib
  inspect-calib (O11)    human-readable calibration summary
  patterns      (A4)     write the Gray-code pattern stack to disk
  capture-serve (A2)     run the phone-capture HTTP server standalone
  serve         (new)    persistent multi-tenant scan service: submissions
                         from many tenants multiplex onto one device mesh
                         with cross-tenant batching, weighted-fair
                         admission, per-request SLOs, and Prometheus
                         /metrics (pipeline/serving.py)
  tenant        (new)    manage the serve front door's tenants: mint/rotate
                         per-tenant API keys (sha256 at rest), set rate
                         limits — `serve --auth` enforces them
  viewer        (A22)    web viewer for per-stage clouds/meshes (the operator
                         front-end: merge previews, cleanup inspection)
  scan          tab 1    capture one structured-light sequence
  auto-scan     tab 6    full turntable sweep (12 x 30 degrees)
  synth         (new)    render a synthetic scan dataset for tests/demos
  warmup        (new)    pre-compile flagship programs into the persistent cache
  doctor        (new)    bounded environment diagnosis (tunnel, lock, cache)
  pipeline      (new)    fused scan-to-print: reconstruct -> clean -> merge ->
      (alias: print)     mesh in one process with device-resident handoff and
                         a content-addressed stage cache (resume on rerun)
  report        (new)    render a traced run's flight-recorder artifacts
                         (lane timeline, stage walls, cache ratios, fault
                         ledger) from <out>/trace.jsonl + metrics.json;
                         --chrome-trace exports a Perfetto-loadable timeline
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable

_RUNNERS: dict[str, Callable[[argparse.Namespace], int]] = {}


def _cfg(args: argparse.Namespace):
    from structured_light_for_3d_model_replication_tpu import load_config
    from structured_light_for_3d_model_replication_tpu.cli import parse_overrides
    from structured_light_for_3d_model_replication_tpu.utils import faults

    cfg = load_config(getattr(args, "config", None),
                      parse_overrides(getattr(args, "set", [])))
    # arm (or disarm: empty spec) the deterministic fault-injection layer;
    # SL3D_FAULTS/SL3D_FAULTS_SEED env vars override the config section
    plan = faults.configure_from(cfg.faults)
    if plan is not None:
        print(f"[faults] CHAOS RUN: {len(plan.rules)} injection rule(s) "
              f"armed (seed {plan.seed})", file=sys.stderr)
    if cfg.parallel.backend in ("numpy", "cpu"):
        # honor the backend choice for EVERY stage: jnp-path stages (merge,
        # clean, mesh) would otherwise initialize the ambient accelerator —
        # a JAX_PLATFORMS env var does not work here because this box's
        # sitecustomize force-registers the accelerator plugin over it, so
        # the config update (authoritative, same trick as tests/conftest.py)
        # must land before first jax use
        import jax

        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":  # repin after init is ignored
            print("[config] WARNING: parallel.backend="
                  f"{cfg.parallel.backend} requested but the "
                  f"{jax.default_backend()} backend was already initialized; "
                  "stages will run on it", file=sys.stderr)
        else:
            # only a pin that actually LANDED makes later accelerator
            # requests impossible — flagging an ineffective one would warn
            # the opposite of reality
            _cfg._cpu_pinned = True
    elif getattr(_cfg, "_cpu_pinned", False):
        # the pin is process-global and permanent once jax initializes: a
        # LATER config requesting an accelerator in the same interpreter
        # (library embedding, multi-command runner) would silently run on
        # CPU without this warning (ADVICE r3)
        print("[config] WARNING: parallel.backend="
              f"{cfg.parallel.backend} requested, but an earlier config in "
              "this process pinned jax to CPU (numpy/cpu backend); "
              "accelerator stages will run on CPU. Use a fresh process for "
              "accelerator work.", file=sys.stderr)
    return cfg


def _runner(name: str):
    def deco(fn):
        _RUNNERS[name] = fn
        return fn
    return deco


def register(sub: argparse._SubParsersAction, add_config_args) -> None:
    p = sub.add_parser("reconstruct",
                       help="decode + triangulate scan folder(s) into PLY clouds")
    p.add_argument("target", help="scan folder (single), parent folder (batch), "
                                  "or comma-separated file list (files)")
    p.add_argument("--calib", required=True, help="calibration file (.mat/.npz)")
    p.add_argument("--mode", choices=["single", "batch", "files"],
                   default="single")
    p.add_argument("--output", default=None,
                   help="output .ply (single) or output directory (batch/files)")
    p.add_argument("--io-workers", type=int, default=None,
                   help="host I/O threads for the pipelined batch executor "
                        "(frame prefetch + PLY writeback; <=1 forces the "
                        "serial loop; default: parallel.io_workers)")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="frame stacks the prefetcher may hold ahead of "
                        "compute (default: parallel.prefetch_depth)")
    p.add_argument("--compute-batch", type=int, default=None,
                   help="views per device launch for the view-batched "
                        "executor (bucket-padded forward_views programs, "
                        "sharded across devices when >1 is attached; <=1 "
                        "forces the per-view dispatch loop; default: "
                        "parallel.compute_batch)")
    p.add_argument("--packed-ingest", dest="packed_ingest",
                   action="store_true", default=None,
                   help="capture-rate ingest (pipeline.packed_ingest): "
                        "load each view as a packed 1-bit bit-plane stack "
                        "(frames.slbp, or packed in the loader), stream "
                        "the ~8x-smaller planes to the device and decode "
                        "from bits on device; byte-identical outputs "
                        "(batched executor only)")
    p.add_argument("--no-packed-ingest", dest="packed_ingest",
                   action="store_false",
                   help="force raw frame-stack ingest "
                        "(pipeline.packed_ingest=false)")
    add_config_args(p)

    p = sub.add_parser("clean",
                       help="point-cloud cleanup chain on one PLY, or on "
                            "every PLY in a folder (batch mode: input is a "
                            "directory, output is the destination directory)")
    p.add_argument("input", help=".ply file, or a folder of .ply files")
    p.add_argument("output", help="output .ply (file input) or output "
                                  "directory (folder input)")
    p.add_argument("--steps", default="background,cluster,radius,statistical",
                   help="comma list drawn from background,cluster,radius,statistical")
    p.add_argument("--artifacts", default=None,
                   help="record each intermediate cloud into this directory "
                        "for the web viewer (tab-3 per-step inspection; "
                        "single-file mode only)")
    add_config_args(p)

    p = sub.add_parser(
        "pipeline", aliases=["print"],
        help="fused scan-to-print: reconstruct -> per-view clean -> "
             "merge-360 -> mesh in one process (device-resident handoff, "
             "no intermediate PLY parses); reruns resume from the "
             "content-addressed stage cache under <out>/.slscan-cache")
    p.add_argument("target", help="scan root: one folder per view")
    p.add_argument("--calib", required=True, help="calibration file (.mat/.npz)")
    p.add_argument("--out", required=True, help="output directory "
                   "(merged.ply, model.stl, .slscan-cache/)")
    p.add_argument("--steps", default="background,cluster,radius,statistical",
                   help="clean-chain steps per view (comma list; empty "
                        "string disables cleaning)")
    p.add_argument("--stl-name", default="model.stl")
    p.add_argument("--no-cache", action="store_true",
                   help="recompute every stage (skip the stage cache)")
    p.add_argument("--view-plys", action="store_true",
                   help="also emit each cleaned view as <out>/views/*.ply "
                        "(side output on the writeback queue; always "
                        "binary)")
    p.add_argument("--ascii", action="store_true",
                   help="write the FINAL merged PLY in ASCII (reference "
                        "interop; %%.4f floats — lossy, see docs/API.md). "
                        "Intermediates stay binary regardless")
    p.add_argument("--io-workers", type=int, default=None,
                   help="host I/O threads for the pipelined executor")
    p.add_argument("--prefetch-depth", type=int, default=None)
    p.add_argument("--compute-batch", type=int, default=None,
                   help="views per device launch for the reconstruct stage "
                        "(default: parallel.compute_batch)")
    p.add_argument("--stream", dest="stream", action="store_true",
                   default=None,
                   help="streaming merge (default: merge.stream): register "
                        "pair (i, i+1) the moment both views are cleaned, "
                        "overlapped with reconstruction; byte-identical to "
                        "the barrier merge")
    p.add_argument("--no-stream", dest="stream", action="store_false",
                   help="force the monolithic barrier merge "
                        "(merge.stream=false)")
    p.add_argument("--pair-batch", type=int, default=None,
                   help="ready pairs per register-lane launch "
                        "(default: merge.pair_batch)")
    p.add_argument("--incremental", dest="incremental", action="store_true",
                   default=None,
                   help="incremental assembly (default: merge.incremental; "
                        "coordinated pods with streaming merge only): fold "
                        "cleaned views and pair transforms into running "
                        "merged-cloud state as items settle, so only the "
                        "postprocess tail remains after the last item; "
                        "byte-identical to the barrier assembly")
    p.add_argument("--no-incremental", dest="incremental",
                   action="store_false",
                   help="force the monolithic assembly pass "
                        "(merge.incremental=false)")
    p.add_argument("--fused-clean", dest="fused_clean", action="store_true",
                   default=None,
                   help="HBM-resident view fastpath "
                        "(pipeline.fused_clean): compact + clean + "
                        "final-compact each batch's views on device and "
                        "sync once at the collect boundary; byte-identical "
                        "to the discrete path, degrades per-view on "
                        "failure (batched executor only)")
    p.add_argument("--no-fused-clean", dest="fused_clean",
                   action="store_false",
                   help="force the discrete host-masked clean path "
                        "(pipeline.fused_clean=false)")
    p.add_argument("--packed-ingest", dest="packed_ingest",
                   action="store_true", default=None,
                   help="capture-rate ingest (pipeline.packed_ingest): "
                        "load each view as a packed 1-bit bit-plane stack "
                        "(frames.slbp, or packed in the loader), stream "
                        "the ~8x-smaller planes to the device and decode "
                        "from bits on device; byte-identical outputs "
                        "(batched executor only)")
    p.add_argument("--no-packed-ingest", dest="packed_ingest",
                   action="store_false",
                   help="force raw frame-stack ingest "
                        "(pipeline.packed_ingest=false)")
    p.add_argument("--trace", action="store_true",
                   help="arm the flight recorder (observability.trace; env "
                        "SL3D_TRACE=1): write an append-only crash-safe "
                        "trace.jsonl event journal + metrics.json into "
                        "<out>; inspect with 'sl3d report <out>'")
    p.add_argument("--run-budget", type=float, default=None, metavar="S",
                   help="overall wall-clock budget for this run, seconds "
                        "(pipeline.run_budget_s; 0 = unbounded): exceeding "
                        "it aborts with an aborted failure manifest — the "
                        "request-deadline primitive")
    p.add_argument("--no-deadlines", action="store_true",
                   help="disable the per-lane deadline layer + stall "
                        "watchdog (deadlines.enabled=false; env "
                        "SL3D_NO_DEADLINES=1) — waits become unbounded "
                        "again, as before PR 7")
    p.add_argument("--workers", type=int, default=None, metavar="N",
                   help="coordinated multiprocess mode "
                        "(coordinator.workers): lease per-view/per-pair "
                        "items to N local worker processes with lease "
                        "expiry + work stealing; a killed or preempted "
                        "worker costs only its in-flight items, and the "
                        "output stays byte-identical to a single-process "
                        "run (grants journal to <out>/ledger.jsonl; a "
                        "crashed coordinator resumes with zero recompute)")
    add_config_args(p)

    p = sub.add_parser(
        "worker",
        help="INTERNAL: one coordinated-run worker process — spawned by "
             "'pipeline --workers N', not meant for direct use")
    p.add_argument("--spec", required=True,
                   help="worker spec JSON written by the coordinator "
                        "(<out>/.coord/workerN.json)")

    p = sub.add_parser(
        "report",
        help="render a traced pipeline run's flight-recorder artifacts: "
             "lane timeline, per-stage walls, cache hit ratios, launch/"
             "bucket table, fault ledger — works on clean, degraded, and "
             "interrupted runs")
    p.add_argument("out_dir", help="a pipeline out dir containing "
                                   "trace.jsonl (run with --trace)")
    p.add_argument("--width", type=int, default=60,
                   help="timeline width in columns")
    p.add_argument("--chrome-trace", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="also export a Chrome/Perfetto trace-event JSON "
                        "(default: <out_dir>/trace.json); load it at "
                        "ui.perfetto.dev to SEE the lane overlap")
    p.add_argument("--prometheus", action="store_true",
                   help="print metrics.json as Prometheus exposition text "
                        "instead of the human report")
    p.add_argument("--validate", action="store_true",
                   help="schema-validate the journal and exit non-zero on "
                        "any problem (the CI TRACE_SMOKE check)")
    add_config_args(p)

    p = sub.add_parser("merge-360",
                       help="register+merge a folder of per-view PLYs")
    p.add_argument("input_folder")
    p.add_argument("output")
    p.add_argument("--method", choices=["sequential", "posegraph"], default=None,
                   help="override merge.method")
    p.add_argument("--save-transforms", default=None,
                   help="write per-view 4x4 transforms as JSON")
    p.add_argument("--artifacts", default=None,
                   help="record per-step merge previews into this directory "
                        "(browse them with 'viewer', the web equivalent of the "
                        "reference's blocking per-step preview)")
    add_config_args(p)

    p = sub.add_parser("mesh", help="mesh a cloud PLY into STL or mesh-PLY")
    p.add_argument("input")
    p.add_argument("output", help=".stl or .ply output path")
    p.add_argument("--save-normals", default=None,
                   help="also dump the oriented-normals debug cloud (PLY)")
    add_config_args(p)

    p = sub.add_parser("calibrate",
                       help="analyze calibration poses and solve the stereo rig")
    p.add_argument("base_dir", help="folder of per-pose capture folders")
    p.add_argument("--output", default=None,
                   help="calibration output file (default: <base_dir>/calib.mat)")
    p.add_argument("--analyze-only", action="store_true",
                   help="only print per-pose reprojection errors")
    p.add_argument("--poses", default=None,
                   help="comma list of pose folder names to use (default: auto "
                        "pruning by error ceilings)")
    p.add_argument("--max-cam-err", type=float, default=1.0)
    p.add_argument("--max-proj-err", type=float, default=2.0)
    p.add_argument("--review", default=None, metavar="ARTIFACT_DIR",
                   help="publish per-pose errors to this viewer artifact "
                        "dir and WAIT for the operator's in-viewer pose "
                        "selection before the final solve (the reference "
                        "GUI's prune dialog, gui.py:1211-1250)")
    p.add_argument("--review-timeout", type=float, default=600.0,
                   help="seconds to wait for the in-viewer selection before "
                        "falling back to auto pruning")
    add_config_args(p)

    p = sub.add_parser("inspect-calib",
                       help="human-readable calibration summary (quality bands)")
    p.add_argument("calib", help="calibration file (.mat/.npz)")
    p.add_argument("--plot", default=None, metavar="PNG",
                   help="also render the 3-D rig geometry plot (Calib Check "
                        "tab parity) to this PNG")
    add_config_args(p)

    p = sub.add_parser("patterns", help="write the Gray-code pattern stack")
    p.add_argument("out_dir")
    add_config_args(p)

    p = sub.add_parser("capture-serve",
                       help="run the phone-capture HTTP server")
    p.add_argument("--save-dir", default="captures",
                   help="where manual /upload images land")
    p.add_argument("--viewer", action="store_true",
                   help="also serve the artifact web viewer (next port up)")
    p.add_argument("--artifact-dir", default="artifacts",
                   help="directory the --viewer browses")
    add_config_args(p)

    p = sub.add_parser(
        "serve",
        help="persistent multi-tenant scan service: POST /submit scan "
             "requests, cross-tenant batched warming on one device mesh, "
             "per-request SLOs, per-tenant quotas, Prometheus /metrics; "
             "every result byte-identical to a solo `sl3d pipeline` run. "
             "Durable: accepted requests survive kill -9 (restart over "
             "the same root resumes them); SIGTERM/SIGINT drain")
    p.add_argument("root", help="service state directory (scans/, shared "
                                "stage cache, ledger.jsonl, requests/, "
                                "serve.json)")
    p.add_argument("--host", default=None,
                   help="bind address (default: serving.host)")
    p.add_argument("--port", type=int, default=None,
                   help="bind port, 0 = ephemeral (default: serving.port)")
    p.add_argument("--max-active-scans", type=int, default=None,
                   help="scans admitted to the engine at once "
                        "(default: serving.max_active_scans)")
    p.add_argument("--drain-budget", type=float, default=None,
                   help="seconds active scans get to finish after "
                        "SIGTERM before being checkpointed for the next "
                        "start (default: serving.drain_budget_s)")
    p.add_argument("--ready-file", default=None,
                   help="also write the bound-address JSON here once "
                        "listening (CI/loadgen discovery handshake)")
    p.add_argument("--ha", action="store_true", default=None,
                   help="join the leader-elected gateway group over this "
                        "root (serving.ha_enabled): exactly one member "
                        "owns the engine, the rest serve reads and "
                        "redirect /submit to the leader; kill the leader "
                        "and a standby takes over within --ha-lease "
                        "seconds with zero recompute")
    p.add_argument("--ha-lease", type=float, default=None,
                   help="leader lease lifetime in seconds — the failover "
                        "bound (default: serving.ha_lease_s)")
    p.add_argument("--fleet", action="store_true", default=None,
                   help="elastic worker fleet (serving.fleet_enabled): "
                        "the leader gateway autoscales `sl3d worker` "
                        "processes against live queue signals, journals "
                        "every decision to the ledger, and respawns "
                        "killed workers under capped backoff with flap "
                        "damping; scale-in drains by lease expiry")
    p.add_argument("--fleet-max", type=int, default=None,
                   help="fleet size ceiling (default: "
                        "serving.fleet_max_workers)")
    p.add_argument("--fleet-min", type=int, default=None,
                   help="fleet size floor kept warm even when idle "
                        "(default: serving.fleet_min_workers)")
    p.add_argument("--auth", action="store_true", default=None,
                   help="authenticated front door (serving.auth_enabled): "
                        "/submit requires a per-tenant API key from "
                        "<root>/tenants.json (`sl3d tenant add` mints "
                        "one) and enforces per-tenant rate limits; "
                        "metered usage served at /usage")
    add_config_args(p)

    p = sub.add_parser(
        "tenant",
        help="manage the authenticated front door's tenants: `tenant add "
             "<root> <name>` mints an API key (printed ONCE; only its "
             "sha256 lands in <root>/tenants.json), `tenant list <root>` "
             "shows who exists")
    p.add_argument("action", choices=("add", "list"))
    p.add_argument("root", help="service state directory (the one "
                                "`sl3d serve` runs over)")
    p.add_argument("name", nargs="?", default=None,
                   help="tenant name (add)")
    p.add_argument("--key", default=None,
                   help="use this key instead of minting one (key "
                        "rotation; still stored hashed)")
    p.add_argument("--rate-limit", type=int, default=None,
                   help="per-tenant submits allowed per window (overrides "
                        "serving.auth_rate_limit for this tenant)")
    p.add_argument("--rate-window", type=float, default=None,
                   help="sliding window seconds for --rate-limit")
    add_config_args(p)

    p = sub.add_parser("viewer",
                       help="web viewer for per-stage artifacts (PLY/STL): "
                            "the operator front-end (GUI tab parity, "
                            "server/gui.py:1549-1564 preview flow)")
    p.add_argument("artifact_dir")
    p.add_argument("--port", type=int, default=5051)
    add_config_args(p)

    p = sub.add_parser("scan", help="capture one structured-light sequence")
    p.add_argument("save_dir")
    add_config_args(p)

    p = sub.add_parser("auto-scan", help="full 360-degree turntable sweep")
    p.add_argument("output_root")
    p.add_argument("--base-name", default="scan")
    p.add_argument("--artifacts", default=None,
                   help="record live sweep progress (elapsed/remaining) into "
                        "this directory for the web viewer")
    add_config_args(p)

    p = sub.add_parser(
        "warmup",
        help="pre-compile the flagship decode + merge programs into the "
             "persistent XLA cache (a fresh machine otherwise pays ~35 s "
             "of compiles on its first reconstruction/merge)")
    p.add_argument("--cam", default="1920x1080", help="camera WxH to warm")
    p.add_argument("--proj", default="1920x1080", help="projector WxH to warm")
    p.add_argument("--views", type=int, default=24,
                   help="batched view count for the forward_views program")
    p.add_argument("--compute-batch", type=int, default=None,
                   help="also pre-compile the batch executor's bucket-"
                        "ladder programs (full bucket + power-of-two tail "
                        "buckets, donated buffers, sharded when >1 device "
                        "is attached) for this compute_batch (default: "
                        "parallel.compute_batch; 0 skips)")
    p.add_argument("--merge-views", type=int, default=24,
                   help="turntable views for the merge-chain programs "
                        "(0 skips the merge warm)")
    p.add_argument("--merge-cam", default="480x360")
    p.add_argument("--merge-proj", default="512x256")
    p.add_argument("--cache-dir", default=".jax_cache",
                   help="persistent compilation cache directory (shared "
                        "with bench.py when run from the repo root)")
    add_config_args(p)

    p = sub.add_parser("synth",
                       help="render a synthetic turntable scan dataset")
    p.add_argument("output_root")
    p.add_argument("--views", type=int, default=4)
    p.add_argument("--cam", default="320x240", help="camera WxH")
    p.add_argument("--proj", default="256x128", help="projector WxH")
    add_config_args(p)

    p = sub.add_parser(
        "doctor",
        help="diagnose the execution environment: accelerator tunnel "
             "health (bounded probe — a wedged tunnel cannot hang this), "
             "TPU claim lock, persistent compile cache, native IO library")
    p.add_argument("--probe-timeout", type=float, default=60.0,
                   help="seconds before the backend probe is declared hung "
                        "(the full wedge signature needs ~180)")
    p.add_argument("--no-probe", action="store_true",
                   help="skip the accelerator probe (report the rest "
                        "instantly; also the switch for intentionally "
                        "cpu-only setups)")
    p.add_argument("--root", default=".",
                   help="directory whose .tpu_lock/.jax_cache to inspect "
                        "(the repo root where bench.py and tools/ run; "
                        "default: current directory)")
    add_config_args(p)


def run(args: argparse.Namespace) -> int:
    runner = _RUNNERS.get(args.command)
    if runner is None:
        raise SystemExit(f"unknown command: {args.command}")
    try:
        return runner(args)
    except RuntimeError as e:
        # the accelerator plugin can fail FAST at first jax use ("Unable
        # to initialize backend 'axon': ... not in the list of known
        # backends" — a wedge variant observed live, r4). A user command
        # should degrade to the CPU backend with a warning, like the
        # numpy path always could, not die on a broken accelerator.
        # (The hang variant cannot be caught here — `sl3d doctor`
        # diagnoses it in a bounded probe.) Retry is safe: the failure
        # fires at backend init, before the command does any work —
        # stages with per-item tolerance re-raise this error class so it
        # reaches here instead of marking every item failed.
        from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
            is_backend_init_error,
        )

        if not is_backend_init_error(e):
            raise
        import jax

        print(f"[cli] WARNING: accelerator backend failed to initialize "
              f"({str(e)[:120]}); retrying this command on the CPU "
              f"backend", file=sys.stderr)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            raise e
        _cfg._cpu_pinned = True  # keep the process-global pin advisory honest
        return runner(args)


@_runner("reconstruct")
def _cmd_reconstruct(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    cfg = _cfg(args)
    if args.io_workers is not None:
        cfg.parallel.io_workers = args.io_workers
    if args.prefetch_depth is not None:
        cfg.parallel.prefetch_depth = args.prefetch_depth
    if args.compute_batch is not None:
        cfg.parallel.compute_batch = args.compute_batch
    if args.packed_ingest is not None:
        cfg.pipeline.packed_ingest = args.packed_ingest
    report = stages.reconstruct(args.calib, args.target, mode=args.mode,
                                output=args.output, cfg=cfg)
    if report.overlap:
        o = report.overlap
        print(f"[reconstruct] pipeline overlap: load {o['load_s']}s + "
              f"compute {o['compute_s']}s + write {o['write_s']}s in "
              f"{o['critical_path_s']}s wall (x{o['overlap_ratio']})")
        if o.get("launches"):
            print(f"[reconstruct] batched compute: {o['views_dispatched']} "
                  f"views in {o['launches']} launches (mean "
                  f"{o['mean_views_per_launch']}/launch, "
                  f"{o['shard_devices']} device(s), buckets "
                  f"{list(o['bucket_first_dispatch_s'])})")
    return 0 if report.outputs and not report.failed else (2 if report.outputs else 1)


@_runner("clean")
def _cmd_clean(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    if os.path.isdir(args.input):
        # batch mode: clean every PLY in the folder on the I/O pool
        report = stages.clean_batch(args.input, args.output, cfg=_cfg(args),
                                    steps=steps)
        return 0 if report.outputs and not report.failed else \
            (2 if report.outputs else 1)
    step_cb = None
    if args.artifacts:
        from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
            StageRecorder,
        )

        rec = StageRecorder(args.artifacts)
        step_cb = lambda name, p, c: rec.save_cloud(f"clean_{name}", p, c)  # noqa: E731
    stages.clean_cloud(args.input, args.output, cfg=_cfg(args), steps=steps,
                       step_callback=step_cb)
    return 0


@_runner("pipeline")
@_runner("print")
def _cmd_pipeline(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    cfg = _cfg(args)
    if args.io_workers is not None:
        cfg.parallel.io_workers = args.io_workers
    if args.prefetch_depth is not None:
        cfg.parallel.prefetch_depth = args.prefetch_depth
    if args.compute_batch is not None:
        cfg.parallel.compute_batch = args.compute_batch
    if args.no_cache:
        cfg.pipeline.cache = False
    if args.view_plys:
        cfg.pipeline.write_view_plys = True
    if args.ascii:
        cfg.pipeline.ascii_output = True
    if args.stream is not None:
        cfg.merge.stream = args.stream
    if args.pair_batch is not None:
        cfg.merge.pair_batch = args.pair_batch
    if args.incremental is not None:
        cfg.merge.incremental = args.incremental
    if args.fused_clean is not None:
        cfg.pipeline.fused_clean = args.fused_clean
    if args.packed_ingest is not None:
        cfg.pipeline.packed_ingest = args.packed_ingest
    if args.trace:
        cfg.observability.trace = True
    if args.run_budget is not None:
        cfg.pipeline.run_budget_s = args.run_budget
    if args.no_deadlines:
        cfg.deadlines.enabled = False
    if args.workers is not None:
        cfg.coordinator.workers = args.workers
    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    report = stages.run_pipeline(args.calib, args.target, args.out, cfg=cfg,
                                 steps=steps, stl_name=args.stl_name)
    print(f"[pipeline] merge mode: {report.merge_mode} "
          f"({report.merge_status})")
    if report.assembly:
        asm = report.assembly
        tail = asm.get("tail_s")
        print(f"[pipeline] assembly: {asm.get('used_views', 0)} of "
              f"{asm.get('folded_views', 0)} folded view(s) seeded the "
              f"merge" + (f"; tail {tail}s after last item settled"
                          if tail is not None else ""))
    if report.coordinator:
        c = report.coordinator
        print(f"[pipeline] coordinator: {c['items_total']} item(s) across "
              f"{c['workers']} worker(s), steals={c.get('steals', 0)}, "
              f"resumed={c.get('resumed_completed', 0)}; ledger -> "
              f"{c['ledger']}")
        if c.get("listen"):
            fb = c.get("fabric") or {}
            print(f"[pipeline] fabric: listening on {c['listen']}; blob "
                  f"fetches={fb.get('fetches', 0)} "
                  f"pushes={fb.get('pushes', 0)} "
                  f"dedups={fb.get('dedups', 0)} "
                  f"({fb.get('bytes_fetched', 0)} B out / "
                  f"{fb.get('bytes_pushed', 0)} B in / "
                  f"{fb.get('bytes_deduped', 0)} B deduped); locality "
                  f"hits={c.get('locality_hits', 0)} "
                  f"misses={c.get('locality_misses', 0)}")
    if report.overlap:
        o = report.overlap
        clean = (f" + clean {o['clean_s']}s" if o.get("clean_s") else "")
        print(f"[pipeline] overlap: load {o['load_s']}s + compute "
              f"{o['compute_s']}s{clean} + write {o['write_s']}s in "
              f"{o['critical_path_s']}s wall (x{o['overlap_ratio']})")
        if o.get("pair_launches"):
            print(f"[pipeline] streamed merge: {o['pairs_dispatched']} "
                  f"pair(s) in {o['pair_launches']} register launch(es) "
                  f"(mean {o['mean_pairs_per_launch']}/launch, register "
                  f"{o['register_s']}s vs critical path "
                  f"{o['critical_path_s']}s)")
        if o.get("transfer_bytes_h2d") or o.get("transfer_bytes_d2h"):
            print(f"[pipeline] transfers: "
                  f"{o.get('transfer_bytes_h2d', 0)} B h2d "
                  f"({o.get('transfer_bytes_frames', 0)} B frame "
                  f"uploads) / {o.get('transfer_bytes_d2h', 0)} B d2h")
        for name, k in sorted((o.get("kernels") or {}).items()):
            print(f"[pipeline] kernel {name}: {k['launches']} launch(es), "
                  f"{k['wall_s']}s wall, {k['bytes_moved']} B moved")
    if report.cache:
        print(f"[pipeline] stage cache: {report.cache['hits']} hits, "
              f"{report.cache['misses']} misses")
    if report.failed:
        # a degraded-but-completed run is a SUCCESS with reduced coverage:
        # the STL exists, the failures are quarantined + manifested. Exit 0
        # so automation keeps flowing; an abort (below min_views) raised
        # out of run_pipeline instead and never reaches here.
        print(f"[pipeline] WARNING: completed DEGRADED — "
              f"{len(report.failed)} view(s) quarantined; see "
              f"{report.manifest_path}", file=sys.stderr)
    stalls = os.path.join(args.out, "stalls.json")
    if os.path.exists(stalls):
        print(f"[pipeline] WARNING: the stall watchdog fired during this "
              f"run; breach records + thread stacks -> {stalls}",
              file=sys.stderr)
    return 0


@_runner("worker")
def _cmd_worker(args) -> int:
    # the coordinated-run worker: config, faults, and identity all come
    # from the spec file the coordinator wrote — no _cfg() here (the
    # worker must see EXACTLY the coordinator's resolved config)
    from structured_light_for_3d_model_replication_tpu.parallel import (
        worker,
    )

    return worker.run_worker(args.spec)


@_runner("report")
def _cmd_report(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        report as replib,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        telemetry,
    )

    cfg = _cfg(args)
    trace_file = cfg.observability.trace_file
    journal = os.path.join(args.out_dir, trace_file)
    journals = replib.host_journals(args.out_dir, trace_file)
    if not journals:
        print(f"[report] no {trace_file} under {args.out_dir} — run the "
              f"pipeline with --trace (or SL3D_TRACE=1) first",
              file=sys.stderr)
        return 1

    if args.validate:
        # a coordinated run leaves one journal per host; ALL must be valid
        any_errors = False
        for jp in journals:
            errors = replib.validate_journal(jp)
            for e in errors:
                print(f"[report] INVALID: {e}", file=sys.stderr)
            print(f"[report] journal {'INVALID' if errors else 'valid'}: "
                  f"{jp}")
            any_errors = any_errors or bool(errors)
        if any_errors:
            return 1

    if not os.path.exists(journal):
        # worker journals only (the coordinator ran untraced): the merged
        # cross-host timeline is still renderable
        rows = replib.merge_host_timeline(args.out_dir, trace_file)
        if not args.validate and not args.prometheus:
            print(replib.render_host_timeline(rows))
        return 0

    if args.prometheus:
        mpath = os.path.join(args.out_dir, cfg.observability.metrics_file)
        if not os.path.exists(mpath):
            print(f"[report] no {cfg.observability.metrics_file} under "
                  f"{args.out_dir} (interrupted run?)", file=sys.stderr)
            return 1
        with open(mpath, encoding="utf-8") as f:
            print(telemetry.prometheus_text(json.load(f)), end="")
        return 0

    analysis = replib.analyze_run(
        args.out_dir, trace_file=trace_file,
        metrics_file=cfg.observability.metrics_file)
    if not args.validate:
        print(replib.render_report(analysis, width=args.width))
        if len(journals) > 1:
            # coordinated run: merge the per-host worker journals into
            # one timeline with a host column under the main report
            rows = replib.merge_host_timeline(args.out_dir, trace_file)
            print()
            print(replib.render_host_timeline(rows))

    if args.chrome_trace is not None:
        out_path = args.chrome_trace or os.path.join(args.out_dir,
                                                     "trace.json")
        info = telemetry.export_chrome_trace(journal, out_path)
        print(f"[report] chrome trace -> {out_path} ({info['events']} "
              f"events, {info['lanes']} lane(s) on {info['tracks']} "
              f"track(s)); open at ui.perfetto.dev or chrome://tracing")
    return 0


@_runner("merge-360")
def _cmd_merge(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    cfg = _cfg(args)
    if args.method:
        cfg.merge.method = args.method
    step_cb = None
    if args.artifacts:
        from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
            StageRecorder,
        )

        step_cb = StageRecorder(args.artifacts).merge_step
    _, _, transforms = stages.merge_views(args.input_folder, args.output,
                                          cfg=cfg, step_callback=step_cb)
    if args.save_transforms:
        with open(args.save_transforms, "w") as f:
            json.dump([np_t.tolist() for np_t in transforms], f, indent=2)
    return 0


@_runner("mesh")
def _cmd_mesh(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    stages.mesh_cloud(args.input, args.output, cfg=_cfg(args),
                      save_normals_path=args.save_normals)
    return 0


@_runner("calibrate")
def _cmd_calibrate(args) -> int:
    from structured_light_for_3d_model_replication_tpu.calib import chessboard as cb
    from structured_light_for_3d_model_replication_tpu.calib import inspect as ci
    from structured_light_for_3d_model_replication_tpu.calib import pipeline as cp

    cfg = _cfg(args)
    board = cb.BoardSpec(rows=cfg.checkerboard.rows, cols=cfg.checkerboard.cols,
                         square_size=cfg.checkerboard.square_size_mm)
    proj_size = (cfg.projector.width, cfg.projector.height)
    errors, observations, img_shape = cp.analyze_calibration(
        args.base_dir, board=board, proj_size=proj_size)
    print(f"{'pose':<20} {'cam px':>8} {'proj px':>8}  quality")
    for pose, (ec, ep) in sorted(errors.items()):
        print(f"{pose:<20} {ec:>8.3f} {ep:>8.3f}  {ci.quality_band(ec)}")
    if args.analyze_only:
        return 0
    if args.poses:
        selected = [p.strip() for p in args.poses.split(",") if p.strip()]
    elif args.review:
        from structured_light_for_3d_model_replication_tpu.acquire import (
            viewer as viewerlib,
        )

        viewerlib.publish_pose_review(args.review, errors)
        print(f"pose review published to {args.review} — select poses in "
              f"the viewer (sl3d viewer {args.review}); waiting up to "
              f"{args.review_timeout:.0f}s...")
        selected = viewerlib.await_pose_selection(args.review,
                                                  args.review_timeout)
        if selected is not None:
            selected = [s for s in selected if s in errors]
        if not selected:  # timeout, empty selection, or no matching names
            print("no usable selection received — falling back to auto "
                  "pruning")
            selected = cp.select_poses(errors, args.max_cam_err,
                                       args.max_proj_err)
    else:
        selected = cp.select_poses(errors, args.max_cam_err, args.max_proj_err)
    print(f"using {len(selected)}/{len(errors)} poses: {', '.join(sorted(selected))}")
    output = args.output or os.path.join(args.base_dir, "calib.mat")
    cp.calibrate_and_save(args.base_dir, output, selected_poses=selected,
                          board=board, proj_size=proj_size,
                          observations=observations, img_shape=img_shape)
    return 0


@_runner("inspect-calib")
def _cmd_inspect(args) -> int:
    from structured_light_for_3d_model_replication_tpu.calib import inspect as ci
    from structured_light_for_3d_model_replication_tpu.io import matfile

    calib = matfile.load_calibration(args.calib)
    print(ci.format_summary(ci.summarize_calibration(calib)))
    if args.plot:
        from structured_light_for_3d_model_replication_tpu.calib import visualize

        info = visualize.plot_rig(calib, args.plot)
        print(f"rig plot -> {info['plot']}")
    return 0


@_runner("patterns")
def _cmd_patterns(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    stages.write_patterns(args.out_dir, cfg=_cfg(args))
    return 0


@_runner("serve")
def _cmd_serve(args) -> int:
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        serving,
    )

    cfg = _cfg(args)
    if args.host is not None:
        cfg.serving.host = args.host
    if args.port is not None:
        cfg.serving.port = args.port
    if args.max_active_scans is not None:
        cfg.serving.max_active_scans = args.max_active_scans
    if args.drain_budget is not None:
        cfg.serving.drain_budget_s = args.drain_budget
    if args.ha:
        cfg.serving.ha_enabled = True
    if args.ha_lease is not None:
        cfg.serving.ha_lease_s = args.ha_lease
    if args.fleet:
        cfg.serving.fleet_enabled = True
    if args.fleet_max is not None:
        cfg.serving.fleet_max_workers = args.fleet_max
    if args.fleet_min is not None:
        cfg.serving.fleet_min_workers = args.fleet_min
    if args.auth:
        cfg.serving.auth_enabled = True
    return serving.serve(args.root, cfg=cfg,
                         ready_file=args.ready_file)


@_runner("tenant")
def _cmd_tenant(args) -> int:
    from structured_light_for_3d_model_replication_tpu.parallel import (
        admission,
    )

    path = os.path.join(args.root, "tenants.json")
    if args.action == "list":
        auth = admission.TenantAuth(path)
        names = auth.known()
        if not names:
            print(f"no tenants in {path}")
            return 0
        for name in names:
            lim = auth.tenant_limits(name)
            extra = (f"  rate {lim[0]}/{lim[1]:g}s" if lim else "")
            print(f"{name}{extra}")
        return 0
    if not args.name:
        print("tenant add needs a name", file=sys.stderr)
        return 1
    import secrets

    key = args.key or secrets.token_hex(16)
    os.makedirs(args.root, exist_ok=True)
    admission.write_tenant(path, args.name, key,
                           rate_limit=args.rate_limit,
                           rate_window_s=args.rate_window)
    # the only time the plaintext exists outside the client: tenants.json
    # holds sha256 only, so a leaked state dir leaks no credentials
    print(f"tenant {args.name!r} written to {path}")
    print(f"API key (save it — shown once): {key}")
    return 0


@_runner("capture-serve")
def _cmd_capture_serve(args) -> int:
    import time

    from structured_light_for_3d_model_replication_tpu.acquire.server import (
        CaptureServer,
    )

    cfg = _cfg(args).acquire
    os.makedirs(args.save_dir, exist_ok=True)
    srv = CaptureServer(cfg.http_host, cfg.http_port,
                        poll_hold=cfg.long_poll_hold_s,
                        disconnect_after=cfg.disconnect_after_s,
                        upload_dir=args.save_dir).start()
    print(f"capture server on http://{cfg.http_host}:{srv.port} "
          f"(open this on the phone; ctrl-C to stop)")
    view = None
    if getattr(args, "viewer", False):
        from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
            ViewerServer,
        )

        os.makedirs(args.artifact_dir, exist_ok=True)
        view = ViewerServer(args.artifact_dir, cfg.http_host,
                            srv.port + 1).start()
        print(f"artifact viewer on http://{cfg.http_host}:{view.port}")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
        if view is not None:
            view.stop()
    return 0


@_runner("viewer")
def _cmd_viewer(args) -> int:
    import time

    from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
        ViewerServer,
    )

    cfg = _cfg(args).acquire
    view = ViewerServer(args.artifact_dir, cfg.http_host, args.port).start()
    print(f"artifact viewer on http://{cfg.http_host}:{view.port} "
          f"(serving {args.artifact_dir}; ctrl-C to stop)")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        view.stop()
    return 0


def _build_capture_rig(cfg):
    """Projector + capture server + sequencer + turntable from AcquireConfig."""
    from structured_light_for_3d_model_replication_tpu.acquire.projector import (
        open_projector,
    )
    from structured_light_for_3d_model_replication_tpu.acquire.sequencer import (
        CaptureSequencer,
    )
    from structured_light_for_3d_model_replication_tpu.acquire.server import (
        CaptureServer,
    )
    from structured_light_for_3d_model_replication_tpu.acquire.turntable import (
        open_turntable,
    )

    a = cfg.acquire
    server = CaptureServer(a.http_host, a.http_port,
                           poll_hold=a.long_poll_hold_s,
                           disconnect_after=a.disconnect_after_s).start()
    projector = open_projector("virtual" if a.simulate else "auto",
                               screen_offset_x=cfg.projector.screen_offset_x)
    sequencer = CaptureSequencer(
        projector,
        lambda path: server.trigger_capture(path, timeout=a.capture_timeout_s),
        proj_size=(cfg.projector.width, cfg.projector.height),
        brightness=cfg.projector.brightness,
        downsample=cfg.projector.downsample,
        scan_settle_ms=a.settle_ms_scan, calib_settle_ms=a.settle_ms_calib,
        pack_frames=a.pack_frames, pack_keep_raw=a.pack_keep_raw,
    )
    turntable = open_turntable("sim" if a.simulate else "auto",
                               port=a.serial_port or None)
    return server, projector, sequencer, turntable


@_runner("scan")
def _cmd_scan(args) -> int:
    cfg = _cfg(args)
    server, projector, sequencer, turntable = _build_capture_rig(cfg)
    try:
        sequencer.capture_scan(args.save_dir)
    finally:
        projector.close()
        server.stop()
        if hasattr(turntable, "close"):
            turntable.close()
    return 0


@_runner("auto-scan")
def _cmd_auto_scan(args) -> int:
    from structured_light_for_3d_model_replication_tpu.acquire.autoscan import (
        auto_scan_360,
    )

    cfg = _cfg(args)
    progress = None
    if args.artifacts:
        from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
            StageRecorder,
        )

        progress = StageRecorder(args.artifacts).autoscan_progress
    server, projector, sequencer, turntable = _build_capture_rig(cfg)
    try:
        result = auto_scan_360(
            sequencer, turntable, args.output_root,
            turns=cfg.acquire.turns, step_deg=cfg.acquire.degrees_per_turn,
            base_name=args.base_name, rotate_timeout=cfg.acquire.rotate_timeout_s,
            capture_retries=cfg.acquire.capture_retries,
            rotate_retries=cfg.acquire.rotate_retries,
            progress=progress,
        )
    finally:
        projector.close()
        server.stop()
        if hasattr(turntable, "close"):
            turntable.close()
    return 0 if result.view_dirs else 1


@_runner("warmup")
def _cmd_warmup(args) -> int:
    """Compile the flagship programs once into the persistent XLA cache.

    A fresh checkout on a fresh machine pays the full compile bill on its
    first real scan (measured +31.8 s on the merge chain alone, round-3
    bench) — this runs the same shapes on synthetic content so every later
    process (bench.py, reconstruct, merge-360) hits warm executables.
    """
    import time

    import numpy as np

    cfg = _cfg(args)  # honor backend pin/overrides before jax initializes
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(args.cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # jax initializes the persistent cache AT MOST ONCE per process, at
        # the first compile — embedded in a process that already compiled
        # something (library use, a multi-command runner), the dir update
        # above would be silently ignored forever. Reset so the next
        # compile re-initializes against OUR directory.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # older jax without the knob/internals
        print(f"[warmup] persistent cache unavailable ({e})", file=sys.stderr)
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models.scanner import (
        SLScanner,
    )
    from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    def wh(s):
        w, h = s.lower().split("x")
        return int(w), int(h)

    cam, proj = wh(args.cam), wh(args.proj)
    print(f"[warmup] backend={jax.default_backend()} "
          f"cache={os.path.abspath(args.cache_dir)}")

    # decode+triangulate: the pattern stack itself is a decodable capture
    # (content is irrelevant to compilation; shapes + config are the key)
    base = gc.generate_pattern_stack(proj[0], proj[1])
    yi = (np.arange(cam[1]) * proj[1]) // cam[1]
    xi = (np.arange(cam[0]) * proj[0]) // cam[0]
    frames = base[:, yi[:, None], xi[None, :]]
    rig = syn.default_rig(cam_size=cam, proj_size=proj)
    for plane_eval in ("quadratic", "table"):
        sc = SLScanner(rig.calibration(), cam, proj, row_mode=1,
                       plane_eval=plane_eval)
        t0 = time.perf_counter()
        jax.block_until_ready(sc.forward(jnp.asarray(frames),
                                         thresh_mode="manual").points)
        print(f"[warmup] forward[{plane_eval}] {cam[0]}x{cam[1]}: "
              f"{time.perf_counter() - t0:.1f}s")
    if args.views > 1:
        stack = jnp.stack([jnp.roll(jnp.asarray(frames), 7 * i, axis=2)
                           for i in range(args.views)])
        t0 = time.perf_counter()
        jax.block_until_ready(
            sc.forward_views(stack, thresh_mode="manual").points)
        print(f"[warmup] forward_views[{args.views}]: "
              f"{time.perf_counter() - t0:.1f}s")

    # batch-executor bucket ladder: the batched lane runs DIFFERENT
    # programs from forward_views (donated frame buffers; shard_map when
    # >1 device) — warming only the plain program would leave the first
    # real batch paying its compile inside the measured hot path
    cb = (args.compute_batch if args.compute_batch is not None
          else cfg.parallel.compute_batch)
    if cb > 1:
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )
        from structured_light_for_3d_model_replication_tpu.pipeline.stages import (
            _view_bucket,
        )

        mesh = meshlib.views_mesh(cfg.parallel)
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        buckets = sorted({_view_bucket(v, cb, n_dev)
                          for v in range(1, cb + 1)})
        from structured_light_for_3d_model_replication_tpu.ops import (
            fused_view as fvlib,
        )

        clean_steps = ("background", "cluster", "radius", "statistical")
        frames_np = np.asarray(frames)
        for b in buckets:
            bucket_stack = np.stack([np.roll(frames_np, 7 * i, axis=2)
                                     for i in range(b)])
            t0 = time.perf_counter()
            res = sc.forward_views_batched(bucket_stack,
                                           thresh_mode="manual", mesh=mesh)
            jax.block_until_ready(res.points)
            print(f"[warmup] forward_views_batched[bucket={b}"
                  f"{f', {n_dev} devices' if mesh is not None else ''}]: "
                  f"{time.perf_counter() - t0:.1f}s")
            # fused decode->clean ladder: the HBM-resident fastpath runs
            # its own gather/clean/select programs straight off the decode
            # output — warm them per bucket so a --fused-clean run pays no
            # compile inside the drain
            t0 = time.perf_counter()
            try:
                fvlib.fused_clean_views(res.points, res.colors, res.valid,
                                        cfg.clean, clean_steps)
                print(f"[warmup] fused_clean[bucket={b}]: "
                      f"{time.perf_counter() - t0:.1f}s")
            except Exception as e:
                print(f"[warmup] fused_clean[bucket={b}] skipped ({e})",
                      file=sys.stderr)
            # packed-decode ladder: the capture-rate ingest lane decodes
            # from packed bit-planes through its OWN donated / shard_map
            # programs — warm them per bucket so a --packed-ingest run
            # pays no compile inside the streaming drain
            t0 = time.perf_counter()
            try:
                from structured_light_for_3d_model_replication_tpu.io import (
                    images as imio,
                )

                packed = [imio.pack_stack(v) for v in bucket_stack]
                res_p = sc.forward_views_packed(
                    jnp.asarray(np.stack([p.planes for p in packed])),
                    jnp.asarray(np.stack([p.white for p in packed])),
                    jnp.asarray(np.stack([p.black for p in packed])),
                    n_frames=int(frames_np.shape[0]),
                    thresh_mode="manual", mesh=mesh)
                jax.block_until_ready(res_p.points)
                print(f"[warmup] forward_views_packed[bucket={b}]: "
                      f"{time.perf_counter() - t0:.1f}s")
            except Exception as e:
                print(f"[warmup] forward_views_packed[bucket={b}] "
                      f"skipped ({e})", file=sys.stderr)

    # kernel capability probes: each Pallas kernel compiles a tiny probe
    # once per process and falls back (interpret on CPU, numpy twin on
    # probe failure) — surface the verdicts so an operator knows which
    # path real runs will take BEFORE launching one
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )

    verdicts = pk.kernel_report()
    print(f"[warmup] pallas mode: {verdicts.pop('mode')}")
    for name, ok in sorted(verdicts.items()):
        print(f"[warmup] kernel {name}: {'ok' if ok else 'fallback'}")

    if args.merge_views > 0:
        from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
            merge_360,
        )
        from structured_light_for_3d_model_replication_tpu.ops import (
            triangulate as tri,
        )

        mcam, mproj = wh(args.merge_cam), wh(args.merge_proj)
        mrig = syn.default_rig(cam_size=mcam, proj_size=mproj)
        scene = syn.Scene([
            syn.Sphere(np.array([0.0, 0.0, 420.0]), 70.0),
            syn.Sphere(np.array([55.0, -40.0, 360.0]), 28.0),
            syn.Sphere(np.array([-48.0, 35.0, 370.0]), 22.0),
        ])
        t0 = time.perf_counter()
        clouds = []
        for R, t in syn.turntable_poses(args.merge_views,
                                        360.0 / args.merge_views,
                                        pivot=np.array([0.0, 0.0, 400.0])):
            vf, _ = syn.render_scene(mrig, scene.transformed(R, t))
            dec = gc.decode_stack_np(vf, n_cols=mproj[0], n_rows=mproj[1],
                                     thresh_mode="manual")
            cloud = tri.triangulate_np(dec.col_map, dec.row_map, dec.mask,
                                       dec.texture, mrig.calibration(),
                                       row_mode=1)
            p, c = tri.compact_cloud(cloud)
            clouds.append((p.astype(np.float32), c.astype(np.uint8)))
        print(f"[warmup] rendered {args.merge_views} merge views "
              f"({time.perf_counter() - t0:.1f}s, host)")
        t0 = time.perf_counter()
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )

        # same mesh resolution as the real merge-360 stage: warming the
        # unsharded program while parallel.merge_mesh routes real runs
        # through shard_map would leave the cache cold where it matters
        merge_360(clouds, cfg=cfg.merge, log=lambda m: None,
                  mesh=meshlib.merge_mesh(cfg.parallel))
        print(f"[warmup] merge chain: {time.perf_counter() - t0:.1f}s")

        # streaming-merge register ladder: the pipeline's register lane
        # dispatches ready pairs in _pair_group_bucket-sized groups (full
        # pair_batch + power-of-two ragged tails), each a DISTINCT program
        # from the all-pairs chain launch above — warm every rung so the
        # first streamed scan pays no compile inside the overlapped lane
        if cfg.merge.stream and len(clouds) >= 2:
            from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
                _pair_group_bucket, prep_view, register_prep_pairs,
            )

            pb = max(1, cfg.merge.pair_batch)
            voxel = float(cfg.merge.voxel_size)
            preps = [prep_view(p, voxel, cfg.merge.sample_before)
                     for p, _ in clouds[:2]]
            mesh_m = meshlib.merge_mesh(cfg.parallel)
            n_dev = int(mesh_m.devices.size) if mesh_m is not None else 1
            for size in sorted({_pair_group_bucket(n, pb, n_dev)
                                for n in range(1, pb + 1)}):
                t0 = time.perf_counter()
                register_prep_pairs([(preps[1], preps[0])] * size,
                                    list(range(size)), cfg.merge, voxel,
                                    mesh=mesh_m, batch=size)
                print(f"[warmup] register ladder[group={size}"
                      f"{f', {n_dev} devices' if mesh_m is not None else ''}"
                      f"]: {time.perf_counter() - t0:.1f}s")

        # accumulate/transform ladder: finalize_chain moves every view
        # through ONE bucket-padded device batch (transform_views_batched
        # — view-count buckets x point-slot buckets, a distinct program
        # per pair). Warm each view bucket a merge of up to merge_views
        # can hit, so the assembly tail of the first real run pays no
        # compile either
        if len(clouds) >= 2:
            from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
                _bucket_pad, _transform_views_bucket, transform_views_batched,
            )

            mesh_m = meshlib.merge_mesh(cfg.parallel)
            n_dev = int(mesh_m.devices.size) if mesh_m is not None else 1
            pts = [p.astype(np.float32) for p, _ in clouds]
            slots = _bucket_pad(max(len(p) for p in pts))
            eye = np.eye(4, dtype=np.float32)
            for vb in sorted({_transform_views_bucket(n, n_dev)
                              for n in range(2, len(clouds) + 1)}):
                n = min(vb, len(pts))
                t0 = time.perf_counter()
                transform_views_batched(
                    [pts[i % len(pts)] for i in range(n)], [eye] * n,
                    mesh=mesh_m, use_device=True)
                print(f"[warmup] accumulate ladder[views={vb}, "
                      f"slots={slots}"
                      f"{f', {n_dev} devices' if mesh_m is not None else ''}"
                      f"]: {time.perf_counter() - t0:.1f}s")
    print("[warmup] done — subsequent processes reuse these executables "
          "via the persistent cache")
    return 0


@_runner("synth")
def _cmd_synth(args) -> int:
    import numpy as np

    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    def wh(s):
        w, h = s.lower().split("x")
        return int(w), int(h)

    cam, proj = wh(args.cam), wh(args.proj)
    rig = syn.default_rig(cam_size=cam, proj_size=proj)
    scene = syn.sphere_on_background()
    obj, background = scene.objects  # turntable rotates the object, not the wall
    # an off-pivot satellite breaks the main sphere's rotational symmetry:
    # every rendered view is genuinely distinct, so per-view cache keys,
    # quarantine decisions, and degraded merges exercise the real
    # multi-view paths instead of collapsing onto one identical frame set
    # above the main sphere (|y| > its radius) so no turntable angle can
    # occlude it; the xz offset gives it a real orbit
    satellite = syn.Sphere(np.array([48.0, -92.0, 430.0]), 16.0)
    os.makedirs(args.output_root, exist_ok=True)
    matfile.save_calibration(os.path.join(args.output_root, "calib.mat"),
                             rig.calibration())
    step = 360.0 / args.views
    pivot = np.array([0.0, 0.0, 420.0])  # sphere_on_background center depth
    for i, (R, t) in enumerate(syn.turntable_poses(args.views, step, pivot)):
        view_scene = syn.Scene([obj.transformed(R, t),
                                satellite.transformed(R, t), background])
        frames, _ = syn.render_scene(rig, view_scene)
        d = os.path.join(args.output_root,
                         f"scan_{int(round(i * step)):03d}deg_scan")
        imio.save_stack(d, frames)
        print(f"[synth] view {i + 1}/{args.views} -> {d}")
    print(f"[synth] calib + {args.views} views under {args.output_root}")
    return 0


@_runner("doctor")
def _cmd_doctor(args) -> int:
    """One-shot environment diagnosis. Every check is bounded: the backend
    probe runs in a subprocess (utils.preflight), so a wedged device tunnel
    prints a verdict instead of hanging the doctor itself."""
    from structured_light_for_3d_model_replication_tpu.io import native
    from structured_light_for_3d_model_replication_tpu.utils import tpulock
    from structured_light_for_3d_model_replication_tpu.utils.preflight import (
        accelerator_preflight,
    )

    ok = True
    # the lock/cache live where the TPU tooling runs (bench.py, tools/ pin
    # them to their repo root) — doctor must be pointed at that directory
    root = os.path.abspath(args.root)

    # accelerator tunnel (subprocess probe: init + one device op)
    if args.no_probe:
        print("[doctor] backend: probe skipped (--no-probe)")
    else:
        status, detail = accelerator_preflight(timeout=args.probe_timeout,
                                               cwd=root)
        healthy = status == "ok" and detail != "cpu"
        print(f"[doctor] backend: {'ok' if healthy else 'FAIL'} — "
              f"{status} ({detail})"
              + ("" if healthy else
                 "; see BENCH_NOTES.md for the wedge playbook"))
        if status == "ok" and detail == "cpu":
            # same verdict every TPU tool treats as unhealthy (tpu_session
            # healthy(), tpu_watch, bench's retry loop): either no
            # accelerator is attached, or the plugin failed fast — the
            # wedge variant that alternates with the hung signature
            print("[doctor]   cpu verdict: no accelerator attached, or "
                  "the plugin failed fast (wedge variant). Intentionally "
                  "cpu-only? use --no-probe")
        ok = ok and healthy

    # TPU claim lock: report the holder without contending for it
    held, detail = tpulock.probe_tpu_lock(root)
    if held:
        print(f"[doctor] tpu lock: HELD ({detail}) — another TPU client "
              f"is active; it releases on exit/kill")
    else:
        print(f"[doctor] tpu lock: {detail}")

    # persistent compile cache
    cache = os.path.join(root, ".jax_cache")
    if os.path.isdir(cache):
        entries = os.listdir(cache)

        def _sz(e):  # entries vanish mid-scan while jax rewrites the cache
            try:
                return os.path.getsize(os.path.join(cache, e))
            except OSError:
                return 0

        size = sum(_sz(e) for e in entries) / 1e6
        print(f"[doctor] compile cache: {len(entries)} executables, "
              f"{size:.1f} MB ({cache})")
        if not entries:
            print("[doctor]   hint: run `sl3d warmup` to pre-pay ~30 s of "
                  "first-scan compiles")
    else:
        print(f"[doctor] compile cache: absent ({cache}) — first scan pays "
              f"the full compile bill; `sl3d warmup` pre-pays it")

    # native IO library
    if native.available():
        print("[doctor] native slio: available")
    else:
        print("[doctor] native slio: not built (pure-python writers used; "
              "build with `make -C native`)")

    # optional host-side dependencies
    for mod, why in (("cv2", "chessboard detection / projector window"),
                     ("serial", "hardware turntable"),
                     ("matplotlib", "calibration rig plots")):
        try:
            __import__(mod)
            print(f"[doctor] {mod}: available")
        except ImportError:
            print(f"[doctor] {mod}: absent — {why} unavailable (everything "
                  f"else works)")

    print(f"[doctor] {'all core checks passed' if ok else 'ISSUES FOUND'}")
    return 0 if ok else 1
