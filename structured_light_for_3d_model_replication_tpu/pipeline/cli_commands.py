"""CLI subcommand registry.

Pipeline stages self-register their CLI surface here; cli.py stays a thin shell.
"""
from __future__ import annotations

import argparse
from typing import Callable

_RUNNERS: dict[str, Callable[[argparse.Namespace], int]] = {}


def register(sub: argparse._SubParsersAction, add_config_args) -> None:
    """Register all pipeline subcommands. Populated as stages land."""


def run(args: argparse.Namespace) -> int:
    runner = _RUNNERS.get(args.command)
    if runner is None:
        raise SystemExit(f"unknown command: {args.command}")
    return runner(args)
