"""File-level pipeline stages: the artifact-per-stage contract.

Every stage reads its input artifact from disk and persists its output, so any
stage can be re-entered from files alone — the checkpoint/resume model the
reference uses throughout (images -> calib.mat -> per-view .ply -> cleaned .ply
-> merged .ply -> .stl; server/gui.py tabs 2-7 are pure functions of files).
These functions are the single implementation shared by the CLI, tests, and any
future GUI front-end.

Capability parity: process_multi_ply (server/processing.py:251-334), the tab-3
cleanup chain (server/gui.py:1391-1522), merge_pro_360 (processing.py:489-629),
mesh_360/reconstruct_stl (processing.py:632-860).
"""
from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile, ply
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
from structured_light_for_3d_model_replication_tpu.utils import profiling as prof

__all__ = [
    "BatchReport", "PipelineReport", "reconstruct_source", "reconstruct",
    "clean_cloud", "clean_batch", "merge_views", "mesh_cloud",
    "run_pipeline", "sort_ply_paths_by_angle", "write_patterns",
]

_DEG_RE = re.compile(r"(\d+(?:\.\d+)?)\s*deg", re.IGNORECASE)


@dataclass
class BatchReport:
    """Per-item success/failure accounting (processing.py:314-334 semantics).

    ``overlap``: filled by the pipelined batch executor with the
    load/compute/write overlap accounting (``OverlapStats.as_dict()``);
    None on the serial path. Not part of the per-item contract — outputs,
    failed, and the summary counts are identical across executors.
    """

    outputs: list[str] = field(default_factory=list)
    failed: list[tuple[str, str]] = field(default_factory=list)
    elapsed_s: float = 0.0
    overlap: dict | None = None

    @property
    def summary(self) -> str:
        total = len(self.outputs) + len(self.failed)
        return f"{len(self.outputs)}/{total} succeeded in {self.elapsed_s:.1f}s"


def sort_ply_paths_by_angle(paths: list[str]) -> list[str]:
    """Order merge inputs by the ``"<n>deg"`` tag in the filename, falling back
    to lexical order for untagged files (server/processing.py:499-519)."""

    def key(p):
        m = _DEG_RE.search(os.path.basename(p))
        return (0, float(m.group(1)), p) if m else (1, 0.0, p)

    return sorted(paths, key=key)


def _scan_sources(target: str, mode: str, need: int, log=None) -> list[str]:
    """Resolve `target` to a list of scan-folder sources per the reference's
    single/batch/files modes (processing.py:300-322). Batch mode logs every
    folder it skips (too few frames / no frames) so a partial capture is
    diagnosable instead of silently shrinking the batch."""
    if mode == "single":
        return [target]
    if mode == "batch":
        subs = sorted(
            os.path.join(target, d) for d in os.listdir(target)
            if os.path.isdir(os.path.join(target, d))
        )
        out = []
        for s in subs:
            try:
                n = len(imio.list_frame_files(s))
            except (FileNotFoundError, NotADirectoryError):
                if log is not None:
                    log(f"[reconstruct] skipping {s}: no frame images found")
                continue
            if n >= need:
                out.append(s)
            elif log is not None:
                log(f"[reconstruct] skipping {s}: {n} frames < {need} "
                    f"required (partial capture?)")
        return out
    if mode == "files":
        return [p.strip() for p in target.split(",") if p.strip()]
    raise ValueError(f"unknown reconstruct mode {mode!r} (single|batch|files)")


def _compute_cloud(frames, texture, calib: dict, cfg: Config, scanner=None,
                   async_dispatch: bool = False):
    """Decode + triangulate one loaded stack -> CloudResult (possibly lazy).

    Backend-switched exactly like ``reconstruct_source``; the split from the
    disk load is what lets the pipelined executor prefetch stacks on a
    thread pool while this runs. ``async_dispatch`` routes the scanner path
    through ``forward_async`` (explicit device_put, no host sync) — same
    program, same numbers, only the wait point moves to the caller's drain.
    On the jax paths the returned CloudResult is in flight either way (JAX
    async dispatch); the sync happens at ``tri.compact_cloud``.
    """
    dcfg, tcfg = cfg.decode, cfg.triangulate
    ds = cfg.projector.downsample  # must match the capture-time D_SAMPLE_PROJ
    if cfg.parallel.backend == "numpy":
        dec = gc.decode_stack_np(
            frames, texture, n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate_np(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval,
        )
    elif scanner is not None and not tcfg.bitexact:
        # a bitexact config must take the branch below (device decode +
        # host-NumPy triangulation) no matter what the caller passed
        fwd = scanner.forward_async if async_dispatch else scanner.forward
        cloud = fwd(frames, thresh_mode=dcfg.thresh_mode,
                    shadow_val=dcfg.shadow_val,
                    contrast_val=dcfg.contrast_val)
    else:
        import jax.numpy as jnp

        dec = gc.decode_stack(
            jnp.asarray(frames), jnp.asarray(texture),
            n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval, bitexact=tcfg.bitexact,
        )
    return cloud


def reconstruct_source(source, calib: dict, cfg: Config, scanner=None):
    """One scan source (folder or file list) -> (points, colors) compact arrays.

    Backend-switched: ``cfg.parallel.backend == 'numpy'`` runs the bit-exact
    CPU path; ``'jax'`` runs the fused TPU program (SLScanner when provided,
    else the module-level jit kernels).
    """
    frames, texture = imio.load_stack(source,
                                      io_workers=cfg.parallel.io_workers)
    return tri.compact_cloud(_compute_cloud(frames, texture, calib, cfg,
                                            scanner))


def _item_name(src) -> str:
    return os.path.basename(os.path.normpath(src)) or "cloud"


def _out_path_for(src, mode: str, output: str | None) -> str:
    """Output-path contract shared by both executors (identical artifacts)."""
    if mode == "single" and output:
        return output
    if output:
        return os.path.join(output, f"{_item_name(src)}.ply")
    return os.path.normpath(src) + ".ply"


def _reconstruct_serial(sources, calib, cfg, scanner, mode, output, report,
                        log, clean_steps=None, collect=None,
                        write_plys=True) -> None:
    """The reference-shaped per-view loop: load, compute, write, one view at
    a time. Kept as the ``parallel.io_workers <= 1`` arm and the semantics
    twin the pipelined executor is verified against.

    ``clean_steps``/``collect``/``write_plys``: the fused-pipeline hooks,
    identical contract to the pipelined executor — an optional masked clean
    chain after compute, an in-memory per-view sink ``collect(idx, src,
    pts, cols)``, and PLY emission demoted to an optional side output."""
    timer = prof.StageTimer()
    for idx, src in enumerate(sources):
        name = _item_name(src)
        try:
            with timer.stage(name), prof.trace():
                pts, cols = reconstruct_source(src, calib, cfg, scanner)
                if clean_steps is not None:
                    pts, cols, _ = _clean_arrays(pts, cols, cfg, clean_steps)
            if write_plys:
                out_path = _out_path_for(src, mode, output)
                ply.write_ply(out_path, pts, cols)
            else:
                out_path = name
            if collect is not None:
                collect(idx, src, pts, cols)
            log(f"[reconstruct] {name}: {len(pts):,} points -> "
                f"{out_path if write_plys else 'in-memory handoff'}")
            report.outputs.append(out_path)
        except Exception as e:  # per-item tolerance (processing.py:323-330)
            from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
                is_backend_init_error,
            )

            if is_backend_init_error(e):
                # process-level condition, not an item failure: propagate
                # so the CLI's CPU-fallback retry can handle it (otherwise
                # every item "fails" identically and no retry fires)
                raise
            log(f"[reconstruct] {name} FAILED: {e}")
            report.failed.append((src, str(e)))
    prof.get_logger().debug("reconstruct stage timing:\n%s", timer.report())


def _reconstruct_pipelined(sources, calib, cfg, scanner, mode, output, report,
                           log, clean_steps=None, collect=None,
                           write_plys=True) -> None:
    """Pipelined batch executor: three (or four) overlapped stages per view.

      load     — frame stacks prefetched on an ``io_workers`` thread pool,
                 at most ``prefetch_depth`` stacks in flight (backpressure:
                 each 46x1080p stack is ~95 MB of host RAM)
      compute  — the main thread dispatches view N+1's transfer+decode+
                 triangulate while view N is still in flight (JAX async
                 dispatch; the numpy backend computes inline instead)
      clean    — (``clean_steps`` given — the fused pipeline) the drain
                 worker runs the masked clean chain on view N while the
                 main thread is already reconstructing view N+1; wall time
                 lands in the OverlapStats ``clean`` lane
      write    — a drain worker pays the device sync (``compact_cloud``)
                 and hands the compacted arrays to ``ply.WritebackQueue``,
                 so PLY encoding/disk never blocks the next dispatch.
                 ``write_plys=False`` (the fused pipeline) skips the PLY
                 side output entirely; ``collect(idx, src, pts, cols)``
                 then receives each view's cleaned compact cloud in the
                 drain thread.

    Per-item results are assembled strictly in source order at the end, so
    outputs/failed/summary are identical to ``_reconstruct_serial`` — only
    the schedule differs. Backend-init errors propagate (the CPU-fallback
    retry contract), whether they fire at dispatch or first surface at the
    drain sync.
    """
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        is_backend_init_error,
    )

    stats = prof.OverlapStats()
    depth = max(1, cfg.parallel.prefetch_depth)
    workers = cfg.parallel.io_workers

    # idx -> ("fail", src, msg) | ("done", drain_future); assembled in order
    results: dict[int, tuple] = {}
    load_pool = ThreadPoolExecutor(max_workers=workers,
                                   thread_name_prefix="sl3d-prefetch")
    drain_pool = ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="sl3d-drain")
    wbq = ply.WritebackQueue(
        on_write=lambda _path, dt: stats.add("write", dt))

    def load_one(src):
        t0 = time.perf_counter()
        out = imio.load_stack(src, io_workers=workers)
        stats.add("load", time.perf_counter() - t0)
        return out

    def drain_one(idx, src, cloud, out_path):
        # the device sync lives HERE, off the dispatch thread: compaction's
        # np.asarray blocks until the view's program retires
        t0 = time.perf_counter()
        pts, cols = tri.compact_cloud(cloud)
        stats.add("compute", time.perf_counter() - t0, items=1)
        if clean_steps is not None:
            t0 = time.perf_counter()
            pts, cols, _ = _clean_arrays(pts, cols, cfg, clean_steps)
            stats.add("clean", time.perf_counter() - t0)
        wfut = wbq.submit(out_path, pts, cols) if write_plys else None
        if collect is not None:
            collect(idx, src, pts, cols)
        return out_path, len(pts), wfut

    t_wall = time.perf_counter()
    try:
        with prof.trace():
            inflight: deque = deque()
            undrained: deque = deque()
            pending = list(enumerate(sources))
            for idx, src in pending[:depth]:
                inflight.append((idx, src, load_pool.submit(load_one, src)))
            next_i = len(inflight)
            while inflight:
                idx, src, lfut = inflight.popleft()
                stats.sample_queue(len(inflight))
                if next_i < len(pending):       # keep the prefetch bound full
                    j, s = pending[next_i]
                    inflight.append((j, s, load_pool.submit(load_one, s)))
                    next_i += 1
                try:
                    frames, texture = lfut.result()
                except Exception as e:
                    results[idx] = ("fail", src, str(e))
                    continue
                # backpressure on the compute->drain side too: at most
                # depth+1 dispatched-but-undrained clouds live at once
                # (each holds a full uncompacted H*W result on host or in
                # HBM), so batch size never multiplies peak memory.
                # Future.exception() blocks without raising — per-item
                # errors stay with the in-order drain below.
                while len(undrained) > depth:
                    undrained.popleft().exception()
                try:
                    t0 = time.perf_counter()
                    cloud = _compute_cloud(frames, texture, calib, cfg,
                                           scanner, async_dispatch=True)
                    stats.add("compute", time.perf_counter() - t0)
                except Exception as e:
                    if is_backend_init_error(e):
                        raise
                    results[idx] = ("fail", src, str(e))
                    continue
                out_path = (_out_path_for(src, mode, output) if write_plys
                            else _item_name(src))
                dfut = drain_pool.submit(drain_one, idx, src, cloud,
                                         out_path)
                undrained.append(dfut)
                results[idx] = ("done", dfut)

            # ---- in-order drain: the single sync point of the pipeline ----
            for idx, src in pending:
                name = _item_name(src)
                kind, *rest = results[idx]
                if kind == "done":
                    try:
                        out_path, n_pts, wfut = rest[0].result()
                        if wfut is not None:
                            wfut.result()       # surface write errors
                        log(f"[reconstruct] {name}: {n_pts:,} points -> "
                            f"{out_path if wfut is not None else 'in-memory handoff'}")
                        report.outputs.append(out_path)
                        continue
                    except Exception as e:
                        # a backend-init failure can first surface at the
                        # drain sync — still a process-level condition
                        if is_backend_init_error(e):
                            raise
                        rest = [src, str(e)]
                log(f"[reconstruct] {name} FAILED: {rest[-1]}")
                report.failed.append((src, rest[-1]))
    finally:
        load_pool.shutdown(wait=False, cancel_futures=True)
        drain_pool.shutdown(wait=False, cancel_futures=True)
        wbq.close(wait=True)
    stats.finish(time.perf_counter() - t_wall)
    report.overlap = stats.as_dict()
    prof.get_logger().debug("reconstruct pipeline overlap: %s",
                            stats.summary())


def _build_scanner(sources, calib, cfg: Config):
    """SLScanner for the fused device program, or None for the NumPy /
    bitexact paths (which triangulate through the host twin). Shared by
    ``reconstruct`` and ``run_pipeline``."""
    # bitexact export triangulates through the NumPy twin in
    # reconstruct_source, never the scanner's fused program
    if cfg.parallel.backend == "numpy" or cfg.triangulate.bitexact:
        return None
    from structured_light_for_3d_model_replication_tpu.models.scanner import (
        SLScanner,
    )

    first = imio.list_frame_files(sources[0])
    probe = imio.load_gray(first[0])
    return SLScanner(
        calib, (probe.shape[1], probe.shape[0]),
        proj_size=(cfg.decode.n_cols, cfg.decode.n_rows),
        row_mode=cfg.triangulate.row_mode,
        epipolar_tol=cfg.triangulate.epipolar_tol,
        n_sets_col=cfg.decode.n_sets_col, n_sets_row=cfg.decode.n_sets_row,
        downsample=cfg.projector.downsample,
        plane_eval=cfg.triangulate.plane_eval,
    )


def reconstruct(calib_path: str, target: str, mode: str = "single",
                output: str | None = None, cfg: Config | None = None,
                log=print) -> BatchReport:
    """Scan folder(s) -> per-view colored PLY (process_multi_ply parity).

    ``output``: for single mode a .ply path (default: <target>.ply); for
    batch/files a directory (default: alongside each source).

    Multi-view batches run on the pipelined executor (prefetch + async
    device dispatch + background writeback — ``_reconstruct_pipelined``)
    when ``cfg.parallel.io_workers > 1``; outputs and the report are
    identical to the serial loop, which remains the ``io_workers <= 1``
    fallback and the single-view path.
    """
    cfg = cfg or Config()
    calib = matfile.load_calibration(calib_path)
    need = gc.frames_per_view(cfg.decode.n_cols, cfg.decode.n_rows,
                              cfg.projector.downsample)
    sources = _scan_sources(target, mode, need, log=log)
    if not sources:
        raise ValueError(f"no scan sources found under {target!r} (mode={mode})")

    scanner = _build_scanner(sources, calib, cfg)

    report = BatchReport()
    if output and mode != "single":
        os.makedirs(output, exist_ok=True)
    t0 = time.monotonic()
    if cfg.parallel.io_workers > 1 and len(sources) > 1:
        _reconstruct_pipelined(sources, calib, cfg, scanner, mode, output,
                               report, log)
    else:
        _reconstruct_serial(sources, calib, cfg, scanner, mode, output,
                            report, log)
    report.elapsed_s = time.monotonic() - t0
    log(f"[reconstruct] {report.summary}")
    return report


_CLEAN_STEPS = ("background", "cluster", "radius", "statistical")


def _clean_arrays(pts: np.ndarray, cols: np.ndarray, cfg: Config,
                  steps=_CLEAN_STEPS, log=None, step_callback=None):
    """Masked-chain cleanup of one in-memory cloud; the single implementation
    behind clean_cloud, the batch clean, and the fused pipeline's clean lane.

    Runs ops/pointcloud.clean_chain: every step narrows a validity mask over
    a _bucket_pad-padded fixed shape — ONE jitted program, one compile per
    (bucket, params) pair across all views and reruns, host compaction only
    once at the end. Returns (pts', cols', counts dict) with the same
    counts/log/abort semantics the file-level chain always had."""
    from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
        _bucket_pad,
    )
    from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc

    log = log or (lambda m: None)
    n = len(pts)
    counts = {"input": n}
    params = pc.chain_params(cfg.clean, tuple(steps))
    if not params:
        return pts, cols, counts
    if cfg.parallel.backend == "numpy":
        masks, cnts = pc.clean_chain_np(pts, np.ones(n, bool), cfg.clean,
                                        tuple(steps))
        masks, cnts = np.asarray(masks), np.asarray(cnts)
    else:
        import jax.numpy as jnp

        bucket = _bucket_pad(n)
        pts_pad = pts
        if bucket > n:
            pts_pad = np.concatenate(
                [pts, np.full((bucket - n, 3), 1e9, np.float32)])
        valid = np.arange(bucket) < n
        masks_d, cnts_d = pc.clean_chain(jnp.asarray(pts_pad),
                                         jnp.asarray(valid), cfg.clean,
                                         tuple(steps))
        masks = np.asarray(masks_d)[:, :n]
        cnts = np.asarray(cnts_d)
    final = masks[-1] if len(params) else np.ones(n, bool)
    for i, (step, _) in enumerate(params):
        counts[step] = int(cnts[i])
        log(f"[clean] {step}: {int(cnts[i]):,} points remain")
        if step_callback is not None:
            step_callback(step, pts[masks[i]], cols[masks[i]])
        if int(cnts[i]) == 0:
            log("[clean] WARNING: all points removed; aborting chain")
            final = masks[i]
            break
    return pts[final], cols[final], counts


def clean_cloud(input_ply: str, output_ply: str, cfg: Config | None = None,
                steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                log=print, step_callback=None) -> dict:
    """Cleanup chain on one cloud PLY: background plane removal -> largest
    cluster -> radius outlier -> statistical outlier (the tab-3 chain,
    gui.py:1391-1522; ops per processing.py:337-448). Steps are individually
    selectable. ``step_callback(name, points, colors)`` receives each
    intermediate cloud (the tab's in-memory per-step inspection flow, made
    non-blocking). The chain itself is the masked fixed-shape program
    (ops/pointcloud.clean_chain) — this wrapper only adds the PLY boundary."""
    cfg = cfg or Config()
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)
    cols = np.asarray(data.get("colors")) if data.get("colors") is not None \
        else np.zeros_like(pts, dtype=np.uint8)
    pts, cols, counts = _clean_arrays(pts, cols, cfg, tuple(steps), log=log,
                                      step_callback=step_callback)
    ply.write_ply(output_ply, pts, cols)
    log(f"[clean] wrote {output_ply} ({len(pts):,} points)")
    return counts


def clean_batch(input_folder: str, output_folder: str,
                cfg: Config | None = None,
                steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                log=print) -> BatchReport:
    """Clean every PLY in a folder: reads on the shared I/O pool, the masked
    chain per cloud (clouds sharing a _bucket_pad bucket share ONE compile),
    writes on the WritebackQueue — the batch twin of clean_cloud with
    reconstruct's per-item failure tolerance."""
    from concurrent.futures import ThreadPoolExecutor

    cfg = cfg or Config()
    paths = sorted(os.path.join(input_folder, f)
                   for f in os.listdir(input_folder)
                   if f.lower().endswith(".ply"))
    if not paths:
        raise ValueError(f"no .ply files in {input_folder!r}")
    os.makedirs(output_folder, exist_ok=True)
    report = BatchReport()
    t0 = time.monotonic()
    workers = max(1, cfg.parallel.io_workers)
    with ThreadPoolExecutor(max_workers=min(workers, len(paths)),
                            thread_name_prefix="sl3d-cleanread") as pool, \
            ply.WritebackQueue() as wbq:
        reads = [(p, pool.submit(ply.read_ply, p)) for p in paths]
        pend = []
        for src, fut in reads:
            name = os.path.basename(src)
            try:
                data = fut.result()
                pts = np.asarray(data["points"], np.float32)
                cols = (np.asarray(data["colors"])
                        if data.get("colors") is not None
                        else np.zeros_like(pts, dtype=np.uint8))
                pts, cols, counts = _clean_arrays(pts, cols, cfg,
                                                  tuple(steps))
                out_path = os.path.join(output_folder, name)
                pend.append((src, out_path, len(pts),
                             wbq.submit(out_path, pts, cols)))
            except Exception as e:
                log(f"[clean] {name} FAILED: {e}")
                report.failed.append((src, str(e)))
        for src, out_path, n_pts, wfut in pend:
            try:
                wfut.result()
                log(f"[clean] {os.path.basename(src)}: {n_pts:,} points -> "
                    f"{out_path}")
                report.outputs.append(out_path)
            except Exception as e:
                log(f"[clean] {os.path.basename(src)} FAILED: {e}")
                report.failed.append((src, str(e)))
    report.elapsed_s = time.monotonic() - t0
    log(f"[clean] {report.summary}")
    return report


def merge_views(input_folder: str, output_ply: str, cfg: Config | None = None,
                log=print, step_callback=None):
    """Folder of per-view PLYs -> one registered 360-degree cloud
    (merge_pro_360 parity; ``cfg.merge.method`` picks greedy sequential (A18)
    or pose-graph global optimization (Old/360Merge.py:50-78 capability)).
    ``step_callback(i, points, colors)`` mirrors the reference's per-step
    merge preview hook (processing.py:600-603) — e.g. a
    acquire.viewer.StageRecorder.merge_step for the web viewer."""
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )

    cfg = cfg or Config()
    out_abs = os.path.abspath(output_ply)
    paths = sort_ply_paths_by_angle([
        p for f in os.listdir(input_folder)
        if f.lower().endswith(".ply")
        and os.path.abspath(p := os.path.join(input_folder, f)) != out_abs
    ])
    if len(paths) < 2:
        raise ValueError(f"need >= 2 PLY views in {input_folder}, found {len(paths)}")
    log(f"[merge] {len(paths)} views: " + ", ".join(os.path.basename(p) for p in paths))
    # per-view PLY reads on the shared I/O pool (parallel.io_workers; the
    # registration can't start early anyway, so amortize the disk wall);
    # pool.map preserves path order, so the merge chain is unchanged
    if cfg.parallel.io_workers > 1 and len(paths) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(cfg.parallel.io_workers, len(paths)),
                thread_name_prefix="sl3d-plyread") as pool:
            datas = list(pool.map(ply.read_ply, paths))
    else:
        datas = [ply.read_ply(p) for p in paths]
    clouds = []
    for d in datas:
        c = d.get("colors")
        if c is None:
            c = np.zeros_like(d["points"], dtype=np.uint8)
        clouds.append((np.asarray(d["points"], np.float32), np.asarray(c, np.uint8)))

    mesh = None
    if cfg.parallel.merge_mesh:
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )

        mesh = meshlib.merge_mesh(cfg.parallel)
        if mesh is not None:
            log(f"[merge] sharding the chain over "
                f"{mesh.devices.size} devices (parallel.merge_mesh)")
    # parallel.force_bf16_features=true FORCES the opt-in bf16 feature
    # matmuls; false (default) leaves the auto policy (f32 everywhere
    # since the r5 on-chip quality sweep; see _resolve_feat_bf16)
    fb16 = True if cfg.parallel.force_bf16_features else None
    with prof.trace():
        if cfg.merge.method == "posegraph":
            points, colors, transforms = recon.merge_360_posegraph(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
        else:
            points, colors, transforms = recon.merge_360(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
    ply.write_ply(output_ply, points, colors)
    log(f"[merge] wrote {output_ply} ({len(points):,} points)")
    return points, colors, transforms


def _mesh_arrays(pts: np.ndarray, cfg: Config, log=print, normals=None):
    """Cloud arrays -> (verts, faces): the meshing stage without the PLY
    boundary — normals estimated+oriented when not supplied. Shared by
    mesh_cloud and the fused pipeline."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models import meshing
    from structured_light_for_3d_model_replication_tpu.ops import normals as nrm

    valid = np.ones(len(pts), bool)
    if normals is None:
        nr = nrm.estimate_normals(jnp.asarray(pts), jnp.asarray(valid),
                                  k=cfg.mesh.normal_max_nn,
                                  radius=cfg.mesh.normal_radius or None)
        nr = nrm.orient_normals(jnp.asarray(pts), nr, jnp.asarray(valid),
                                mode=cfg.mesh.orientation)
        normals = np.asarray(nr)
        log(f"[mesh] estimated normals (k={cfg.mesh.normal_max_nn}, "
            f"{cfg.mesh.orientation} orientation)")
    with prof.trace():
        verts, faces = meshing.reconstruct_mesh(pts, valid, normals,
                                                cfg=cfg.mesh, log=log)
    return np.asarray(verts), np.asarray(faces), normals


def _write_mesh(output_path: str, verts, faces, log=print):
    from structured_light_for_3d_model_replication_tpu.models import meshing

    if output_path.lower().endswith(".stl"):
        meshing.mesh_to_stl(output_path, verts, faces)
    else:
        ply.write_mesh_ply(output_path, verts, faces)
    log(f"[mesh] wrote {output_path} ({len(verts):,} verts, {len(faces):,} faces)")


def mesh_cloud(input_ply: str, output_path: str, cfg: Config | None = None,
               save_normals_path: str | None = None, log=print):
    """Cloud PLY -> mesh (.stl or .ply by extension): reconstruct_stl/mesh_360
    parity including the optional normals debug dump (processing.py:690-693)."""
    cfg = cfg or Config()
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)

    verts, faces, normals = _mesh_arrays(pts, cfg, log=log,
                                         normals=data.get("normals"))
    if save_normals_path:
        ply.write_ply(save_normals_path, pts, data.get("colors"), normals)
        log(f"[mesh] normals debug cloud -> {save_normals_path}")
    _write_mesh(output_path, verts, faces, log=log)
    return verts, faces


@dataclass
class PipelineReport:
    """Accounting for one fused scan-to-print run."""

    merged_ply: str | None = None
    stl_path: str | None = None
    views_computed: int = 0
    views_cached: int = 0
    failed: list[tuple[str, str]] = field(default_factory=list)
    merge_status: str = ""          # 'computed' | 'cache-hit'
    mesh_status: str = ""
    merged_points: int = 0
    overlap: dict | None = None     # executor lanes incl. the clean lane
    cache: dict | None = None       # StageCache.stats()
    elapsed_s: float = 0.0

    @property
    def summary(self) -> str:
        return (f"{self.views_computed} views computed + "
                f"{self.views_cached} cached, merge {self.merge_status}, "
                f"mesh {self.mesh_status}, {self.merged_points:,} points "
                f"in {self.elapsed_s:.1f}s")


def run_pipeline(calib_path: str, target: str, out_dir: str,
                 cfg: Config | None = None,
                 steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                 merged_name: str = "merged.ply",
                 stl_name: str = "model.stl", log=print) -> PipelineReport:
    """The fused scan-to-print command: reconstruct -> per-view masked clean
    -> merge-360 -> mesh, end to end in ONE process with device-resident
    handoff — per-view clouds flow from the pipelined executor's clean lane
    into ``merge_360`` as a ``DeviceClouds`` stack, and the merged cloud is
    meshed from memory. No stage reads another stage's PLY: PLY/STL emission
    is a side output (intermediates always binary; the final merged PLY
    honors ``pipeline.ascii_output``).

    Every stage sits behind the content-addressed StageCache under
    ``<out_dir>/.slscan-cache``: a rerun (after an interrupt, or after
    editing frames/calibration/config) recomputes only the stages whose
    inputs changed — a fully-warm rerun does zero decode/clean/merge/mesh
    compute and just re-emits the artifacts.
    """
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        StageCache, config_subtree,
    )

    cfg = cfg or Config()
    t_start = time.monotonic()
    calib = matfile.load_calibration(calib_path)
    need = gc.frames_per_view(cfg.decode.n_cols, cfg.decode.n_rows,
                              cfg.projector.downsample)
    sources = _scan_sources(target, "batch", need, log=log)
    if len(sources) < 2:
        raise ValueError(
            f"pipeline needs >= 2 scan views under {target!r}, found "
            f"{len(sources)}")
    # the merge chain is angle-ordered; scan folders carry the same
    # '<n>deg' tag the per-view PLYs would, so the fused run and the
    # discrete reconstruct->merge-360 chain see the views in one order
    sources = sort_ply_paths_by_angle(sources)
    os.makedirs(out_dir, exist_ok=True)
    report = PipelineReport()
    cache = StageCache(os.path.join(out_dir, ".slscan-cache"),
                       enabled=cfg.pipeline.cache, log=log)

    # ---- stage 1+2: per-view reconstruct + masked clean -----------------
    steps = tuple(steps)
    view_cfg = config_subtree(cfg, ("decode", "triangulate", "projector",
                                    "clean")) + json.dumps(
        {"steps": list(steps), "backend": cfg.parallel.backend})
    view_keys: list[str] = []
    collected: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    missing: list[tuple[int, str]] = []
    for i, src in enumerate(sources):
        key = cache.key("view", files=[calib_path] + imio.list_frame_files(src),
                        config_json=view_cfg)
        view_keys.append(key)
        hit = cache.get("view", key)
        if hit is not None:
            collected[i] = (np.asarray(hit["points"], np.float32),
                            np.asarray(hit["colors"], np.uint8))
        else:
            missing.append((i, src))
    report.views_cached = len(collected)

    if missing:
        miss_sources = [s for _, s in missing]
        scanner = _build_scanner(miss_sources, calib, cfg)
        view_dir = None
        if cfg.pipeline.write_view_plys:
            view_dir = os.path.join(out_dir, "views")
            os.makedirs(view_dir, exist_ok=True)

        def collect(j, src, pts, cols):
            i = missing[j][0]
            collected[i] = (pts, cols)
            cache.put("view", view_keys[i], points=pts, colors=cols)

        batch = BatchReport()
        run_args = (miss_sources, calib, cfg, scanner, "batch", view_dir,
                    batch, log)
        kw = dict(clean_steps=steps, collect=collect,
                  write_plys=cfg.pipeline.write_view_plys)
        if cfg.parallel.io_workers > 1 and len(miss_sources) > 1:
            _reconstruct_pipelined(*run_args, **kw)
        else:
            _reconstruct_serial(*run_args, **kw)
        report.failed = batch.failed
        report.overlap = batch.overlap
    report.views_computed = len(collected) - report.views_cached
    if len(collected) < 2:
        raise ValueError(
            f"pipeline: only {len(collected)} views survived reconstruction "
            f"(failed: {[os.path.basename(s) for s, _ in report.failed]})")

    # ---- stage 3: merge-360 (device-resident handoff) -------------------
    order = sorted(collected)
    view_digests = [StageCache.digest_arrays(points=collected[i][0],
                                             colors=collected[i][1])
                    for i in order]
    merge_cfg = config_subtree(cfg, ("merge",)) + json.dumps(
        {"backend": cfg.parallel.backend,
         "force_bf16": cfg.parallel.force_bf16_features,
         "merge_mesh": cfg.parallel.merge_mesh})
    merge_key = cache.key("merge", digests=view_digests,
                          config_json=merge_cfg)
    hit = cache.get("merge", merge_key)
    merged_path = os.path.join(out_dir, merged_name)
    if hit is not None:
        points = np.asarray(hit["points"], np.float32)
        colors = np.asarray(hit["colors"], np.uint8)
        transforms = [t for t in np.asarray(hit["transforms"])]
        report.merge_status = "cache-hit"
    else:
        clouds = [collected[i] for i in order]
        mesh_grid = None
        if cfg.parallel.merge_mesh:
            from structured_light_for_3d_model_replication_tpu.parallel import (
                mesh as meshlib,
            )

            mesh_grid = meshlib.merge_mesh(cfg.parallel)
        fb16 = True if cfg.parallel.force_bf16_features else None
        with prof.trace():
            if cfg.merge.method == "posegraph":
                points, colors, transforms = recon.merge_360_posegraph(
                    clouds, cfg.merge, log=log, mesh=mesh_grid,
                    feat_bf16=fb16)
            else:
                # DeviceClouds: the per-view clean -> merge handoff stays
                # in accelerator memory (one compact upload on a host
                # executor; zero re-upload when the views are resident)
                dcv = recon.stack_views_device(clouds)
                points, colors, transforms = recon.merge_360(
                    dcv, cfg.merge, log=log, mesh=mesh_grid, feat_bf16=fb16)
        points = np.asarray(points, np.float32)
        colors = np.asarray(colors, np.uint8)
        cache.put("merge", merge_key, points=points, colors=colors,
                  transforms=np.stack([np.asarray(t) for t in transforms]))
        report.merge_status = "computed"
    ply.write_ply(merged_path, points, colors,
                  binary=not cfg.pipeline.ascii_output)
    log(f"[pipeline] merged cloud -> {merged_path} ({len(points):,} points)")
    report.merged_ply = merged_path
    report.merged_points = len(points)

    # ---- stage 4: mesh -> STL ------------------------------------------
    merged_digest = StageCache.digest_arrays(points=points)
    mesh_key = cache.key("mesh", digests=[merged_digest],
                         config_json=config_subtree(cfg, ("mesh",)))
    hit = cache.get("mesh", mesh_key)
    if hit is not None:
        verts = np.asarray(hit["verts"], np.float32)
        faces = np.asarray(hit["faces"], np.int32)
        report.mesh_status = "cache-hit"
    else:
        verts, faces, _ = _mesh_arrays(points, cfg, log=log)
        cache.put("mesh", mesh_key, verts=verts, faces=faces)
        report.mesh_status = "computed"
    stl_path = os.path.join(out_dir, stl_name)
    _write_mesh(stl_path, verts, faces, log=log)
    report.stl_path = stl_path

    report.cache = cache.stats()
    report.elapsed_s = time.monotonic() - t_start
    log(f"[pipeline] {report.summary}")
    return report


def write_patterns(out_dir: str, cfg: Config | None = None, log=print) -> list[str]:
    """Persist the projector pattern stack as numbered images — the offline
    equivalent of generate_patterns (server/sl_system.py:44-86)."""
    cfg = cfg or Config()
    p = cfg.projector
    frames = gc.generate_pattern_stack(p.width, p.height,
                                       brightness=p.brightness,
                                       downsample=p.downsample)
    paths = imio.save_stack(out_dir, frames)
    log(f"[patterns] {len(paths)} frames -> {out_dir}")
    return paths
