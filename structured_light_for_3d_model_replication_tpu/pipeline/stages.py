"""File-level pipeline stages: the artifact-per-stage contract.

Every stage reads its input artifact from disk and persists its output, so any
stage can be re-entered from files alone — the checkpoint/resume model the
reference uses throughout (images -> calib.mat -> per-view .ply -> cleaned .ply
-> merged .ply -> .stl; server/gui.py tabs 2-7 are pure functions of files).
These functions are the single implementation shared by the CLI, tests, and any
future GUI front-end.

Capability parity: process_multi_ply (server/processing.py:251-334), the tab-3
cleanup chain (server/gui.py:1391-1522), merge_pro_360 (processing.py:489-629),
mesh_360/reconstruct_stl (processing.py:632-860).
"""
from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import atomic
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile, ply
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import profiling as prof
from structured_light_for_3d_model_replication_tpu.utils import telemetry as tel

__all__ = [
    "BatchReport", "PipelineReport", "reconstruct_source", "reconstruct",
    "clean_cloud", "clean_batch", "merge_views", "mesh_cloud",
    "run_pipeline", "sort_ply_paths_by_angle", "write_patterns",
]

_DEG_RE = re.compile(r"(\d+(?:\.\d+)?)\s*deg", re.IGNORECASE)


@dataclass
class BatchReport:
    """Per-item success/failure accounting (processing.py:314-334 semantics).

    ``overlap``: filled by the pipelined batch executor with the
    load/compute/write overlap accounting (``OverlapStats.as_dict()``);
    None on the serial path. Not part of the per-item contract — outputs,
    failed, and the summary counts are identical across executors.

    ``failures`` carries the structured :class:`~.utils.faults.FailureRecord`
    twin of every ``failed`` tuple (stage, attempt count, transient-vs-
    permanent classification — the quarantine/manifest payload); ``retries``
    counts transient-fault retries that ultimately succeeded.
    """

    outputs: list[str] = field(default_factory=list)
    failed: list[tuple[str, str]] = field(default_factory=list)
    failures: list[faults.FailureRecord] = field(default_factory=list)
    retries: int = 0
    elapsed_s: float = 0.0
    overlap: dict | None = None
    # execution-regime stamps so batched-vs-serial lines are legible when
    # reports land in bench records / logs: host CPU count always; attached
    # device count only on the jax lanes (never probed on the numpy
    # backend, which must not initialize a jax backend)
    host_cpus: int | None = None
    device_count: int | None = None
    # flight-recorder correlation id: the same run_id stamps this report,
    # the trace.jsonl/metrics.json artifacts, failures.json, and bench
    # lines, so any record can be joined back to its journal
    run_id: str | None = None

    @property
    def summary(self) -> str:
        total = len(self.outputs) + len(self.failed)
        retr = f", {self.retries} retried" if self.retries else ""
        return (f"{len(self.outputs)}/{total} succeeded in "
                f"{self.elapsed_s:.1f}s{retr}")


def sort_ply_paths_by_angle(paths: list[str]) -> list[str]:
    """Order merge inputs by the ``"<n>deg"`` tag in the filename, falling back
    to lexical order for untagged files (server/processing.py:499-519)."""

    def key(p):
        m = _DEG_RE.search(os.path.basename(p))
        return (0, float(m.group(1)), p) if m else (1, 0.0, p)

    return sorted(paths, key=key)


def _scan_sources(target: str, mode: str, need: int, log=None) -> list[str]:
    """Resolve `target` to a list of scan-folder sources per the reference's
    single/batch/files modes (processing.py:300-322). Batch mode logs every
    folder it skips (too few frames / no frames) so a partial capture is
    diagnosable instead of silently shrinking the batch."""
    if mode == "single":
        return [target]
    if mode == "batch":
        subs = sorted(
            os.path.join(target, d) for d in os.listdir(target)
            if os.path.isdir(os.path.join(target, d))
        )
        out = []
        for s in subs:
            try:
                # header-only for packed containers (frames.slbp) — planning
                # never pays an unpack
                n = imio.count_frames(s)
            except (FileNotFoundError, NotADirectoryError, IOError):
                if log is not None:
                    log(f"[reconstruct] skipping {s}: no frame images found")
                continue
            if n >= need:
                out.append(s)
            elif log is not None:
                log(f"[reconstruct] skipping {s}: {n} frames < {need} "
                    f"required (partial capture?)")
        return out
    if mode == "files":
        return [p.strip() for p in target.split(",") if p.strip()]
    raise ValueError(f"unknown reconstruct mode {mode!r} (single|batch|files)")


def _compute_cloud(frames, texture, calib: dict, cfg: Config, scanner=None,
                   async_dispatch: bool = False):
    """Decode + triangulate one loaded stack -> CloudResult (possibly lazy).

    Backend-switched exactly like ``reconstruct_source``; the split from the
    disk load is what lets the pipelined executor prefetch stacks on a
    thread pool while this runs. ``async_dispatch`` routes the scanner path
    through ``forward_async`` (explicit device_put, no host sync) — same
    program, same numbers, only the wait point moves to the caller's drain.
    On the jax paths the returned CloudResult is in flight either way (JAX
    async dispatch); the sync happens at ``tri.compact_cloud``.
    """
    dcfg, tcfg = cfg.decode, cfg.triangulate
    ds = cfg.projector.downsample  # must match the capture-time D_SAMPLE_PROJ
    if cfg.parallel.backend == "numpy":
        dec = gc.decode_stack_np(
            frames, texture, n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate_np(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval,
        )
    elif scanner is not None and not tcfg.bitexact:
        # a bitexact config must take the branch below (device decode +
        # host-NumPy triangulation) no matter what the caller passed
        fwd = scanner.forward_async if async_dispatch else scanner.forward
        cloud = fwd(frames, thresh_mode=dcfg.thresh_mode,
                    shadow_val=dcfg.shadow_val,
                    contrast_val=dcfg.contrast_val)
    else:
        import jax.numpy as jnp

        dec = gc.decode_stack(
            jnp.asarray(frames), jnp.asarray(texture),
            n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval, bitexact=tcfg.bitexact,
        )
    return cloud


def reconstruct_source(source, calib: dict, cfg: Config, scanner=None):
    """One scan source (folder or file list) -> (points, colors) compact arrays.

    Backend-switched: ``cfg.parallel.backend == 'numpy'`` runs the bit-exact
    CPU path; ``'jax'`` runs the fused TPU program (SLScanner when provided,
    else the module-level jit kernels).
    """
    frames, texture = imio.load_stack(source,
                                      io_workers=cfg.parallel.io_workers)
    return tri.compact_cloud(_compute_cloud(frames, texture, calib, cfg,
                                            scanner))


def _item_name(src) -> str:
    return os.path.basename(os.path.normpath(src)) or "cloud"


def _retry_policy(cfg: Config) -> faults.RetryPolicy:
    """The per-view transient-retry budget, from ``pipeline.*`` config."""
    return faults.RetryPolicy(
        max_retries=cfg.pipeline.max_retries,
        backoff_base_s=cfg.pipeline.retry_backoff_s,
        backoff_max_s=cfg.pipeline.retry_backoff_max_s,
        jitter=cfg.pipeline.retry_jitter)


def _retry_stage(stage: str, fn, policy: faults.RetryPolicy, on_retry=None):
    """``faults.retry_call`` with the failing stage annotated onto the final
    exception, so FailureRecords built downstream name the right lane."""
    try:
        return faults.retry_call(fn, policy, on_retry=on_retry)
    except faults.InjectedCrash:
        raise
    except Exception as e:
        faults.annotate(e, stage=stage)
        raise


def _lane_budget_s(cfg: Config, lane: str) -> float | None:
    """The bounded-wait budget for one lane's per-item future, or None
    (plain blocking wait) when the deadline layer is disabled or the lane
    budget is 0 — the one flag check of the disabled path."""
    dcfg = cfg.deadlines
    if not dcfg.enabled:
        return None
    budget = getattr(dcfg, f"{lane}_s", 0.0)
    if budget <= 0:
        return None
    ctx = dl.current()
    if ctx is not None and ctx.run_deadline is not None:
        # never wait past the overall run budget either
        budget = min(budget, max(0.05, ctx.run_deadline.remaining()))
    return budget


def _lane_wait(fut, cfg: Config, lane: str, what: str):
    """Bounded ``Future.result`` for one lane item: a stalled worker
    thread costs its item a DeadlineExceeded (annotated with the lane so
    the FailureRecord names it) instead of hanging the run."""
    try:
        return dl.wait_future(fut, _lane_budget_s(cfg, lane), what=what)
    except dl.DeadlineExceeded as e:
        faults.annotate(e, stage=lane)
        raise


def _budget_check(what: str) -> None:
    """Overall ``pipeline.run_budget_s`` check (the ABORT path), called at
    stage boundaries and executor scheduling steps. One None check when
    no run context / budget is armed."""
    ctx = dl.current()
    if ctx is not None:
        ctx.check_run_budget(what)


def _load_fired(src, cfg: Config):
    """Frame-stack load behind the ``frame.load`` injection site."""
    # work-STARTED heartbeat (completion beats come from OverlapStats.add):
    # the watchdog distinguishes "a long load is in progress" from "nothing
    # has moved at all" by the entry beat
    dl.beat("load")
    faults.fire("frame.load", item=src)
    if imio.is_packed_source(src):
        # a packed source's unpack IS the codec step on this lane
        dl.beat("load")
        faults.fire("frame.pack", item=src)
    return imio.load_stack(src, io_workers=cfg.parallel.io_workers)


def _load_packed_fired(src, cfg: Config):
    """Packed-ingest load: a packed source loads its container directly; a
    raw source is packed at load time (the capture-side codec step) behind
    the ``frame.pack`` injection site. Either way the prefetch thread hands
    the executor a PackedStack — ~8x fewer bytes on the h2d stream."""
    dl.beat("load")
    faults.fire("frame.load", item=src)
    if imio.is_packed_source(src):
        dl.beat("load")
        faults.fire("frame.pack", item=src)
        return imio.load_packed_stack(src)
    frames, texture = imio.load_stack(src, io_workers=cfg.parallel.io_workers)
    dl.beat("load")
    faults.fire("frame.pack", item=src)
    return imio.pack_stack(frames, texture=texture)


def _compute_fired(frames, texture, calib, cfg, scanner, src,
                   async_dispatch=False):
    """Per-view decode+triangulate behind the ``compute.view`` site."""
    dl.beat("compute")
    faults.fire("compute.view", item=src)
    return _compute_cloud(frames, texture, calib, cfg, scanner,
                          async_dispatch=async_dispatch)


def _record_failure(report: BatchReport, src, name: str, exc: BaseException,
                    log, default_stage: str = "compute",
                    stats: "prof.OverlapStats | None" = None) -> None:
    """One per-item failure -> log line + legacy tuple + structured record."""
    rec = faults.FailureRecord.from_exception(default_stage, name, exc)
    log(f"[reconstruct] {name} FAILED ({rec.stage}, attempt "
        f"{rec.attempts}): {exc}")
    report.failed.append((src, str(exc)))
    report.failures.append(rec)
    tr = tel.current()
    if tr is not None:
        tr.instant("failure.record", view=name, stage=rec.stage,
                   error=rec.error_type, attempts=rec.attempts,
                   transient=rec.transient)
    if stats is not None:
        stats.add_failure(rec.stage if rec.stage in prof.OverlapStats._STAGES
                          else default_stage)


def _out_path_for(src, mode: str, output: str | None) -> str:
    """Output-path contract shared by both executors (identical artifacts)."""
    if mode == "single" and output:
        return output
    if output:
        return os.path.join(output, f"{_item_name(src)}.ply")
    return os.path.normpath(src) + ".ply"


def _reconstruct_serial(sources, calib, cfg, scanner, mode, output, report,
                        log, clean_steps=None, collect=None,
                        write_plys=True) -> None:
    """The reference-shaped per-view loop: load, compute, write, one view at
    a time. Kept as the ``parallel.io_workers <= 1`` arm and the semantics
    twin the pipelined executor is verified against.

    ``clean_steps``/``collect``/``write_plys``: the fused-pipeline hooks,
    identical contract to the pipelined executor — an optional masked clean
    chain after compute, an in-memory per-view sink ``collect(idx, src,
    pts, cols)``, and PLY emission demoted to an optional side output.

    Resilience: load and compute each run under the ``pipeline.max_retries``
    transient-retry budget (exponential backoff); an exhausted or permanent
    failure quarantines the view as a :class:`FailureRecord` and the batch
    continues — the per-item tolerance (processing.py:323-330), now
    structured."""
    timer = prof.StageTimer()
    policy = _retry_policy(cfg)
    for idx, src in enumerate(sources):
        _budget_check("reconstruct")
        name = _item_name(src)

        def on_retry(n, e, _name=name):
            report.retries += 1
            log(f"[reconstruct] {_name}: transient {type(e).__name__} "
                f"({e}); retry {n}/{policy.max_retries} after "
                f"{policy.delay_s(n):.2f}s backoff")

        try:
            with timer.stage(name), prof.trace():
                frames, texture = _retry_stage(
                    "load", lambda: _load_fired(src, cfg), policy, on_retry)
                pts, cols = _retry_stage(
                    "compute",
                    lambda: tri.compact_cloud(
                        _compute_fired(frames, texture, calib, cfg, scanner,
                                       src)),
                    policy, on_retry)
                if clean_steps is not None:
                    pts, cols, _ = _clean_arrays(pts, cols, cfg, clean_steps)
            if write_plys:
                out_path = _out_path_for(src, mode, output)
                _retry_stage("write",
                             lambda: ply.write_ply(out_path, pts, cols),
                             policy, on_retry)
            else:
                out_path = name
            if collect is not None:
                collect(idx, src, pts, cols)
            log(f"[reconstruct] {name}: {len(pts):,} points -> "
                f"{out_path if write_plys else 'in-memory handoff'}")
            report.outputs.append(out_path)
        except Exception as e:  # per-item tolerance (processing.py:323-330)
            from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
                is_backend_init_error,
            )

            if is_backend_init_error(e):
                # process-level condition, not an item failure: propagate
                # so the CLI's CPU-fallback retry can handle it (otherwise
                # every item "fails" identically and no retry fires)
                raise
            _record_failure(report, src, name, e, log)
    prof.get_logger().debug("reconstruct stage timing:\n%s", timer.report())


def _reconstruct_pipelined(sources, calib, cfg, scanner, mode, output, report,
                           log, clean_steps=None, collect=None,
                           write_plys=True, stats=None) -> None:
    """Pipelined batch executor: three (or four) overlapped stages per view.

      load     — frame stacks prefetched on an ``io_workers`` thread pool,
                 at most ``prefetch_depth`` stacks in flight (backpressure:
                 each 46x1080p stack is ~95 MB of host RAM)
      compute  — the main thread dispatches view N+1's transfer+decode+
                 triangulate while view N is still in flight (JAX async
                 dispatch; the numpy backend computes inline instead)
      clean    — (``clean_steps`` given — the fused pipeline) the drain
                 worker runs the masked clean chain on view N while the
                 main thread is already reconstructing view N+1; wall time
                 lands in the OverlapStats ``clean`` lane
      write    — a drain worker pays the device sync (``compact_cloud``)
                 and hands the compacted arrays to ``ply.WritebackQueue``,
                 so PLY encoding/disk never blocks the next dispatch.
                 ``write_plys=False`` (the fused pipeline) skips the PLY
                 side output entirely; ``collect(idx, src, pts, cols)``
                 then receives each view's cleaned compact cloud in the
                 drain thread.

    Per-item results are assembled strictly in source order at the end, so
    outputs/failed/summary are identical to ``_reconstruct_serial`` — only
    the schedule differs. Backend-init errors propagate (the CPU-fallback
    retry contract), whether they fire at dispatch or first surface at the
    drain sync.
    """
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        is_backend_init_error,
    )

    # `stats` may be shared with the caller (run_pipeline's streaming merge
    # adds its register lane to the same object so overlap reads as one
    # schedule); standalone runs own a private one
    stats = stats if stats is not None else prof.OverlapStats()
    policy = _retry_policy(cfg)
    depth = max(1, cfg.parallel.prefetch_depth)
    workers = cfg.parallel.io_workers

    def lane_retry(lane):
        def on_retry(n, e):
            stats.add_retry(lane)
            log(f"[reconstruct] transient {type(e).__name__} in {lane} "
                f"lane ({e}); retry {n}/{policy.max_retries}")
        return on_retry

    # idx -> ("fail", src, exc) | ("done", drain_future); assembled in order
    results: dict[int, tuple] = {}
    load_pool = ThreadPoolExecutor(max_workers=workers,
                                   thread_name_prefix="sl3d-prefetch")
    drain_pool = ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="sl3d-drain")
    wbq = ply.WritebackQueue(
        on_write=lambda path, dt: stats.add("write", dt,
                                            view=os.path.basename(path)),
        retry=policy,
        on_retry=lambda _path, n, e: lane_retry("write")(n, e))

    def load_one(src):
        t0 = time.perf_counter()
        out = _retry_stage("load", lambda: _load_fired(src, cfg), policy,
                           lane_retry("load"))
        stats.add("load", time.perf_counter() - t0, view=_item_name(src))
        return out

    def drain_one(idx, src, cloud, out_path):
        # the device sync lives HERE, off the dispatch thread: compaction's
        # np.asarray blocks until the view's program retires
        t0 = time.perf_counter()
        pts, cols = tri.compact_cloud(cloud)
        stats.add("compute", time.perf_counter() - t0, items=1,
                  view=_item_name(src))
        if clean_steps is not None:
            t0 = time.perf_counter()
            pts, cols, _ = _clean_arrays(pts, cols, cfg, clean_steps)
            stats.add("clean", time.perf_counter() - t0,
                      view=_item_name(src))
        wfut = wbq.submit(out_path, pts, cols) if write_plys else None
        if collect is not None:
            collect(idx, src, pts, cols)
        return out_path, len(pts), wfut

    t_wall = time.perf_counter()
    try:
        with prof.trace():
            inflight: deque = deque()
            undrained: deque = deque()
            pending = list(enumerate(sources))
            for idx, src in pending[:depth]:
                inflight.append((idx, src, load_pool.submit(load_one, src)))
            next_i = len(inflight)
            while inflight:
                _budget_check("reconstruct")
                idx, src, lfut = inflight.popleft()
                stats.sample_queue(len(inflight))
                if next_i < len(pending):       # keep the prefetch bound full
                    j, s = pending[next_i]
                    inflight.append((j, s, load_pool.submit(load_one, s)))
                    next_i += 1
                try:
                    frames, texture = _lane_wait(
                        lfut, cfg, "load", f"load of {_item_name(src)}")
                except dl.DeadlineExceeded as e:
                    # stalled prefetch: abandon THIS view (quarantine
                    # downstream), keep the batch moving
                    results[idx] = ("fail", src, e)
                    continue
                except Exception as e:
                    results[idx] = ("fail", src, e)
                    continue
                # backpressure on the compute->drain side too: at most
                # depth+1 dispatched-but-undrained clouds live at once
                # (each holds a full uncompacted H*W result on host or in
                # HBM), so batch size never multiplies peak memory. The
                # settle wait blocks without raising — per-item errors
                # stay with the in-order drain below, and a stalled drain
                # stops costing here after its compute budget (the drain
                # phase then charges the item itself).
                while len(undrained) > depth:
                    dl.wait_settled(undrained.popleft(),
                                    _lane_budget_s(cfg, "compute"))
                try:
                    t0 = time.perf_counter()
                    cloud = _retry_stage(
                        "compute",
                        lambda: _compute_fired(frames, texture, calib, cfg,
                                               scanner, src,
                                               async_dispatch=True),
                        policy, lane_retry("compute"))
                    stats.add("compute", time.perf_counter() - t0,
                              view=_item_name(src))
                except Exception as e:
                    if is_backend_init_error(e):
                        raise
                    results[idx] = ("fail", src, e)
                    continue
                out_path = (_out_path_for(src, mode, output) if write_plys
                            else _item_name(src))
                dfut = drain_pool.submit(drain_one, idx, src, cloud,
                                         out_path)
                undrained.append(dfut)
                results[idx] = ("done", dfut)

            # ---- in-order drain: the single sync point of the pipeline ----
            for idx, src in pending:
                name = _item_name(src)
                kind, *rest = results[idx]
                err: BaseException
                if kind == "done":
                    try:
                        out_path, n_pts, wfut = _lane_wait(
                            rest[0], cfg, "compute", f"drain of {name}")
                        if wfut is not None:
                            try:
                                # surface write errors, bounded: a
                                # stalled writer costs this view, not
                                # the run
                                _lane_wait(wfut, cfg, "write",
                                           f"write of {name}")
                            except faults.InjectedCrash:
                                raise
                            except Exception as e:
                                faults.annotate(e, stage="write")
                                raise
                        log(f"[reconstruct] {name}: {n_pts:,} points -> "
                            f"{out_path if wfut is not None else 'in-memory handoff'}")
                        report.outputs.append(out_path)
                        continue
                    except Exception as e:
                        # a backend-init failure can first surface at the
                        # drain sync — still a process-level condition
                        if is_backend_init_error(e):
                            raise
                        err = e
                else:
                    err = rest[-1]
                _record_failure(report, src, name, err, log, stats=stats)
    finally:
        load_pool.shutdown(wait=False, cancel_futures=True)
        drain_pool.shutdown(wait=False, cancel_futures=True)
        wbq.close(wait=True,
                  timeout_s=_lane_budget_s(cfg, "drain"))
    stats.finish(time.perf_counter() - t_wall)
    report.overlap = stats.as_dict()
    report.retries += report.overlap.get("retry_total", 0)
    prof.get_logger().debug("reconstruct pipeline overlap: %s",
                            stats.summary())


def _view_bucket(count: int, batch: int, n_dev: int = 1) -> int:
    """Bucket size for one view batch: full batches run at ``batch`` slots;
    a ragged tail lands on the next power of two >= its count (capped at
    ``batch``), so at most log2(batch)+1 programs compile per (shape,
    config) — the clean_chain bucket idiom on the view axis. When sharding,
    the bucket rounds up to a multiple of the device count so the leading
    axis splits evenly."""
    if count >= batch:
        b = batch
    else:
        b = 1
        while b < count:
            b *= 2
        b = min(b, batch)
    if n_dev > 1:
        b = -(-b // n_dev) * n_dev
    return b


def _reconstruct_batched(sources, calib, cfg, scanner, mode, output, report,
                         log, clean_steps=None, collect=None,
                         write_plys=True, stats=None) -> None:
    """View-batched executor: the default compute lane when a device scanner
    is available and ``parallel.compute_batch > 1``. Same overlapped stages
    as ``_reconstruct_pipelined``, but the compute stage dispatches
    ``compute_batch`` views as ONE jitted ``forward_views`` program:

      load      — frame stacks prefetched on the ``io_workers`` pool
                  (window: compute_batch + prefetch_depth stacks), then
                  accumulated into bucket-padded batches (``_view_bucket``
                  ladder — a ragged tail pads to a smaller bucket, padding
                  repeats the last view and is sliced off on device)
      transfer  — each assembled bucket is ``device_put`` as one [V,F,H,W]
                  upload (sharded placement when a mesh is active) while
                  the PREVIOUS batch is still computing/draining — the
                  double buffer: at most 2 dispatched-but-undrained batches
                  exist, so transfer k+1 overlaps compute k
      compute   — ``SLScanner.forward_views_batched``: one donated device
                  launch per bucket; with >1 device and
                  ``parallel.shard_views`` the view axis shards across the
                  full mesh (shard_map, the register_pairs_sharded
                  mechanism)
      clean/write — per VIEW, unchanged: the drain worker syncs the batch,
                  splits it back into per-view compact clouds, and runs the
                  same clean/writeback/collect hooks as the per-view lanes

    Per-view semantics are preserved end to end. Fault containment: the
    ``compute.view`` injection site fires per view at batch-assembly time,
    and ANY batch-level failure (injected or real — a poisoned input, a
    compile error) re-runs that batch's views individually through the
    per-view retry/quarantine lane, so one poisoned view can never
    quarantine its batchmates. Outputs are byte-identical to the per-view
    loop (the batched program lax.map's the same per-view math), which
    remains the ``compute_batch <= 1`` arm and the numpy/bitexact fallback.
    """
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from structured_light_for_3d_model_replication_tpu.parallel import (
        mesh as meshlib,
    )
    from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
        is_backend_init_error,
    )

    stats = stats if stats is not None else prof.OverlapStats()
    policy = _retry_policy(cfg)
    batch_n = max(1, cfg.parallel.compute_batch)
    workers = max(1, cfg.parallel.io_workers)
    depth = batch_n + max(1, cfg.parallel.prefetch_depth)
    dcfg = cfg.decode
    fwd_kw = dict(thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
                  contrast_val=dcfg.contrast_val)
    # HBM-resident fastpath (pipeline.fused_clean): the drain compacts +
    # cleans the whole batch on device and syncs ONCE; any failure inside
    # degrades to the per-view lane exactly like a poisoned batch
    use_fused = bool(cfg.pipeline.fused_clean)
    # packed ingest (pipeline.packed_ingest): the prefetch threads hand the
    # executor bit-plane PackedStacks (raw sources pack at load, packed
    # sources load their container) and each view's ~8x-smaller buffers
    # stream to the device AS THEY ARRIVE — h2d overlaps the previous
    # bucket's compute instead of serializing at bucket assembly. A
    # schedule/format knob only: decode from packed bits is bit-identical
    # (see ops/graycode.decode_packed_np), and the knob lives outside the
    # stage-cache key material.
    use_packed = bool(cfg.pipeline.packed_ingest)

    mesh = meshlib.views_mesh(cfg.parallel)
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    if mesh is not None:
        log(f"[reconstruct] sharding view batches over {n_dev} devices "
            f"(parallel.shard_views)")

    def lane_retry(lane):
        def on_retry(n, e):
            stats.add_retry(lane)
            log(f"[reconstruct] transient {type(e).__name__} in {lane} "
                f"lane ({e}); retry {n}/{policy.max_retries}")
        return on_retry

    # idx -> ("fail", src, exc) | ("batch", drain_future, j-within-batch)
    results: dict[int, tuple] = {}
    load_pool = ThreadPoolExecutor(max_workers=workers,
                                   thread_name_prefix="sl3d-prefetch")
    drain_pool = ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="sl3d-drain")
    wbq = ply.WritebackQueue(
        on_write=lambda path, dt: stats.add("write", dt,
                                            view=os.path.basename(path)),
        retry=policy,
        on_retry=lambda _path, n, e: lane_retry("write")(n, e))

    def load_one(src):
        t0 = time.perf_counter()
        out = _retry_stage("load", lambda: _load_fired(src, cfg), policy,
                           lane_retry("load"))
        stats.add("load", time.perf_counter() - t0, view=_item_name(src))
        return out

    def load_one_packed(src):
        """Packed-ingest prefetch: load/pack the stack AND stream its
        bit-plane buffers to the device right here on the prefetch thread —
        the h2d of view k+1 overlaps the compute of bucket k (JAX async
        dispatch; single-device lane). The mesh lane defers the upload to
        the sharded bucket put at assembly. Returns (PackedStack, device
        buffers | None) — the executor's (frames, texture) item slots."""
        t0 = time.perf_counter()
        ps = _retry_stage("load", lambda: _load_packed_fired(src, cfg),
                          policy, lane_retry("load"))
        stats.add("load", time.perf_counter() - t0, view=_item_name(src))
        dev = None
        if mesh is None:
            t0 = time.perf_counter()
            dev = (jax.device_put(ps.planes), jax.device_put(ps.white),
                   jax.device_put(ps.black))
            stats.add("transfer", time.perf_counter() - t0,
                      view=_item_name(src))
        # frame h2d at actual WIRE size (the packed bytes), with the raw u8
        # equivalent alongside so report can show the ratio
        stats.add_transfer(frames=int(ps.nbytes),
                           frames_raw=int(np.prod(ps.shape)))
        return ps, dev

    loader = load_one_packed if use_packed else load_one

    def finish_view(idx, src, pts, cols, dev=None, cleaned=False):
        """Clean + write/collect ONE compacted view (drain thread) — the
        per-view tail every executor shares. ``cleaned`` marks a view the
        fused drain already cleaned on device; ``dev`` carries its
        device-resident compact points to the collect hook (the registrar
        preps them without a re-upload)."""
        if clean_steps is not None and not cleaned:
            t0 = time.perf_counter()

            def _clean_fired():
                # the fused-clean injection site fires here too, so a
                # poisoned view re-running through the per-view lane
                # quarantines ALONE (its batchmates pass)
                faults.fire("clean.fused", item=src)
                return _clean_arrays(pts, cols, cfg, clean_steps,
                                     stats=stats)

            pts, cols, _ = _retry_stage("clean", _clean_fired, policy,
                                        lane_retry("clean"))
            stats.add("clean", time.perf_counter() - t0,
                      view=_item_name(src))
        out_path = (_out_path_for(src, mode, output) if write_plys
                    else _item_name(src))
        wfut = wbq.submit(out_path, pts, cols) if write_plys else None
        if collect is not None:
            if dev is not None:
                collect(idx, src, pts, cols, dev=dev)
            else:
                collect(idx, src, pts, cols)
        return ("ok", out_path, len(pts), wfut)

    def run_view_fallback(item):
        """The per-view lane a poisoned batch degrades to: identical
        retry/quarantine semantics to the serial/pipelined executors. A
        packed item unpacks here — decode on the binarized stack is
        bit-identical (pack_stack's contract), so quarantine survivors
        match the raw lane byte for byte."""
        idx, src, frames, texture = item
        if isinstance(frames, imio.PackedStack):
            frames, texture = imio.unpack_stack(frames)
        try:
            t0 = time.perf_counter()
            cloud = _retry_stage(
                "compute",
                lambda: _compute_fired(frames, texture, calib, cfg, scanner,
                                       src),
                policy, lane_retry("compute"))
            pts, cols = tri.compact_cloud(cloud)
            stats.add("compute", time.perf_counter() - t0, items=1,
                      view=_item_name(src))
            return finish_view(idx, src, pts, cols)
        except Exception as e:
            if is_backend_init_error(e):
                raise
            return ("fail", src, e)

    def drain_batch_fused(items, cloud):
        """HBM-resident drain: compact + clean + final-compact the whole
        batch on device (ops/fused_view) and sync ONCE. The clean.fused
        site fires per item BEFORE any device work, so a poisoned batch
        degrades to the per-view lane, where the site re-fires per view
        and the poisoned view quarantines alone."""
        from structured_light_for_3d_model_replication_tpu.ops import (
            fused_view as fvlib,
        )

        for _idx, src, _f, _t in items:
            faults.fire("clean.fused", item=src)
        t0 = time.perf_counter()
        views, d2h, clean_s = fvlib.fused_clean_views(
            cloud.points, cloud.colors, cloud.valid, cfg.clean, clean_steps)
        wall = time.perf_counter() - t0
        stats.add("compute", max(0.0, wall - clean_s), items=len(items))
        if clean_s:
            stats.add("clean", clean_s)
        stats.add_transfer(d2h=d2h)
        stats.add_kernel("fused_view", wall,
                         bucket=int(cloud.points.shape[1]), bytes_moved=d2h)
        outs = []
        for (idx, src, _frames, _texture), v in zip(items, views):
            outs.append(finish_view(idx, src, v.points, v.colors,
                                    dev=(v.dev_points, v.count),
                                    cleaned=True))
        return outs

    def drain_batch(items, cloud):
        """Sync one batched launch (the device wait lives HERE, off the
        dispatch thread) and fan back out into per-view artifacts; any
        failure re-runs the batch's views individually."""
        try:
            if use_fused:
                return drain_batch_fused(items, cloud)
            t0 = time.perf_counter()
            # one blocking device_get of the batch pytree (not three
            # per-leaf np.asarray syncs)
            pts_v, cols_v, val_v = jax.device_get(
                (cloud.points, cloud.colors, cloud.valid))
            stats.add("compute", time.perf_counter() - t0, items=len(items))
            stats.add_transfer(d2h=int(pts_v.nbytes) + int(cols_v.nbytes)
                               + int(val_v.nbytes))
            outs = []
            for j, (idx, src, _frames, _texture) in enumerate(items):
                # per-view compaction through the SAME export helper the
                # per-view lanes use (incl. gray->RGB replication)
                pts, cols = tri.compact_cloud(
                    tri.CloudResult(pts_v[j], cols_v[j], val_v[j]))
                outs.append(finish_view(idx, src, pts, cols))
            return outs
        except faults.InjectedCrash:
            raise
        except Exception as e:
            if is_backend_init_error(e):
                raise
            if faults.is_transient(e):
                # the batch-level firing (e.g. clean.fused in the fused
                # drain) consumed a transient's budget; the per-view
                # re-run below is its successful retry
                stats.add_retry("clean" if use_fused else "compute")
            log(f"[reconstruct] batched launch of {len(items)} view(s) "
                f"failed ({type(e).__name__}: {e}); re-running views "
                f"individually")
            return [run_view_fallback(it) for it in items]

    def dispatch_batch(items):
        """Main thread: assemble, transfer, and launch one bucket; returns
        the drain future. Never raises per-item errors — a poisoned batch
        degrades to the per-view lane inside the drain worker."""
        poisoned = None
        for _idx, src, _f, _t in items:
            # the per-view injection site fires at assembly time so chaos
            # semantics survive batching; a hit poisons the WHOLE batch into
            # the per-view lane, where retry/quarantine are exact
            try:
                faults.fire("compute.view", item=src)
            except faults.InjectedCrash:
                raise
            except Exception as e:
                poisoned = e
                break
        if poisoned is None:
            try:
                t0 = time.perf_counter()
                v = len(items)
                bucket = _view_bucket(v, batch_n, n_dev)
                if use_packed:
                    import jax.numpy as jnp

                    n_frames = int(items[0][2].n_frames)
                    if mesh is not None:
                        # sharded bucket put of the PACKED host arrays —
                        # still ~8x fewer wire bytes than the raw stack
                        def pad(a):
                            if bucket > v:
                                a = np.concatenate(
                                    [a, np.repeat(a[-1:], bucket - v,
                                                  axis=0)])
                            return jax.device_put(
                                a, meshlib.batch_sharding(mesh))

                        planes_d = pad(np.stack(
                            [it[2].planes for it in items]))
                        white_d = pad(np.stack(
                            [it[2].white for it in items]))
                        black_d = pad(np.stack(
                            [it[2].black for it in items]))
                    else:
                        # per-view buffers already streamed to HBM by the
                        # prefetch threads; bucket assembly is an on-device
                        # stack (padding repeats device buffers — no h2d)
                        devs = [it[3] for it in items]
                        devs = devs + [devs[-1]] * (bucket - v)
                        planes_d = jnp.stack([d[0] for d in devs])
                        white_d = jnp.stack([d[1] for d in devs])
                        black_d = jnp.stack([d[2] for d in devs])
                    stats.add("transfer", time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    cloud = scanner.forward_views_packed(
                        planes_d, white_d, black_d, n_frames=n_frames,
                        mesh=mesh, **fwd_kw)
                else:
                    fv = np.stack([f for _, _, f, _ in items])
                    if bucket > v:
                        fv = np.concatenate(
                            [fv, np.repeat(fv[-1:], bucket - v, axis=0)])
                    if mesh is not None:
                        fv_d = jax.device_put(fv,
                                              meshlib.batch_sharding(mesh))
                    else:
                        fv_d = jax.device_put(fv)
                    stats.add("transfer", time.perf_counter() - t0)
                    stats.add_transfer(frames=int(fv.nbytes))
                    t0 = time.perf_counter()
                    cloud = scanner.forward_views_batched(fv_d, mesh=mesh,
                                                          **fwd_kw)
                stats.add_launch(v, bucket, time.perf_counter() - t0)
                cloud = tri.CloudResult(cloud.points[:v], cloud.colors[:v],
                                        cloud.valid[:v])
                return drain_pool.submit(drain_batch, items, cloud)
            except faults.InjectedCrash:
                raise
            except Exception as e:
                if is_backend_init_error(e):
                    raise
                poisoned = e
        if faults.is_transient(poisoned):
            # the assembly-time firing consumed a transient's budget; the
            # per-view re-run below is its successful retry
            stats.add_retry("compute")
        log(f"[reconstruct] batch of {len(items)} view(s) degraded to "
            f"per-view compute ({type(poisoned).__name__}: {poisoned})")
        return drain_pool.submit(
            lambda its=list(items): [run_view_fallback(it) for it in its])

    t_wall = time.perf_counter()
    try:
        with prof.trace():
            inflight: deque = deque()
            pending = list(enumerate(sources))
            next_i = 0
            while next_i < len(pending) and len(inflight) < depth:
                idx, src = pending[next_i]
                inflight.append((idx, src, load_pool.submit(loader, src)))
                next_i += 1

            batch_items: list[tuple] = []
            batch_futs: deque = deque()

            def flush():
                if not batch_items:
                    return
                # double buffer: at most 2 dispatched-but-undrained batches
                # (each holds bucket x stack on device + its results), so
                # batch size bounds peak memory instead of multiplying it.
                # The settle wait blocks without raising — per-item errors
                # stay with the in-order assembly below, which charges a
                # stalled batch to its own views.
                while len(batch_futs) >= 2:
                    dl.wait_settled(batch_futs.popleft(),
                                    _lane_budget_s(cfg, "compute"))
                dfut = dispatch_batch(list(batch_items))
                batch_futs.append(dfut)
                for j, (idx, _src, _f, _t) in enumerate(batch_items):
                    results[idx] = ("batch", dfut, j)
                batch_items.clear()

            while inflight:
                _budget_check("reconstruct")
                idx, src, lfut = inflight.popleft()
                stats.sample_queue(len(inflight))
                if next_i < len(pending):     # keep the prefetch window full
                    j, s = pending[next_i]
                    inflight.append((j, s, load_pool.submit(loader, s)))
                    next_i += 1
                try:
                    frames, texture = _lane_wait(
                        lfut, cfg, "load", f"load of {_item_name(src)}")
                except faults.InjectedCrash:
                    raise
                except Exception as e:
                    results[idx] = ("fail", src, e)
                    continue
                if batch_items and frames.shape != batch_items[0][2].shape:
                    flush()       # heterogeneous stacks cannot share a batch
                batch_items.append((idx, src, frames, texture))
                if len(batch_items) >= batch_n:
                    flush()
            flush()               # ragged tail -> smaller bucket

            # ---- in-order assembly: identical report to the other lanes --
            for idx, src in pending:
                name = _item_name(src)
                kind, *rest = results[idx]
                err: BaseException
                if kind == "batch":
                    dfut, j = rest
                    try:
                        out = _lane_wait(dfut, cfg, "compute",
                                         f"batch drain of {name}")[j]
                    except Exception as e:
                        if is_backend_init_error(e):
                            raise
                        err = e
                    else:
                        if out[0] == "ok":
                            _, out_path, n_pts, wfut = out
                            try:
                                if wfut is not None:
                                    try:
                                        _lane_wait(wfut, cfg, "write",
                                                   f"write of {name}")
                                    except faults.InjectedCrash:
                                        raise
                                    except Exception as e:
                                        faults.annotate(e, stage="write")
                                        raise
                                log(f"[reconstruct] {name}: {n_pts:,} "
                                    f"points -> "
                                    f"{out_path if wfut is not None else 'in-memory handoff'}")
                                report.outputs.append(out_path)
                                continue
                            except Exception as e:
                                if is_backend_init_error(e):
                                    raise
                                err = e
                        else:
                            err = out[2]
                else:
                    err = rest[-1]
                _record_failure(report, src, name, err, log, stats=stats)
    finally:
        load_pool.shutdown(wait=False, cancel_futures=True)
        drain_pool.shutdown(wait=False, cancel_futures=True)
        wbq.close(wait=True,
                  timeout_s=_lane_budget_s(cfg, "drain"))
    stats.finish(time.perf_counter() - t_wall)
    report.overlap = stats.as_dict()
    report.overlap["compute_batch"] = batch_n
    report.overlap["shard_devices"] = n_dev
    report.retries += report.overlap.get("retry_total", 0)
    prof.get_logger().debug("reconstruct batched overlap: %s",
                            stats.summary())


def _use_batched(cfg: Config, scanner, n_sources: int) -> bool:
    """One predicate for both call sites (reconstruct, run_pipeline): the
    view-batched lane needs a device scanner (numpy backend and bitexact
    export triangulate per view on host), >1 view, and compute_batch > 1."""
    return (scanner is not None and cfg.parallel.compute_batch > 1
            and n_sources > 1)


def _build_scanner(sources, calib, cfg: Config):
    """SLScanner for the fused device program, or None for the NumPy /
    bitexact paths (which triangulate through the host twin). Shared by
    ``reconstruct`` and ``run_pipeline``."""
    # bitexact export triangulates through the NumPy twin in
    # reconstruct_source, never the scanner's fused program
    if cfg.parallel.backend == "numpy" or cfg.triangulate.bitexact:
        return None
    from structured_light_for_3d_model_replication_tpu.models.scanner import (
        SLScanner,
    )

    first = imio.list_frame_files(sources[0])
    hdr = imio.probe_packed(first[0])
    if hdr is not None:      # packed container: geometry from the header
        cam_size = (int(hdr["width"]), int(hdr["height"]))
    else:
        probe = imio.load_gray(first[0])
        cam_size = (probe.shape[1], probe.shape[0])
    return SLScanner(
        calib, cam_size,
        proj_size=(cfg.decode.n_cols, cfg.decode.n_rows),
        row_mode=cfg.triangulate.row_mode,
        epipolar_tol=cfg.triangulate.epipolar_tol,
        n_sets_col=cfg.decode.n_sets_col, n_sets_row=cfg.decode.n_sets_row,
        downsample=cfg.projector.downsample,
        plane_eval=cfg.triangulate.plane_eval,
    )


def reconstruct(calib_path: str, target: str, mode: str = "single",
                output: str | None = None, cfg: Config | None = None,
                log=print) -> BatchReport:
    """Scan folder(s) -> per-view colored PLY (process_multi_ply parity).

    ``output``: for single mode a .ply path (default: <target>.ply); for
    batch/files a directory (default: alongside each source).

    Multi-view batches with a device scanner run on the VIEW-BATCHED
    executor (``_reconstruct_batched``: bucket-padded ``forward_views``
    launches of ``parallel.compute_batch`` views, sharded across devices
    when >1 is attached); ``compute_batch <= 1``, the numpy backend, and
    bitexact export fall back to the pipelined per-view executor
    (``cfg.parallel.io_workers > 1``) and then the serial loop. Outputs and
    the report are identical across all three — only the schedule differs.
    """
    cfg = cfg or Config()
    calib = matfile.load_calibration(calib_path)
    need = gc.frames_per_view(cfg.decode.n_cols, cfg.decode.n_rows,
                              cfg.projector.downsample)
    sources = _scan_sources(target, mode, need, log=log)
    if not sources:
        raise ValueError(f"no scan sources found under {target!r} (mode={mode})")

    scanner = _build_scanner(sources, calib, cfg)

    report = BatchReport()
    # one id per run: reuse an enclosing flight recorder's (the fused
    # pipeline threads its id through) or mint a fresh one
    _tr = tel.current()
    report.run_id = _tr.run_id if _tr is not None else tel.new_run_id()
    report.host_cpus = os.cpu_count()
    if scanner is not None:
        # the scanner's construction already initialized the jax backend;
        # the numpy lane must never probe devices (it would claim one)
        import jax

        report.device_count = jax.device_count()
    if output and mode != "single":
        os.makedirs(output, exist_ok=True)
    # standalone runs own a deadline context (run_pipeline installs its
    # own and this respects it — nested arming would shadow the watchdog)
    ctx = prev_ctx = None
    dcfg = cfg.deadlines
    if dcfg.enabled and dl.current() is None:
        stall_dir = output if output and os.path.isdir(output) else None
        ctx = dl.RunContext(
            run_deadline=dl.Deadline.after(cfg.pipeline.run_budget_s,
                                           "reconstruct run"))
        if dcfg.hard_stall_s > 0 or dcfg.soft_stall_s > 0:
            ctx.watchdog = dl.Watchdog(
                dcfg.soft_stall_s, dcfg.hard_stall_s, ctx.token,
                poll_s=dcfg.watchdog_poll_s, out_dir=stall_dir,
                run_id=report.run_id, log=log)
        prev_ctx = dl.activate(ctx)
        if ctx.watchdog is not None:
            ctx.watchdog.start()
    t0 = time.monotonic()
    try:
        if _use_batched(cfg, scanner, len(sources)):
            _reconstruct_batched(sources, calib, cfg, scanner, mode, output,
                                 report, log)
        elif cfg.parallel.io_workers > 1 and len(sources) > 1:
            _reconstruct_pipelined(sources, calib, cfg, scanner, mode,
                                   output, report, log)
        else:
            _reconstruct_serial(sources, calib, cfg, scanner, mode, output,
                                report, log)
    finally:
        if ctx is not None:
            ctx.token.cancel("run ended")
            if ctx.watchdog is not None:
                ctx.watchdog.stop()
            dl.deactivate(prev_ctx)
    report.elapsed_s = time.monotonic() - t0
    log(f"[reconstruct] {report.summary}")
    return report


_CLEAN_STEPS = ("background", "cluster", "radius", "statistical")


def _clean_arrays(pts: np.ndarray, cols: np.ndarray, cfg: Config,
                  steps=_CLEAN_STEPS, log=None, step_callback=None,
                  stats=None):
    """Masked-chain cleanup of one in-memory cloud; the single implementation
    behind clean_cloud, the batch clean, and the fused pipeline's clean lane.

    Runs ops/pointcloud.clean_chain: every step narrows a validity mask over
    a _bucket_pad-padded fixed shape — ONE jitted program, one compile per
    (bucket, params) pair across all views and reruns, host compaction only
    once at the end. Returns (pts', cols', counts dict) with the same
    counts/log/abort semantics the file-level chain always had."""
    from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
        _bucket_pad,
    )
    from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc

    log = log or (lambda m: None)
    n = len(pts)
    counts = {"input": n}
    params = pc.chain_params(cfg.clean, tuple(steps))
    if not params:
        return pts, cols, counts
    if cfg.parallel.backend == "numpy":
        masks, cnts = pc.clean_chain_np(pts, np.ones(n, bool), cfg.clean,
                                        tuple(steps))
        masks, cnts = np.asarray(masks), np.asarray(cnts)
    else:
        import jax.numpy as jnp

        bucket = _bucket_pad(n)
        pts_pad = pts
        if bucket > n:
            pts_pad = np.concatenate(
                [pts, np.full((bucket - n, 3), 1e9, np.float32)])
        valid = np.arange(bucket) < n
        masks_d, cnts_d = pc.clean_chain(jnp.asarray(pts_pad),
                                         jnp.asarray(valid), cfg.clean,
                                         tuple(steps))
        masks = np.asarray(masks_d)
        cnts = np.asarray(cnts_d)
        if stats is not None:
            # the host round-trip the fused fastpath eliminates: cloud up,
            # step masks back down
            stats.add_transfer(h2d=int(pts_pad.nbytes) + int(valid.nbytes),
                               d2h=int(masks.nbytes) + int(cnts.nbytes))
        masks = masks[:, :n]
    final = masks[-1] if len(params) else np.ones(n, bool)
    for i, (step, _) in enumerate(params):
        counts[step] = int(cnts[i])
        log(f"[clean] {step}: {int(cnts[i]):,} points remain")
        if step_callback is not None:
            step_callback(step, pts[masks[i]], cols[masks[i]])
        if int(cnts[i]) == 0:
            log("[clean] WARNING: all points removed; aborting chain")
            final = masks[i]
            break
    return pts[final], cols[final], counts


def clean_cloud(input_ply: str, output_ply: str, cfg: Config | None = None,
                steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                log=print, step_callback=None) -> dict:
    """Cleanup chain on one cloud PLY: background plane removal -> largest
    cluster -> radius outlier -> statistical outlier (the tab-3 chain,
    gui.py:1391-1522; ops per processing.py:337-448). Steps are individually
    selectable. ``step_callback(name, points, colors)`` receives each
    intermediate cloud (the tab's in-memory per-step inspection flow, made
    non-blocking). The chain itself is the masked fixed-shape program
    (ops/pointcloud.clean_chain) — this wrapper only adds the PLY boundary."""
    cfg = cfg or Config()
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)
    cols = np.asarray(data.get("colors")) if data.get("colors") is not None \
        else np.zeros_like(pts, dtype=np.uint8)
    pts, cols, counts = _clean_arrays(pts, cols, cfg, tuple(steps), log=log,
                                      step_callback=step_callback)
    ply.write_ply(output_ply, pts, cols)
    log(f"[clean] wrote {output_ply} ({len(pts):,} points)")
    return counts


def clean_batch(input_folder: str, output_folder: str,
                cfg: Config | None = None,
                steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                log=print) -> BatchReport:
    """Clean every PLY in a folder: reads on the shared I/O pool, the masked
    chain per cloud (clouds sharing a _bucket_pad bucket share ONE compile),
    writes on the WritebackQueue — the batch twin of clean_cloud with
    reconstruct's per-item failure tolerance."""
    from concurrent.futures import ThreadPoolExecutor

    cfg = cfg or Config()
    paths = sorted(os.path.join(input_folder, f)
                   for f in os.listdir(input_folder)
                   if f.lower().endswith(".ply"))
    if not paths:
        raise ValueError(f"no .ply files in {input_folder!r}")
    os.makedirs(output_folder, exist_ok=True)
    report = BatchReport()
    t0 = time.monotonic()
    workers = max(1, cfg.parallel.io_workers)
    with ThreadPoolExecutor(max_workers=min(workers, len(paths)),
                            thread_name_prefix="sl3d-cleanread") as pool, \
            ply.WritebackQueue() as wbq:
        reads = [(p, pool.submit(ply.read_ply, p)) for p in paths]
        pend = []
        for src, fut in reads:
            name = os.path.basename(src)
            try:
                data = fut.result()
                pts = np.asarray(data["points"], np.float32)
                cols = (np.asarray(data["colors"])
                        if data.get("colors") is not None
                        else np.zeros_like(pts, dtype=np.uint8))
                pts, cols, counts = _clean_arrays(pts, cols, cfg,
                                                  tuple(steps))
                out_path = os.path.join(output_folder, name)
                pend.append((src, out_path, len(pts),
                             wbq.submit(out_path, pts, cols)))
            except Exception as e:
                log(f"[clean] {name} FAILED: {e}")
                report.failed.append((src, str(e)))
        for src, out_path, n_pts, wfut in pend:
            try:
                wfut.result()
                log(f"[clean] {os.path.basename(src)}: {n_pts:,} points -> "
                    f"{out_path}")
                report.outputs.append(out_path)
            except Exception as e:
                log(f"[clean] {os.path.basename(src)} FAILED: {e}")
                report.failed.append((src, str(e)))
    report.elapsed_s = time.monotonic() - t0
    log(f"[clean] {report.summary}")
    return report


def merge_views(input_folder: str, output_ply: str, cfg: Config | None = None,
                log=print, step_callback=None):
    """Folder of per-view PLYs -> one registered 360-degree cloud
    (merge_pro_360 parity; ``cfg.merge.method`` picks greedy sequential (A18)
    or pose-graph global optimization (Old/360Merge.py:50-78 capability)).
    ``step_callback(i, points, colors)`` mirrors the reference's per-step
    merge preview hook (processing.py:600-603) — e.g. a
    acquire.viewer.StageRecorder.merge_step for the web viewer."""
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )

    cfg = cfg or Config()
    out_abs = os.path.abspath(output_ply)
    paths = sort_ply_paths_by_angle([
        p for f in os.listdir(input_folder)
        if f.lower().endswith(".ply")
        and os.path.abspath(p := os.path.join(input_folder, f)) != out_abs
    ])
    if len(paths) < 2:
        raise ValueError(f"need >= 2 PLY views in {input_folder}, found {len(paths)}")
    log(f"[merge] {len(paths)} views: " + ", ".join(os.path.basename(p) for p in paths))

    # per-view PLY reads on the shared I/O pool (parallel.io_workers; the
    # registration can't start early anyway, so amortize the disk wall);
    # pool.map preserves path order, so the merge chain is unchanged.
    # Per-file tolerance: a corrupt/truncated view (torn write survivor)
    # is dropped with a warning as long as >= max(2, pipeline.min_views)
    # readable views remain — graceful degradation instead of losing the
    # whole merge to one bad file.
    def read_one(p):
        try:
            return ply.read_ply(p), None
        except faults.InjectedCrash:
            raise
        except Exception as e:
            return None, e

    if cfg.parallel.io_workers > 1 and len(paths) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(cfg.parallel.io_workers, len(paths)),
                thread_name_prefix="sl3d-plyread") as pool:
            datas = list(pool.map(read_one, paths))
    else:
        datas = [read_one(p) for p in paths]
    dropped = [(p, e) for p, (d, e) in zip(paths, datas) if d is None]
    for p, e in dropped:
        log(f"[merge] WARNING: dropping unreadable view "
            f"{os.path.basename(p)}: {e}")
    floor = max(2, cfg.pipeline.min_views)
    if len(paths) - len(dropped) < floor:
        raise ValueError(
            f"merge: only {len(paths) - len(dropped)}/{len(paths)} views "
            f"readable, below the pipeline.min_views={floor} floor "
            f"(unreadable: {[os.path.basename(p) for p, _ in dropped]})")
    clouds = []
    for d, _ in datas:
        if d is None:
            continue
        c = d.get("colors")
        if c is None:
            c = np.zeros_like(d["points"], dtype=np.uint8)
        clouds.append((np.asarray(d["points"], np.float32), np.asarray(c, np.uint8)))

    mesh = None
    if cfg.parallel.merge_mesh:
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )

        mesh = meshlib.merge_mesh(cfg.parallel)
        if mesh is not None:
            log(f"[merge] sharding the chain over "
                f"{mesh.devices.size} devices (parallel.merge_mesh)")
    # parallel.force_bf16_features=true FORCES the opt-in bf16 feature
    # matmuls; false (default) leaves the auto policy (f32 everywhere
    # since the r5 on-chip quality sweep; see _resolve_feat_bf16)
    fb16 = True if cfg.parallel.force_bf16_features else None
    with prof.trace():
        if cfg.merge.method == "posegraph":
            points, colors, transforms = recon.merge_360_posegraph(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
        else:
            points, colors, transforms = recon.merge_360(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
    ply.write_ply(output_ply, points, colors)
    log(f"[merge] wrote {output_ply} ({len(points):,} points)")
    return points, colors, transforms


def _mesh_arrays(pts: np.ndarray, cfg: Config, log=print, normals=None):
    """Cloud arrays -> (verts, faces): the meshing stage without the PLY
    boundary — normals estimated+oriented when not supplied. Shared by
    mesh_cloud and the fused pipeline."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models import meshing
    from structured_light_for_3d_model_replication_tpu.ops import normals as nrm

    valid = np.ones(len(pts), bool)
    if normals is None:
        nr = nrm.estimate_normals(jnp.asarray(pts), jnp.asarray(valid),
                                  k=cfg.mesh.normal_max_nn,
                                  radius=cfg.mesh.normal_radius or None)
        nr = nrm.orient_normals(jnp.asarray(pts), nr, jnp.asarray(valid),
                                mode=cfg.mesh.orientation)
        normals = np.asarray(nr)
        log(f"[mesh] estimated normals (k={cfg.mesh.normal_max_nn}, "
            f"{cfg.mesh.orientation} orientation)")
    with prof.trace():
        verts, faces = meshing.reconstruct_mesh(pts, valid, normals,
                                                cfg=cfg.mesh, log=log)
    return np.asarray(verts), np.asarray(faces), normals


def _write_mesh(output_path: str, verts, faces, log=print):
    from structured_light_for_3d_model_replication_tpu.models import meshing

    if output_path.lower().endswith(".stl"):
        meshing.mesh_to_stl(output_path, verts, faces)
    else:
        ply.write_mesh_ply(output_path, verts, faces)
    log(f"[mesh] wrote {output_path} ({len(verts):,} verts, {len(faces):,} faces)")


def mesh_cloud(input_ply: str, output_path: str, cfg: Config | None = None,
               save_normals_path: str | None = None, log=print):
    """Cloud PLY -> mesh (.stl or .ply by extension): reconstruct_stl/mesh_360
    parity including the optional normals debug dump (processing.py:690-693)."""
    cfg = cfg or Config()
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)

    verts, faces, normals = _mesh_arrays(pts, cfg, log=log,
                                         normals=data.get("normals"))
    if save_normals_path:
        ply.write_ply(save_normals_path, pts, data.get("colors"), normals)
        log(f"[mesh] normals debug cloud -> {save_normals_path}")
    _write_mesh(output_path, verts, faces, log=log)
    return verts, faces


@dataclass
class PipelineReport:
    """Accounting for one fused scan-to-print run."""

    # flight-recorder correlation id: stamps this report, failures.json,
    # trace.jsonl/metrics.json, and any bench line built from the run
    run_id: str | None = None
    merged_ply: str | None = None
    stl_path: str | None = None
    views_computed: int = 0
    views_cached: int = 0
    failed: list[tuple[str, str]] = field(default_factory=list)
    failures: list[faults.FailureRecord] = field(default_factory=list)
    retries: int = 0
    degraded: bool = False          # merged with fewer views than captured
    manifest_path: str | None = None  # failure manifest next to the STL
    merge_status: str = ""          # 'computed' | 'cache-hit'
    # which merge arm actually ran: 'streamed' (register lane overlapped
    # with reconstruction) | 'barrier' (monolithic merge_360) | 'posegraph'
    # (always a barrier; merge.stream is ignored with a logged notice).
    # Stamped into the failure manifest and every bench line so records
    # are attributable to an arm.
    merge_mode: str = ""
    mesh_status: str = ""
    merged_points: int = 0
    overlap: dict | None = None     # executor lanes incl. clean + register
    cache: dict | None = None       # StageCache.stats()
    # multiprocess runs only: the coordinator's lease/steal/ledger summary
    # (parallel/coordinator.py attaches it after the assembly pass)
    coordinator: dict | None = None
    # incremental-assembly accounting (merge.incremental pods only):
    # folded_views/used_views/folded_pairs/fold_wall_s + tail_s — the wall
    # from last-item-settled to artifacts-on-disk
    assembly: dict | None = None
    elapsed_s: float = 0.0

    @property
    def summary(self) -> str:
        deg = ""
        if self.degraded:
            parts = []
            if self.failed:
                parts.append(f"{len(self.failed)} view(s) quarantined")
            pair_fails = len(self.failures) - len(self.failed)
            if pair_fails > 0:
                parts.append(f"{pair_fails} pair(s) identity-fallback")
            deg = " DEGRADED (" + ", ".join(parts or ["see manifest"]) + ")"
        return (f"{self.views_computed} views computed + "
                f"{self.views_cached} cached, merge {self.merge_status}, "
                f"mesh {self.mesh_status}, {self.merged_points:,} points "
                f"in {self.elapsed_s:.1f}s{deg}")


def _write_json_atomic(path: str, payload: dict) -> None:
    """Crash-safe JSON artifact write (tmp + fsync + rename)."""
    with atomic.atomic_write(path) as tmp, open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def _quarantine_failures(out_dir: str, failures, log) -> None:
    """Persist one ``<out>/quarantine/<view>.json`` per failed view — the
    per-view debris an operator (or a re-capture loop) acts on without
    parsing logs."""
    qdir = os.path.join(out_dir, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    tr = tel.current()
    for rec in failures:
        _write_json_atomic(os.path.join(qdir, f"{rec.view}.json"),
                           rec.as_dict())
        if tr is not None:
            tr.instant("quarantine", view=rec.view, stage=rec.stage,
                       error=rec.error_type)
    log(f"[pipeline] quarantined {len(failures)} failed view(s) -> {qdir}")


def _failure_manifest(out_dir: str, report: "PipelineReport",
                      views_total: int, views_survived: int,
                      aborted: bool, log) -> str:
    """The failure manifest JSON written next to the STL: every structured
    failure record, the degradation verdict, and (on chaos runs) the fired
    injection counts so seeded assertions need no log scraping."""
    plan = faults.active_plan()
    path = os.path.join(out_dir, tel.host_scoped("failures.json"))
    _write_json_atomic(path, {
        "run_id": report.run_id,
        "views_total": views_total,
        "views_survived": views_survived,
        "degraded": report.degraded,
        "aborted": aborted,
        "retries": report.retries,
        "merge_mode": report.merge_mode,
        "failures": [r.as_dict() for r in report.failures],
        "injected_faults": plan.counts() if plan is not None else {},
    })
    log(f"[pipeline] failure manifest -> {path}")
    return path


# merge.stream / merge.pair_batch / merge.incremental are SCHEDULE knobs:
# the streamed, barrier, and incremental-assembly arms produce byte-identical
# merged output, so none may dirty a merge or pair cache entry — they are
# stripped from all merge-key material
_MERGE_SCHEDULE_KNOBS = ("stream", "pair_batch", "incremental")


def _merge_numeric_json(cfg: Config) -> str:
    """The merge config subtree minus its schedule knobs — the key material
    shared by the merge-stage entry and every per-pair entry."""
    import dataclasses

    d = dataclasses.asdict(cfg.merge)
    for k in _MERGE_SCHEDULE_KNOBS:
        d.pop(k, None)
    return json.dumps({"merge": d}, sort_keys=True)


class _StreamRegistrar:
    """The ``register`` drain lane of the streaming 360 merge.

    ``run_pipeline`` feeds each view's CLEANED compact cloud here the moment
    the executor's drain lane produces it (or straight from the view cache);
    a single register worker thread preps the view
    (``models.reconstruction.prep_view`` — the canonical per-view program)
    and, as soon as views i and i+1 are both present with every earlier view
    accounted for, registers pair i -> i+1 through ``register_prep_pairs``,
    overlapping feature-prep + RANSAC + ICP with the reconstruction/clean of
    later views. Cache-miss pairs dispatch in groups of ``merge.pair_batch``
    (sharded over the merge mesh when one is up); each pair owns a
    stage-cache entry keyed on the two views' cleaned-cloud OUTPUT digests +
    the merge config numerics + its chain id, so a rerun with one dirty view
    re-registers only its <=2 adjacent pairs.

    Pair ids are CHAIN POSITIONS over the surviving views — exactly the ids
    the barrier ``merge_360`` assigns — so streamed transforms are
    bit-identical to the barrier arm's. While every view so far has arrived
    in order, a pair's chain position is just its first view's index; the
    shifts a quarantined view causes are only final once the executor
    returns, so any pair not streamable under that rule (everything past the
    first failed view, including the (k-1) -> (k+1) re-pair around a
    quarantined view k) registers in ``finish``'s catch-up — correctness
    never depends on the overlap.

    Failure containment (PR-3 semantics): a failing pair registration
    retries under the pipeline retry policy, then falls back to the IDENTITY
    transform with a prominent warning + a structured ``FailureRecord`` —
    the run completes DEGRADED instead of losing the whole merge. Identity
    results are never published to the pair cache, and a merge containing
    one is never published to the merge cache, so a rerun re-attempts the
    real registration.
    """

    def __init__(self, cfg: Config, cache, stats: "prof.OverlapStats",
                 mesh, log):
        from concurrent.futures import ThreadPoolExecutor

        from structured_light_for_3d_model_replication_tpu.models import (
            reconstruction as recon,
        )

        self._recon = recon
        self.cfg = cfg
        self.cache = cache
        self.stats = stats
        self.mesh = mesh
        self.log = log
        self.voxel = float(cfg.merge.voxel_size)
        self.fb16 = True if cfg.parallel.force_bf16_features else None
        self.pair_batch = max(1, cfg.merge.pair_batch)
        self.policy = _retry_policy(cfg)
        self._pair_cfg = _merge_numeric_json(cfg) + json.dumps(
            {"backend": cfg.parallel.backend,
             "force_bf16": cfg.parallel.force_bf16_features})
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="sl3d-register")
        self._futs: list = []
        self._closed = False
        self._wedged = False   # bounded close timed out; worker untrusted
        # all state below is mutated only on the register worker until
        # close() drains it; finish()'s catch-up then owns it single-threaded
        self._digests: dict[int, str] = {}
        self._clouds: dict[int, tuple] = {}
        self._devs: dict[int, tuple] = {}   # i -> (device points, count)
        self._preps: dict[int, object] = {}
        self._frontier = 0            # first view index not yet collected
        self._chain: list[int] = []   # contiguous prefix of collected views
        self._seen: set[tuple] = set()
        self._done: dict[tuple, tuple] = {}
        self._pending: list[tuple] = []
        self.failures: list[faults.FailureRecord] = []

    # ---- public API (any thread) ----------------------------------------

    def feed(self, i: int, pts, cols, dev=None) -> None:
        """Hand view ``i``'s cleaned compact cloud to the lane. Safe from
        the executor's drain thread — all work happens on the register
        worker, so cleaning view N+1 never blocks on registering pair N.
        ``dev`` (``(device_points, count)`` from the fused drain) lets the
        prep consume the HBM-resident buffer instead of re-uploading."""
        self._futs.append(self._pool.submit(self._note, i, pts, cols, dev))

    def close(self) -> None:
        """Drain the worker and surface injected crashes. Idempotent.

        Bounded when the deadline layer is on: a stalled register worker
        may delay close by at most ``deadlines.register_s``; past that
        the lane is marked WEDGED — ``finish`` then falls back to the
        identity transform for every pair the worker never resolved (the
        same DEGRADED path a permanently-failed registration takes)
        instead of racing the still-running worker's state."""
        if self._closed:
            return
        self._closed = True
        budget = _lane_budget_s(self.cfg, "register")
        if budget is None:
            self._pool.shutdown(wait=True)
        else:
            self._pool.shutdown(wait=False)
            deadline = dl.Deadline.after(budget, "register-lane close")
            for f in self._futs:
                rem = deadline.remaining()
                # a spent budget means expired, never unbounded
                if rem <= 0 or not dl.wait_settled(f, rem):
                    self._wedged = True
                    self.log(f"[pipeline] WARNING: register lane still "
                             f"busy after its {budget:g}s close budget — "
                             f"abandoning the worker; unresolved pairs "
                             f"fall back to the identity transform")
                    break
        for f in self._futs:
            if not f.done():
                continue
            e = f.exception()
            if isinstance(e, faults.InjectedCrash):
                raise e
            if e is not None:
                self.log(f"[pipeline] WARNING: register lane error "
                         f"({type(e).__name__}: {e}); the affected pairs "
                         f"fall to the merge-time catch-up")

    def finish(self, order: list[int], collected: dict):
        """Barrier the lane, register every remaining survivor pair (the
        degraded-adjacency re-pairs land here), and return host
        ``(T [P,4,4], gfit [P], ifit [P], irmse [P])`` aligned to
        consecutive pairs of ``order``."""
        self.close()
        pairs = [(p, order[p + 1], order[p]) for p in range(len(order) - 1)]
        if self._wedged:
            # the worker may still be mutating its state — do NOT run the
            # catch-up against it. Every pair it never resolved takes the
            # identity fallback (DEGRADED, never cached), exactly like a
            # permanently-failed registration.
            for t in pairs:
                if t not in self._done:
                    self._identity(t, dl.DeadlineExceeded(
                        "register lane stalled past deadlines.register_s; "
                        "pair abandoned"))
        else:
            for i in order:  # backfill anything a lost feed never recorded
                if i not in self._digests:
                    pts, cols = collected[i]
                    self._clouds[i] = (pts, cols)
                    self._digests[i] = _stagecache_digest(points=pts,
                                                          colors=cols)
            for t in pairs:
                if t not in self._seen:
                    if t[1] - t[2] > 1:
                        self.log(f"[pipeline] re-pairing around quarantined "
                                 f"view(s): pair {t[2]}->{t[1]} (chain "
                                 f"position {t[0]}) closes the ring")
                    self._enqueue(*t)
            self._dispatch()
        if not pairs:
            z = np.zeros(0, np.float32)
            return np.zeros((0, 4, 4), np.float32), z, z, z
        T = np.stack([self._done[t][0] for t in pairs])
        gf = np.asarray([self._done[t][1] for t in pairs], np.float32)
        fi = np.asarray([self._done[t][2] for t in pairs], np.float32)
        ir = np.asarray([self._done[t][3] for t in pairs], np.float32)
        return T, gf, fi, ir

    # ---- register-worker internals ---------------------------------------

    def _note(self, i, pts, cols, dev=None):
        dl.beat("register")   # worker-liveness heartbeat for the watchdog
        self._digests[i] = _stagecache_digest(points=pts, colors=cols)
        self._clouds[i] = (pts, cols)
        if dev is not None:
            self._devs[i] = dev
        while self._frontier in self._clouds:
            self._chain.append(self._frontier)
            self._frontier += 1
            if len(self._chain) >= 2:
                # pair readiness rule: both ends collected AND every earlier
                # view resolved — its chain position (the RANSAC key id) is
                # then final, so the streamed transform is the barrier's
                self._enqueue(len(self._chain) - 2,
                              self._chain[-1], self._chain[-2])

    def _enqueue(self, pid: int, src: int, dst: int) -> None:
        t = (pid, src, dst)
        self._seen.add(t)
        key = self.cache.key(
            "pair", digests=[self._digests[dst], self._digests[src]],
            config_json=self._pair_cfg + json.dumps({"pair": pid}))
        hit = self.cache.get("pair", key)
        if hit is not None:
            self._done[t] = (np.asarray(hit["T"], np.float32),
                             float(hit["gfit"]), float(hit["ifit"]),
                             float(hit["irmse"]))
            return
        self._pending.append((t, key))
        if len(self._pending) >= self.pair_batch:
            self._dispatch()

    def _prep(self, i: int):
        p = self._preps.get(i)
        if p is None:
            t0 = time.perf_counter()
            dev = self._devs.get(i)
            if dev is not None and self.cfg.merge.sample_before <= 1:
                # fused drain handoff: prep the HBM-resident buffer
                # directly — bit-identical to prep_view on the host cloud
                # (prep_view_device's re-pad contract)
                p = self._recon.prep_view_device(dev[0], dev[1], self.voxel)
            else:
                p = self._recon.prep_view(self._clouds[i][0], self.voxel,
                                          self.cfg.merge.sample_before)
            self.stats.add("register", time.perf_counter() - t0, view=i)
            self._preps[i] = p
        return p

    def _identity(self, t: tuple, exc: BaseException) -> None:
        pid, src, dst = t
        self.log(f"[pipeline] WARNING: registration of pair {dst}->{src} "
                 f"failed permanently ({type(exc).__name__}: {exc}); "
                 f"falling back to the IDENTITY transform — the merge "
                 f"completes DEGRADED with view {src} left in its "
                 f"neighbor's frame")
        self.failures.append(faults.FailureRecord.from_exception(
            "register", f"pair_{dst}_{src}", exc))
        self.stats.add_failure("register")
        self._done[t] = (np.eye(4, dtype=np.float32), 0.0, 0.0, 0.0)

    def _dispatch(self) -> None:
        group, self._pending = self._pending, []
        if not group:
            return

        def on_retry(n, e):
            self.stats.add_retry("register")
            self.log(f"[pipeline] transient {type(e).__name__} in register "
                     f"lane ({e}); retry {n}/{self.policy.max_retries}")

        live = []
        for t, key in group:
            pid, src, dst = t
            try:
                # the per-pair injection site, behind the same bounded
                # backoff budget as every other lane
                faults.retry_call(
                    lambda d=dst, s=src: faults.fire("register.pair",
                                                     item=f"{d}->{s}"),
                    self.policy, on_retry=on_retry)
                live.append((t, key))
            except faults.InjectedCrash:
                raise
            except Exception as e:
                self._identity(t, e)
        if not live:
            return
        pairs = [(self._prep(src), self._prep(dst))
                 for (_pid, src, dst), _ in live]
        ids = [t[0] for t, _ in live]

        def run():
            return self._recon.register_prep_pairs(
                pairs, ids, self.cfg.merge, self.voxel, mesh=self.mesh,
                feat_bf16=self.fb16, batch=self.pair_batch)

        t0 = time.perf_counter()
        try:
            T, gf, fi, ir = faults.retry_call(run, self.policy,
                                              on_retry=on_retry)
        except faults.InjectedCrash:
            raise
        except Exception as e:
            for t, _ in live:
                self._identity(t, e)
            return
        self.stats.add_pair_launch(len(live), time.perf_counter() - t0)
        for j, (t, key) in enumerate(live):
            self._done[t] = (np.asarray(T[j], np.float32), float(gf[j]),
                             float(fi[j]), float(ir[j]))
            self.cache.put("pair", key, T=np.asarray(T[j], np.float32),
                           gfit=np.float32(gf[j]), ifit=np.float32(fi[j]),
                           irmse=np.float32(ir[j]))


def _stagecache_digest(**arrays) -> str:
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        StageCache,
    )

    return StageCache.digest_arrays(**arrays)


def _view_plan(calib_path: str, target: str, cfg: Config,
               steps: tuple[str, ...], cache, log):
    """Angle-ordered scan sources plus their content-addressed view cache
    keys — the shared ground truth between the single-process pipeline and
    the multiprocess coordinator. A worker warming the cache MUST key its
    entries exactly as the assembly pass will look them up, so both sides
    derive the plan from this one helper."""
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        config_subtree,
    )

    calib = matfile.load_calibration(calib_path)
    need = gc.frames_per_view(cfg.decode.n_cols, cfg.decode.n_rows,
                              cfg.projector.downsample)
    sources = _scan_sources(target, "batch", need, log=log)
    if len(sources) < 2:
        raise ValueError(
            f"pipeline needs >= 2 scan views under {target!r}, found "
            f"{len(sources)}")
    # the merge chain is angle-ordered; scan folders carry the same
    # '<n>deg' tag the per-view PLYs would, so the fused run and the
    # discrete reconstruct->merge-360 chain see the views in one order
    sources = sort_ply_paths_by_angle(sources)
    view_cfg = config_subtree(cfg, ("decode", "triangulate", "projector",
                                    "clean")) + json.dumps(
        {"steps": list(steps), "backend": cfg.parallel.backend})
    # per-view content keys hashed on the I/O pool — the serial hash wall
    # otherwise delays the batched executor's first launch
    with tel.stage("cache.keys", views=len(sources)):
        view_keys = cache.keys_parallel(
            "view",
            [[calib_path] + imio.list_frame_files(src) for src in sources],
            config_json=view_cfg, io_workers=cfg.parallel.io_workers,
            timeout_s=_lane_budget_s(cfg, "cache"))
    return calib, sources, view_cfg, view_keys


def run_pipeline(calib_path: str, target: str, out_dir: str,
                 cfg: Config | None = None,
                 steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                 merged_name: str = "merged.ply",
                 stl_name: str = "model.stl", log=print,
                 cache=None, prefold=None) -> PipelineReport:
    """The fused scan-to-print command: reconstruct -> per-view masked clean
    -> merge-360 -> mesh, end to end in ONE process with device-resident
    handoff — per-view clouds flow from the pipelined executor's clean lane
    into ``merge_360`` as a ``DeviceClouds`` stack, and the merged cloud is
    meshed from memory. No stage reads another stage's PLY: PLY/STL emission
    is a side output (intermediates always binary; the final merged PLY
    honors ``pipeline.ascii_output``).

    Every stage sits behind the content-addressed StageCache under
    ``<out_dir>/.slscan-cache``: a rerun (after an interrupt, or after
    editing frames/calibration/config) recomputes only the stages whose
    inputs changed — a fully-warm rerun does zero decode/clean/merge/mesh
    compute and just re-emits the artifacts.

    Observability (``observability.trace`` / ``SL3D_TRACE``): the run owns
    a flight recorder — every lane span, cache hit/miss, retry, failure,
    launch, and injected fault lands in ``<out_dir>/trace.jsonl`` (crash-
    safe, append-only) with a ``metrics.json`` registry snapshot next to
    the STL; ``sl3d report <out_dir>`` renders the timeline. A ``run_id``
    correlates the report, the journal, failures.json, and bench lines.
    The recorder closes (and persists metrics) even on a crash/interrupt.

    ``prefold`` (assembly pass of an incremental pod only): a
    ``pipeline.assembly.Prefold`` carrying the coordinator fold lane's
    already-merged prefix. It is re-validated against this run's own view
    order/digests/pair transforms before seeding ``finalize_chain``, so
    output bytes never depend on it.
    """
    cfg = cfg or Config()
    if cfg.coordinator.workers > 0 or cfg.coordinator.listen:
        # host-fault-domain mode: the coordinator leases view/pair items
        # to N worker processes (each a crash domain), then re-enters this
        # function with workers=0 as the assembly pass over the warmed
        # stage cache — so coordinated output is byte-identical to a
        # single-process run by construction. A non-empty
        # coordinator.listen enters the same mode with zero spawned
        # workers: the coordinator serves its real TCP endpoint and waits
        # for external `sl3d worker` joins (the pod-fabric path). Lazy
        # import: coordinator imports stages for the item programs.
        from structured_light_for_3d_model_replication_tpu.parallel import (
            coordinator as _coord,
        )

        return _coord.run_coordinated(calib_path, target, out_dir, cfg,
                                      steps=tuple(steps),
                                      merged_name=merged_name,
                                      stl_name=stl_name, log=log)
    os.makedirs(out_dir, exist_ok=True)
    run_id = tel.new_run_id()
    tracer = prev = None
    if cfg.observability.trace:
        tracer = tel.Tracer(
            os.path.join(out_dir,
                         tel.host_scoped(cfg.observability.trace_file)),
            run_id=run_id,
            meta={"tool": "pipeline", "target": os.path.abspath(target),
                  "backend": cfg.parallel.backend,
                  "merge_method": cfg.merge.method,
                  "merge_stream": cfg.merge.stream,
                  "merge_incremental": cfg.merge.incremental,
                  "host": tel.host_tag(),
                  "host_cpus": os.cpu_count(),
                  "device_count": _initialized_device_count()})
        prev = tel.activate(tracer)
        log(f"[pipeline] flight recorder armed (run {run_id}) -> "
            f"{tracer.path}")
    # ---- deadline layer: run context + lane watchdog --------------------
    # the token+watchdog are the per-item STALL-BREAK path (a stalled
    # view/pair is quarantined, the run continues DEGRADED); the run
    # deadline is the end-to-end ABORT path (pipeline.run_budget_s)
    ctx = prev_ctx = None
    dcfg = cfg.deadlines
    if dcfg.enabled:
        ctx = dl.RunContext(
            run_deadline=dl.Deadline.after(cfg.pipeline.run_budget_s,
                                           "pipeline run"))
        if dcfg.hard_stall_s > 0 or dcfg.soft_stall_s > 0:
            ctx.watchdog = dl.Watchdog(
                dcfg.soft_stall_s, dcfg.hard_stall_s, ctx.token,
                poll_s=dcfg.watchdog_poll_s, out_dir=out_dir,
                run_id=run_id, log=log)
        prev_ctx = dl.activate(ctx)
        if ctx.watchdog is not None:
            ctx.watchdog.start()
        if ctx.run_deadline is not None:
            log(f"[pipeline] run budget armed: "
                f"{cfg.pipeline.run_budget_s:g}s")
    try:
        report = _run_pipeline_impl(calib_path, target, out_dir, cfg,
                                    tuple(steps), merged_name, stl_name,
                                    log, run_id, cache=cache,
                                    prefold=prefold)
        if tracer is not None:
            g = tracer.registry.set_gauge
            g("sl3d_run_wall_seconds", report.elapsed_s)
            g("sl3d_views_computed", report.views_computed)
            g("sl3d_views_cached", report.views_cached)
            g("sl3d_merged_points", report.merged_points)
            g("sl3d_degraded", int(report.degraded))
            if report.overlap:
                g("sl3d_critical_path_seconds",
                  report.overlap.get("critical_path_s") or 0.0)
            if report.assembly and report.assembly.get("tail_s") is not None:
                # same stored value the assembly.tail journal instant
                # carries — the report's ≤1% drift cross-check rides on it
                g("sl3d_assembly_tail_seconds", report.assembly["tail_s"])
        return report
    except Exception as e:
        # EVERY abort leaves a manifest (the below-floor path writes its
        # own richer one first and is not overwritten): a run that ends
        # early — run budget exceeded, unwritable final artifact — must
        # be diagnosable from disk, not just from a traceback. The
        # watchdog's stalls.json lands separately in its stop(); an
        # InjectedCrash (BaseException) deliberately bypasses this, the
        # crash-safety contract covers it.
        mpath = os.path.join(out_dir, tel.host_scoped("failures.json"))
        if not os.path.exists(mpath):
            _write_json_atomic(mpath, {
                "run_id": run_id, "aborted": True, "degraded": False,
                "reason": str(e),
                "run_budget_s": cfg.pipeline.run_budget_s,
                "failures": [faults.FailureRecord.from_exception(
                    "pipeline", "run", e).as_dict()],
            })
            log(f"[pipeline] ABORTED ({type(e).__name__}: {e}); "
                f"manifest -> {mpath}")
        raise
    finally:
        if ctx is not None:
            # wake any lingering cancel-aware sleeps (injected stalls in
            # abandoned worker threads) so teardown never outlives them
            ctx.token.cancel("run ended")
            if ctx.watchdog is not None:
                ctx.watchdog.stop()
                if ctx.watchdog.breaches:
                    log(f"[pipeline] watchdog recorded "
                        f"{len(ctx.watchdog.breaches)} stall breach(es)"
                        + (f" -> {ctx.watchdog.stalls_path}"
                           if ctx.watchdog.stalls_path else ""))
            dl.deactivate(prev_ctx)
        if tracer is not None:
            tel.deactivate(prev)
            metrics_path = os.path.join(
                out_dir, tel.host_scoped(cfg.observability.metrics_file))
            tracer.close(metrics_path)
            log(f"[pipeline] flight recorder -> {tracer.path} + "
                f"{metrics_path} (inspect with: sl3d report {out_dir})")


def _initialized_device_count():
    """Attached-device count, ONLY if this process already initialized a
    jax backend — the numpy-backend pipeline must never claim an
    accelerator just to stamp a regime field (the bench emit() contract)."""
    try:
        from jax._src import xla_bridge as _xb

        if _xb._backends:
            import jax

            return jax.device_count()
    except Exception:
        pass
    return None


def _run_pipeline_impl(calib_path: str, target: str, out_dir: str,
                       cfg: Config, steps: tuple[str, ...],
                       merged_name: str, stl_name: str, log,
                       run_id: str, cache=None,
                       prefold=None) -> PipelineReport:
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        StageCache, config_subtree,
    )

    t_start = time.monotonic()
    os.makedirs(out_dir, exist_ok=True)
    # startup sweep: a kill -9 in an earlier run leaves *.tmp orphans under
    # the out tree (merged/STL/manifest staging, cache puts); none is data
    atomic.sweep_tmp(out_dir, log=log, recursive=True)
    # a previous run's stall ledger / failure manifest must not masquerade
    # as this run's: the watchdog rewrites stalls.json only if THIS run
    # breaches, and the abort path writes failures.json only for THIS
    # run's failures (clean completion re-asserts the removal at the end)
    for stale in ("stalls.json", "failures.json"):
        p = os.path.join(out_dir, tel.host_scoped(stale))
        if os.path.exists(p):
            os.remove(p)
    report = PipelineReport(run_id=run_id)
    if cache is None:
        cache = StageCache(os.path.join(out_dir, ".slscan-cache"),
                           enabled=cfg.pipeline.cache, log=log,
                           verify=cfg.pipeline.verify_cache)

    # ---- stage 1+2: per-view reconstruct + masked clean -----------------
    steps = tuple(steps)
    calib, sources, _view_cfg, view_keys = _view_plan(
        calib_path, target, cfg, steps, cache, log)
    collected: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    missing: list[tuple[int, str]] = []
    for i, src in enumerate(sources):
        hit = cache.get("view", view_keys[i])
        if hit is not None:
            collected[i] = (np.asarray(hit["points"], np.float32),
                            np.asarray(hit["colors"], np.uint8))
        else:
            missing.append((i, src))
    report.views_cached = len(collected)

    # ---- merge mode: streamed register lane vs barrier vs posegraph -----
    if cfg.merge.method == "posegraph":
        if cfg.merge.stream:
            log("[pipeline] NOTICE: merge.method='posegraph' has no "
                "streaming arm — merge.stream is ignored and the barrier "
                "pose-graph merge runs after reconstruction")
        report.merge_mode = "posegraph"
    else:
        report.merge_mode = "streamed" if cfg.merge.stream else "barrier"

    def merge_mesh_grid():
        if not cfg.parallel.merge_mesh:
            return None
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )

        return meshlib.merge_mesh(cfg.parallel)

    stream = None
    stream_stats = None
    t_stream0 = time.perf_counter()

    def arm_stream():
        nonlocal stream, stream_stats
        stream_stats = prof.OverlapStats()
        stream = _StreamRegistrar(cfg, cache, stream_stats,
                                  merge_mesh_grid(), log)
        log(f"[pipeline] merge: streaming register lane armed "
            f"(pair_batch={cfg.merge.pair_batch})")
        for i in sorted(collected):
            stream.feed(i, *collected[i])

    if report.merge_mode == "streamed" and missing:
        # views will stream out of the executor below: pair (i, i+1)
        # registers the moment both are cleaned, overlapped with the
        # reconstruction/clean of later views. (With every view cached the
        # lane is only armed lazily, on a merge-cache miss — a fully-warm
        # rerun stays zero-lookup/zero-compute.)
        arm_stream()

    if missing:
        miss_sources = [s for _, s in missing]
        scanner = _build_scanner(miss_sources, calib, cfg)
        view_dir = None
        if cfg.pipeline.write_view_plys:
            view_dir = os.path.join(out_dir, "views")
            os.makedirs(view_dir, exist_ok=True)

        def collect(j, src, pts, cols, dev=None):
            i = missing[j][0]
            collected[i] = (pts, cols)
            cache.put("view", view_keys[i], points=pts, colors=cols)
            if stream is not None:
                stream.feed(i, pts, cols, dev=dev)

        batch = BatchReport(run_id=run_id)
        run_args = (miss_sources, calib, cfg, scanner, "batch", view_dir,
                    batch, log)
        kw = dict(clean_steps=steps, collect=collect,
                  write_plys=cfg.pipeline.write_view_plys)
        t_rec = time.perf_counter()
        if _use_batched(cfg, scanner, len(miss_sources)):
            # the register lane shares the executor's OverlapStats so
            # overlap reads as ONE schedule (register_s vs critical_path_s)
            _reconstruct_batched(*run_args, **kw, stats=stream_stats)
        elif cfg.parallel.io_workers > 1 and len(miss_sources) > 1:
            _reconstruct_pipelined(*run_args, **kw, stats=stream_stats)
        else:
            _reconstruct_serial(*run_args, **kw)
        _tr = tel.current()
        if _tr is not None:
            _tr.span_end("reconstruct", time.perf_counter() - t_rec,
                         views=len(miss_sources))
        report.failed = batch.failed
        report.failures = batch.failures
        report.retries = batch.retries
        report.overlap = batch.overlap
        if batch.failed:
            # a lane wait that timed out quarantined its view, but the
            # abandoned worker may still have completed LATE and handed
            # the cloud to collect() — a quarantined view must never
            # also merge (its late cache entry is content-correct and
            # may stay for reruns)
            failed_srcs = {s for s, _ in batch.failed}
            for i, src in missing:
                if src in failed_srcs:
                    collected.pop(i, None)
    report.views_computed = len(collected) - report.views_cached

    # ---- failure domain: quarantine + degrade-or-abort decision ---------
    # the floor never drops under 2 — a merge needs two clouds
    floor = max(2, cfg.pipeline.min_views)
    if report.failures:
        _quarantine_failures(out_dir, report.failures, log)
    if len(collected) < floor:
        if stream is not None:
            try:        # the abort is the headline; drain quietly
                stream.close()
            except BaseException:
                pass
        report.manifest_path = _failure_manifest(
            out_dir, report, len(sources), len(collected), aborted=True,
            log=log)
        raise ValueError(
            f"pipeline: only {len(collected)} views survived "
            f"reconstruction, below the pipeline.min_views={floor} floor "
            f"(failed: {[os.path.basename(s) for s, _ in report.failed]}; "
            f"see {report.manifest_path})")
    if report.failed:
        report.degraded = True
        log(f"[pipeline] WARNING: {len(report.failed)}/{len(sources)} "
            f"view(s) failed and were quarantined; continuing DEGRADED "
            f"with {len(collected)} views (floor: pipeline.min_views="
            f"{floor}). The merged model will have reduced coverage at "
            f"the failed angles.")

    # ---- stage 3: merge-360 (device-resident handoff) -------------------
    _budget_check("merge")
    # the barrier stages ahead (chain accumulate, Poisson solve) are
    # single opaque device/numpy calls — no heartbeat can flow from
    # inside them, so the watchdog pauses here (run budget still covers
    # the tail; the register catch-up's own waits stay bounded)
    dl.watchdog_suspend()
    order = sorted(collected)
    view_digests = [StageCache.digest_arrays(points=collected[i][0],
                                             colors=collected[i][1])
                    for i in order]
    merge_cfg = _merge_numeric_json(cfg) + json.dumps(
        {"backend": cfg.parallel.backend,
         "force_bf16": cfg.parallel.force_bf16_features,
         "merge_mesh": cfg.parallel.merge_mesh})
    t_merge = time.perf_counter()
    merge_key = cache.key("merge", digests=view_digests,
                          config_json=merge_cfg)
    hit = cache.get("merge", merge_key)
    merged_path = os.path.join(out_dir, merged_name)
    if hit is not None:
        points = np.asarray(hit["points"], np.float32)
        colors = np.asarray(hit["colors"], np.uint8)
        transforms = [t for t in np.asarray(hit["transforms"])]
        report.merge_status = "cache-hit"
        if stream is not None:
            # identical view bytes -> every streamed pair was a cache hit;
            # drain the lane and keep the published entries
            stream.close()
            stream_stats.finish(time.perf_counter() - t_stream0)
    else:
        clouds = [collected[i] for i in order]
        fb16 = True if cfg.parallel.force_bf16_features else None
        cacheable = True
        if report.merge_mode == "streamed":
            if stream is None:
                # every view was cached but the merge is dirty (a merge
                # config edit): run the register lane synchronously — the
                # per-pair cache makes every unchanged pair free
                arm_stream()
            with prof.trace():
                T_all, gf_all, fi_all, ir_all = stream.finish(order,
                                                              collected)
                stream_stats.finish(time.perf_counter() - t_stream0)
                pf = None
                if prefold is not None:
                    # trust nothing folded during the pod phase until it
                    # matches THIS pass's order/digests/transforms
                    pf = prefold.validate(order,
                                          dict(zip(order, view_digests)),
                                          T_all, log=log)
                if pf is not None:
                    # replay the fold lane's buffered events now that a
                    # tracer is live (the pod phase ran before run_pipeline
                    # opened one): lane spans + OverlapStats from one call
                    for kind, idx, dur in pf.events:
                        stream_stats.add_fold(kind, idx, dur)
                    report.assembly = {
                        "folded_views": prefold.offered_views,
                        "used_views": len(pf.transforms),
                        "folded_pairs": len(pf.T_pairs),
                        "fold_wall_s": round(sum(e[2] for e in pf.events),
                                             6)}
                    log(f"[assembly] seeding finalize from "
                        f"{len(pf.transforms)} prefolded view(s); only "
                        f"the {len(order) - len(pf.transforms)}-view "
                        f"suffix accumulates here")
                # the ONLY remaining barrier: chain-accumulate + final
                # voxel/outlier postprocess (slab-sharded over the mesh
                # when one is up)
                points, colors, transforms = recon.finalize_chain(
                    clouds, T_all, gf_all, fi_all, ir_all, cfg.merge,
                    log=log, mesh=stream.mesh, prefold=pf)
            if stream.failures:
                report.failures.extend(stream.failures)
                report.degraded = True
                cacheable = False   # a rerun must re-attempt the real pair
                log(f"[pipeline] WARNING: {len(stream.failures)} pair "
                    f"registration(s) fell back to identity; the merged "
                    f"model is DEGRADED at those seams")
        else:
            mesh_grid = merge_mesh_grid()
            with prof.trace():
                if cfg.merge.method == "posegraph":
                    points, colors, transforms = recon.merge_360_posegraph(
                        clouds, cfg.merge, log=log, mesh=mesh_grid,
                        feat_bf16=fb16)
                else:
                    # DeviceClouds: the per-view clean -> merge handoff
                    # stays in accelerator memory (one compact upload on a
                    # host executor; zero re-upload when resident)
                    dcv = recon.stack_views_device(clouds)
                    points, colors, transforms = recon.merge_360(
                        dcv, cfg.merge, log=log, mesh=mesh_grid,
                        feat_bf16=fb16)
        points = np.asarray(points, np.float32)
        colors = np.asarray(colors, np.uint8)
        if cacheable:
            cache.put("merge", merge_key, points=points, colors=colors,
                      transforms=np.stack([np.asarray(t)
                                           for t in transforms]))
        report.merge_status = "computed"
    _tr = tel.current()
    if _tr is not None:
        _tr.span_end("merge", time.perf_counter() - t_merge,
                     status=report.merge_status, mode=report.merge_mode,
                     views=len(order))
    if stream_stats is not None:
        # one schedule, one record: the executor lanes plus the register
        # lane (pair launches, register_s vs critical_path_s)
        snap = stream_stats.as_dict()
        for k in ("compute_batch", "shard_devices"):
            if report.overlap and k in report.overlap:
                snap[k] = report.overlap[k]
        report.overlap = snap
    t_wm = time.perf_counter()

    def _final_write_retry(n, e):
        # the final artifacts sit behind the same transient budget as the
        # per-view lanes: a blip at the very last write must not cost a
        # completed scan (soak finding — an unmatched ply.write:transient
        # previously aborted the whole run here)
        report.retries += 1
        log(f"[pipeline] transient {type(e).__name__} writing a final "
            f"artifact ({e}); retry {n}")

    _retry_stage("write",
                 lambda: ply.write_ply(merged_path, points, colors,
                                       binary=not cfg.pipeline.ascii_output),
                 _retry_policy(cfg), _final_write_retry)
    if _tr is not None:
        _tr.span_end("write.merged", time.perf_counter() - t_wm,
                     points=len(points))
    log(f"[pipeline] merged cloud -> {merged_path} ({len(points):,} points)")
    report.merged_ply = merged_path
    report.merged_points = len(points)

    # ---- stage 4: mesh -> STL ------------------------------------------
    _budget_check("mesh")
    t_mesh = time.perf_counter()
    merged_digest = StageCache.digest_arrays(points=points)
    mesh_key = cache.key("mesh", digests=[merged_digest],
                         config_json=config_subtree(cfg, ("mesh",)))
    hit = cache.get("mesh", mesh_key)
    if hit is not None:
        verts = np.asarray(hit["verts"], np.float32)
        faces = np.asarray(hit["faces"], np.int32)
        report.mesh_status = "cache-hit"
    else:
        verts, faces, _ = _mesh_arrays(points, cfg, log=log)
        cache.put("mesh", mesh_key, verts=verts, faces=faces)
        report.mesh_status = "computed"
    stl_path = os.path.join(out_dir, stl_name)
    _retry_stage("write", lambda: _write_mesh(stl_path, verts, faces,
                                              log=log),
                 _retry_policy(cfg), _final_write_retry)
    if _tr is not None:
        _tr.span_end("mesh", time.perf_counter() - t_mesh,
                     status=report.mesh_status, verts=len(verts),
                     faces=len(faces))
    report.stl_path = stl_path

    if report.failures:
        report.manifest_path = _failure_manifest(
            out_dir, report, len(sources), len(collected), aborted=False,
            log=log)
    else:
        # a clean (re)run must not advertise a previous run's failures
        stale = os.path.join(out_dir, tel.host_scoped("failures.json"))
        if os.path.exists(stale):
            os.remove(stale)

    if prefold is not None:
        if report.assembly is None:
            # merge cache-hit (or non-streamed config): nothing needed
            # folding, but the tail is still the certified quantity
            report.assembly = {
                "folded_views": prefold.offered_views, "used_views": 0,
                "folded_pairs": len(prefold.T_pairs),
                "fold_wall_s": round(sum(e[2] for e in prefold.events), 6)}
        if prefold.settled_unix:
            tail_s = round(time.time() - prefold.settled_unix, 6)
            report.assembly["tail_s"] = tail_s
            if stream_stats is not None:
                # OverlapStats gauge + assembly.tail journal instant from
                # ONE call (the PR-6 can't-drift pattern); the report's
                # metrics gauge reads the same stored value
                stream_stats.set_assembly_tail(tail_s, report.assembly)
                if report.overlap is not None:
                    report.overlap.update(stream_stats.assembly_snapshot())
            else:
                _tr3 = tel.current()
                if _tr3 is not None:
                    _tr3.instant("assembly.tail", **report.assembly)
    report.cache = cache.stats()
    report.elapsed_s = time.monotonic() - t_start
    log(f"[pipeline] {report.summary}")
    return report


def write_patterns(out_dir: str, cfg: Config | None = None, log=print) -> list[str]:
    """Persist the projector pattern stack as numbered images — the offline
    equivalent of generate_patterns (server/sl_system.py:44-86)."""
    cfg = cfg or Config()
    p = cfg.projector
    frames = gc.generate_pattern_stack(p.width, p.height,
                                       brightness=p.brightness,
                                       downsample=p.downsample)
    paths = imio.save_stack(out_dir, frames)
    log(f"[patterns] {len(paths)} frames -> {out_dir}")
    return paths
