"""File-level pipeline stages: the artifact-per-stage contract.

Every stage reads its input artifact from disk and persists its output, so any
stage can be re-entered from files alone — the checkpoint/resume model the
reference uses throughout (images -> calib.mat -> per-view .ply -> cleaned .ply
-> merged .ply -> .stl; server/gui.py tabs 2-7 are pure functions of files).
These functions are the single implementation shared by the CLI, tests, and any
future GUI front-end.

Capability parity: process_multi_ply (server/processing.py:251-334), the tab-3
cleanup chain (server/gui.py:1391-1522), merge_pro_360 (processing.py:489-629),
mesh_360/reconstruct_stl (processing.py:632-860).
"""
from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile, ply
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
from structured_light_for_3d_model_replication_tpu.utils import profiling as prof

__all__ = [
    "BatchReport", "reconstruct_source", "reconstruct", "clean_cloud",
    "merge_views", "mesh_cloud", "sort_ply_paths_by_angle", "write_patterns",
]

_DEG_RE = re.compile(r"(\d+(?:\.\d+)?)\s*deg", re.IGNORECASE)


@dataclass
class BatchReport:
    """Per-item success/failure accounting (processing.py:314-334 semantics)."""

    outputs: list[str] = field(default_factory=list)
    failed: list[tuple[str, str]] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def summary(self) -> str:
        total = len(self.outputs) + len(self.failed)
        return f"{len(self.outputs)}/{total} succeeded in {self.elapsed_s:.1f}s"


def sort_ply_paths_by_angle(paths: list[str]) -> list[str]:
    """Order merge inputs by the ``"<n>deg"`` tag in the filename, falling back
    to lexical order for untagged files (server/processing.py:499-519)."""

    def key(p):
        m = _DEG_RE.search(os.path.basename(p))
        return (0, float(m.group(1)), p) if m else (1, 0.0, p)

    return sorted(paths, key=key)


def _scan_sources(target: str, mode: str, need: int) -> list[str]:
    """Resolve `target` to a list of scan-folder sources per the reference's
    single/batch/files modes (processing.py:300-322)."""
    if mode == "single":
        return [target]
    if mode == "batch":
        subs = sorted(
            os.path.join(target, d) for d in os.listdir(target)
            if os.path.isdir(os.path.join(target, d))
        )
        out = []
        for s in subs:
            try:
                if len(imio.list_frame_files(s)) >= need:
                    out.append(s)
            except (FileNotFoundError, NotADirectoryError):
                continue
        return out
    if mode == "files":
        return [p.strip() for p in target.split(",") if p.strip()]
    raise ValueError(f"unknown reconstruct mode {mode!r} (single|batch|files)")


def reconstruct_source(source, calib: dict, cfg: Config, scanner=None):
    """One scan source (folder or file list) -> (points, colors) compact arrays.

    Backend-switched: ``cfg.parallel.backend == 'numpy'`` runs the bit-exact
    CPU path; ``'jax'`` runs the fused TPU program (SLScanner when provided,
    else the module-level jit kernels).
    """
    dcfg, tcfg = cfg.decode, cfg.triangulate
    ds = cfg.projector.downsample  # must match the capture-time D_SAMPLE_PROJ
    frames, texture = imio.load_stack(source)
    if cfg.parallel.backend == "numpy":
        dec = gc.decode_stack_np(
            frames, texture, n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate_np(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval,
        )
    elif scanner is not None and not tcfg.bitexact:
        # a bitexact config must take the branch below (device decode +
        # host-NumPy triangulation) no matter what the caller passed
        cloud = scanner.forward(frames, thresh_mode=dcfg.thresh_mode,
                                shadow_val=dcfg.shadow_val,
                                contrast_val=dcfg.contrast_val)
    else:
        import jax.numpy as jnp

        dec = gc.decode_stack(
            jnp.asarray(frames), jnp.asarray(texture),
            n_cols=dcfg.n_cols, n_rows=dcfg.n_rows,
            n_sets_col=dcfg.n_sets_col, n_sets_row=dcfg.n_sets_row,
            thresh_mode=dcfg.thresh_mode, shadow_val=dcfg.shadow_val,
            contrast_val=dcfg.contrast_val, downsample=ds,
        )
        cloud = tri.triangulate(
            dec.col_map, dec.row_map, dec.mask, dec.texture, calib,
            row_mode=tcfg.row_mode, epipolar_tol=tcfg.epipolar_tol,
            plane_eval=tcfg.plane_eval, bitexact=tcfg.bitexact,
        )
    return tri.compact_cloud(cloud)


def reconstruct(calib_path: str, target: str, mode: str = "single",
                output: str | None = None, cfg: Config | None = None,
                log=print) -> BatchReport:
    """Scan folder(s) -> per-view colored PLY (process_multi_ply parity).

    ``output``: for single mode a .ply path (default: <target>.ply); for
    batch/files a directory (default: alongside each source).
    """
    cfg = cfg or Config()
    calib = matfile.load_calibration(calib_path)
    need = gc.frames_per_view(cfg.decode.n_cols, cfg.decode.n_rows,
                              cfg.projector.downsample)
    sources = _scan_sources(target, mode, need)
    if not sources:
        raise ValueError(f"no scan sources found under {target!r} (mode={mode})")

    scanner = None
    # bitexact export triangulates through the NumPy twin in
    # reconstruct_source, never the scanner's fused program
    if cfg.parallel.backend != "numpy" and not cfg.triangulate.bitexact:
        from structured_light_for_3d_model_replication_tpu.models.scanner import (
            SLScanner,
        )
        first = imio.list_frame_files(sources[0])
        probe = imio.load_gray(first[0])
        scanner = SLScanner(
            calib, (probe.shape[1], probe.shape[0]),
            proj_size=(cfg.decode.n_cols, cfg.decode.n_rows),
            row_mode=cfg.triangulate.row_mode,
            epipolar_tol=cfg.triangulate.epipolar_tol,
            n_sets_col=cfg.decode.n_sets_col, n_sets_row=cfg.decode.n_sets_row,
            downsample=cfg.projector.downsample,
            plane_eval=cfg.triangulate.plane_eval,
        )

    report = BatchReport()
    timer = prof.StageTimer()
    t0 = time.monotonic()
    for src in sources:
        name = os.path.basename(os.path.normpath(src)) or "cloud"
        try:
            with timer.stage(name), prof.trace():
                pts, cols = reconstruct_source(src, calib, cfg, scanner)
            if mode == "single" and output:
                out_path = output
            elif output:
                os.makedirs(output, exist_ok=True)
                out_path = os.path.join(output, f"{name}.ply")
            else:
                out_path = os.path.normpath(src) + ".ply"
            ply.write_ply(out_path, pts, cols)
            log(f"[reconstruct] {name}: {len(pts):,} points -> {out_path}")
            report.outputs.append(out_path)
        except Exception as e:  # per-item tolerance (processing.py:323-330)
            from structured_light_for_3d_model_replication_tpu.utils.jax_compat import (
                is_backend_init_error,
            )

            if is_backend_init_error(e):
                # process-level condition, not an item failure: propagate
                # so the CLI's CPU-fallback retry can handle it (otherwise
                # every item "fails" identically and no retry fires)
                raise
            log(f"[reconstruct] {name} FAILED: {e}")
            report.failed.append((src, str(e)))
    report.elapsed_s = time.monotonic() - t0
    log(f"[reconstruct] {report.summary}")
    prof.get_logger().debug("reconstruct stage timing:\n%s", timer.report())
    return report


_CLEAN_STEPS = ("background", "cluster", "radius", "statistical")


def clean_cloud(input_ply: str, output_ply: str, cfg: Config | None = None,
                steps: tuple[str, ...] | list[str] = _CLEAN_STEPS,
                log=print, step_callback=None) -> dict:
    """Cleanup chain on one cloud: background plane removal -> largest cluster
    -> radius outlier -> statistical outlier (the tab-3 chain, gui.py:1391-1522;
    ops per processing.py:337-448). Steps are individually selectable.
    ``step_callback(name, points, colors)`` receives each intermediate cloud
    (the tab's in-memory per-step inspection flow, made non-blocking)."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc

    cfg = cfg or Config()
    ccfg = cfg.clean
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)
    cols = np.asarray(data.get("colors")) if data.get("colors") is not None \
        else np.zeros_like(pts, dtype=np.uint8)
    use_np = cfg.parallel.backend == "numpy"
    counts = {"input": len(pts)}

    for step in steps:
        if step not in _CLEAN_STEPS:
            raise ValueError(f"unknown clean step {step!r}; valid: {_CLEAN_STEPS}")
        valid = np.ones(len(pts), bool)
        if step == "background" and ccfg.remove_background_plane:
            # the reference keeps the INVERSE of the plane inliers
            # (processing.py:349-354)
            if use_np:
                _, inliers = pc.segment_plane_np(
                    pts, valid, distance_threshold=ccfg.plane_ransac_dist,
                    num_iterations=ccfg.plane_ransac_trials)
            else:
                _, inliers = pc.segment_plane(
                    jnp.asarray(pts), jnp.asarray(valid),
                    distance_threshold=ccfg.plane_ransac_dist,
                    num_iterations=ccfg.plane_ransac_trials)
            keep = valid & ~np.asarray(inliers)
        elif step == "cluster":
            fn = pc.largest_cluster_mask_np if use_np else pc.largest_cluster_mask
            keep = np.asarray(fn(pts if use_np else jnp.asarray(pts),
                                 valid if use_np else jnp.asarray(valid),
                                 eps=ccfg.cluster_eps,
                                 min_points=ccfg.cluster_min_points))
        elif step == "radius":
            fn = pc.radius_outlier_mask_np if use_np else pc.radius_outlier_mask
            keep = np.asarray(fn(pts if use_np else jnp.asarray(pts),
                                 valid if use_np else jnp.asarray(valid),
                                 radius=ccfg.radius,
                                 nb_points=ccfg.radius_nb_points))
        elif step == "statistical":
            # degraded jax-on-CPU delegates inside the op itself to the
            # cKDTree twin at production scale (see statistical_outlier_mask)
            fn = (pc.statistical_outlier_mask_np if use_np
                  else pc.statistical_outlier_mask)
            keep = np.asarray(fn(pts if use_np else jnp.asarray(pts),
                                 valid if use_np else jnp.asarray(valid),
                                 ccfg.outlier_nb_neighbors,
                                 ccfg.outlier_std_ratio))
        else:
            continue
        pts, cols = pts[keep], cols[keep]
        counts[step] = len(pts)
        log(f"[clean] {step}: {len(pts):,} points remain")
        if step_callback is not None:
            step_callback(step, pts, cols)
        if len(pts) == 0:
            log("[clean] WARNING: all points removed; aborting chain")
            break

    ply.write_ply(output_ply, pts, cols)
    log(f"[clean] wrote {output_ply} ({len(pts):,} points)")
    return counts


def merge_views(input_folder: str, output_ply: str, cfg: Config | None = None,
                log=print, step_callback=None):
    """Folder of per-view PLYs -> one registered 360-degree cloud
    (merge_pro_360 parity; ``cfg.merge.method`` picks greedy sequential (A18)
    or pose-graph global optimization (Old/360Merge.py:50-78 capability)).
    ``step_callback(i, points, colors)`` mirrors the reference's per-step
    merge preview hook (processing.py:600-603) — e.g. a
    acquire.viewer.StageRecorder.merge_step for the web viewer."""
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )

    cfg = cfg or Config()
    out_abs = os.path.abspath(output_ply)
    paths = sort_ply_paths_by_angle([
        p for f in os.listdir(input_folder)
        if f.lower().endswith(".ply")
        and os.path.abspath(p := os.path.join(input_folder, f)) != out_abs
    ])
    if len(paths) < 2:
        raise ValueError(f"need >= 2 PLY views in {input_folder}, found {len(paths)}")
    log(f"[merge] {len(paths)} views: " + ", ".join(os.path.basename(p) for p in paths))
    clouds = []
    for p in paths:
        d = ply.read_ply(p)
        c = d.get("colors")
        if c is None:
            c = np.zeros_like(d["points"], dtype=np.uint8)
        clouds.append((np.asarray(d["points"], np.float32), np.asarray(c, np.uint8)))

    mesh = None
    if cfg.parallel.merge_mesh:
        from structured_light_for_3d_model_replication_tpu.parallel import (
            mesh as meshlib,
        )

        mesh = meshlib.merge_mesh(cfg.parallel)
        if mesh is not None:
            log(f"[merge] sharding the chain over "
                f"{mesh.devices.size} devices (parallel.merge_mesh)")
    # parallel.force_bf16_features=true FORCES the opt-in bf16 feature
    # matmuls; false (default) leaves the auto policy (f32 everywhere
    # since the r5 on-chip quality sweep; see _resolve_feat_bf16)
    fb16 = True if cfg.parallel.force_bf16_features else None
    with prof.trace():
        if cfg.merge.method == "posegraph":
            points, colors, transforms = recon.merge_360_posegraph(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
        else:
            points, colors, transforms = recon.merge_360(
                clouds, cfg.merge, log=log, step_callback=step_callback,
                mesh=mesh, feat_bf16=fb16)
    ply.write_ply(output_ply, points, colors)
    log(f"[merge] wrote {output_ply} ({len(points):,} points)")
    return points, colors, transforms


def mesh_cloud(input_ply: str, output_path: str, cfg: Config | None = None,
               save_normals_path: str | None = None, log=print):
    """Cloud PLY -> mesh (.stl or .ply by extension): reconstruct_stl/mesh_360
    parity including the optional normals debug dump (processing.py:690-693)."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models import meshing
    from structured_light_for_3d_model_replication_tpu.ops import normals as nrm

    cfg = cfg or Config()
    data = ply.read_ply(input_ply)
    pts = np.asarray(data["points"], np.float32)
    valid = np.ones(len(pts), bool)

    normals = data.get("normals")
    if normals is None:
        nr = nrm.estimate_normals(jnp.asarray(pts), jnp.asarray(valid),
                                  k=cfg.mesh.normal_max_nn,
                                  radius=cfg.mesh.normal_radius or None)
        nr = nrm.orient_normals(jnp.asarray(pts), nr, jnp.asarray(valid),
                                mode=cfg.mesh.orientation)
        normals = np.asarray(nr)
        log(f"[mesh] estimated normals (k={cfg.mesh.normal_max_nn}, "
            f"{cfg.mesh.orientation} orientation)")
    if save_normals_path:
        ply.write_ply(save_normals_path, pts, data.get("colors"), normals)
        log(f"[mesh] normals debug cloud -> {save_normals_path}")

    with prof.trace():
        verts, faces = meshing.reconstruct_mesh(pts, valid, normals,
                                                cfg=cfg.mesh, log=log)
    if output_path.lower().endswith(".stl"):
        meshing.mesh_to_stl(output_path, verts, faces)
    else:
        ply.write_mesh_ply(output_path, verts, faces)
    log(f"[mesh] wrote {output_path} ({len(verts):,} verts, {len(faces):,} faces)")
    return verts, faces


def write_patterns(out_dir: str, cfg: Config | None = None, log=print) -> list[str]:
    """Persist the projector pattern stack as numbered images — the offline
    equivalent of generate_patterns (server/sl_system.py:44-86)."""
    cfg = cfg or Config()
    p = cfg.projector
    frames = gc.generate_pattern_stack(p.width, p.height,
                                       brightness=p.brightness,
                                       downsample=p.downsample)
    paths = imio.save_stack(out_dir, frames)
    log(f"[patterns] {len(paths)} frames -> {out_dir}")
    return paths
