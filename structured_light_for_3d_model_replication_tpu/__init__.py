"""structured_light_for_3d_model_replication_tpu — TPU-native structured-light scan-to-print framework.

A brand-new JAX/XLA/Pallas/pjit framework with the capabilities of the reference
scan-to-print system (TtT609/Structured_Light_for_3D_Model_Replication): Gray-code
pattern projection, phone-synchronized capture, turntable control, per-pixel stripe
decode, projector-camera stereo calibration, ray-plane triangulation, point-cloud
cleaning, 360-degree multi-view registration/merge, and meshing to printable STL.

Unlike the reference (single-process NumPy/OpenCV/Open3D), the compute core here is
vmapped/shard_mapped JAX running on TPU: decode and triangulation are fused XLA
programs over the full H x W x bitplane stack, point-cloud neighborhood ops are tiled
matmul-shaped reductions on the MXU, registration is batched-hypothesis RANSAC plus
convergence-stopped ICP, and meshing is a grid Poisson solve plus a vectorized
Surface Nets extractor. Views shard across chips on a `jax.sharding.Mesh` ("data" axis); pixel rows /
point blocks shard on the "model" axis.

Subpackage map (reference parity in parentheses, see SURVEY.md section 2):
  ops/       pure array math: graycode, masks, triangulate, knn, pointcloud,
             registration, normals, poisson, surface_nets (A4, A8, A9, A12-A20)
  models/    end-to-end "model" pipelines: scanner forward pass, 360 reconstruction
  parallel/  device mesh, shardings, collective helpers (new; reference is 1-node)
  calib/     chessboard + Gray-corner stereo calibration (A6)
  io/        PLY/STL/.mat/image-stack codecs (A10 replaced with binary vectorized)
  acquire/   HTTP capture server, turntable serial, projector, sequencer (A2, A5, A21)
  pipeline/  artifact-per-stage orchestration + resume (gui.py tab flows, A22)
  utils/     config-adjacent helpers, synthetic scene generator, timing/profiling
"""

__version__ = "0.1.0"

from structured_light_for_3d_model_replication_tpu.config import (  # noqa: F401
    Config,
    DecodeConfig,
    TriangulateConfig,
    load_config,
)
