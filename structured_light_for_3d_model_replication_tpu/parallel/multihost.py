"""Multi-host (multi-process) device-mesh bring-up.

The reference is single-node; its only cross-machine links are the
acquisition-side HTTP/serial protocols (SURVEY.md section 5). The TPU build's
compute-side equivalent is a jax.distributed process group over ICI/DCN:
every host calls ``initialize()``, then builds one global Mesh spanning all
hosts' devices with ``global_mesh()``; pjit/shard_map programs written
against parallel/scan.py then run unchanged — XLA routes collectives over ICI
within a slice and DCN across slices.

On a single process this degrades gracefully: ``initialize()`` is a no-op and
``global_mesh()`` equals the local mesh, so the same pipeline code serves a
laptop, one TPU VM, or a multi-host pod.
"""
from __future__ import annotations

import os

__all__ = ["initialize", "global_mesh", "is_multiprocess", "process_summary"]


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               connect_timeout_s: float | None = None) -> bool:
    """Join the jax.distributed process group when multi-host settings are
    present (flags or the standard env vars); returns True when distributed
    mode is active. Safe to call more than once.

    ``connect_timeout_s`` bounds the group join: a peer that never shows up
    (wrong address, firewalled port, a dead coordinator) otherwise hangs the
    gloo/distributed client indefinitely with no diagnostic. Past the bound
    a ``DeadlineExceeded`` names the address and topology so the operator
    knows WHICH rendezvous stalled. ``None`` keeps the library default."""
    env_addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    addr = coordinator_address or env_addr
    nproc = num_processes if num_processes is not None else (
        int(env_np) if env_np else None)
    if addr is None and nproc is None:
        # No multi-host config. Do NOT call jax.process_count() here — it
        # initializes the local backend as a side effect, after which a later
        # explicit distributed initialize() in this process would fail.
        # Report an already-initialized process group (auto-initialized pod
        # runtimes) from jax.distributed's own state instead.
        return _distributed_active()
    if addr is None:
        # a process count alone cannot join a group — the old code passed
        # coordinator_address=None through and crashed inside jax.distributed
        raise ValueError(
            f"num_processes={nproc!r} given without a coordinator address; "
            "set JAX_COORDINATOR_ADDRESS (JAX can auto-detect the process "
            "count from the address on supported runtimes, but not the "
            "reverse)")
    import jax

    # the XLA:CPU client only runs cross-process collectives over a
    # pluggable backend; without this pin a CPU process group initializes
    # fine and then fails at the first psum with "Multiprocess computations
    # aren't implemented on the CPU backend". Best-effort: accelerator
    # platforms ignore it, and jax versions without the knob keep their
    # default.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    pid = process_id if process_id is not None else (
        int(os.environ.get("JAX_PROCESS_ID", "0")))
    kw = dict(coordinator_address=addr, num_processes=nproc, process_id=pid)
    # deliberately NOT jax's initialization_timeout: on expiry the
    # distributed client LOG(FATAL)s — it aborts the whole process with
    # SIGABRT instead of raising, which is exactly the opposite of a
    # recoverable diagnostic

    def _join():
        try:
            jax.distributed.initialize(**kw)
        except RuntimeError as e:  # already initialized
            if "already" not in str(e).lower():
                raise

    if connect_timeout_s is None:
        _join()
        return True
    import threading
    from concurrent.futures import Future

    from structured_light_for_3d_model_replication_tpu.utils import (
        deadline as dl,
    )

    # the join runs on a DAEMON thread so a wedged gloo client can neither
    # hang the caller past the bound nor block interpreter exit (a
    # ThreadPoolExecutor would: its workers are non-daemon and joined at
    # exit, so an unreachable coordinator would wedge shutdown forever).
    # Past the deadline the thread is simply abandoned — it dies with the
    # process.
    fut: Future = Future()

    def _runner():
        if not fut.set_running_or_notify_cancel():
            return
        try:
            _join()
        except BaseException as e:
            fut.set_exception(e)
        else:
            fut.set_result(None)

    threading.Thread(target=_runner, name="sl3d-mh-connect",
                     daemon=True).start()
    try:
        dl.wait_future(
            fut, connect_timeout_s,
            f"multihost connect to {addr} "
            f"(num_processes={nproc}, process_id={pid})")
    except dl.DeadlineExceeded:
        raise dl.DeadlineExceeded(
            f"multihost.initialize: no process group within "
            f"{connect_timeout_s:g}s — coordinator {addr!r} "
            f"(num_processes={nproc}, process_id={pid}) never "
            f"completed the rendezvous. Check that the coordinator "
            f"host is up, the port is reachable, and every peer was "
            f"launched with the same topology.") from None
    return True


def _distributed_active() -> bool:
    """True iff a jax.distributed process group already exists, determined
    WITHOUT initializing any backend (importing jax is backend-init-free;
    only device/process queries trigger init — reading the distributed
    client state does not)."""
    try:
        from jax._src import distributed  # private, but backend-init-free

        return distributed.global_state.client is not None
    except Exception:
        return False


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def global_mesh(n_data: int | None = None, n_model: int | None = None):
    """Mesh over every device of every process (vs make_mesh's local view).
    Axis semantics match parallel/mesh.py: ('data', 'model') = (views,
    pixel-rows)."""
    import jax

    from structured_light_for_3d_model_replication_tpu.parallel.mesh import (
        make_mesh,
    )

    return make_mesh(n_data=n_data, n_model=n_model, devices=jax.devices())


def process_summary() -> dict:
    """Topology facts for logs and failure reports."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.default_backend(),
    }
