"""Lease bookkeeping for the multiprocess coordinator.

The coordinator never *pushes* work or *trusts* workers: an item is GRANTED
under a lease (worker + generation + expiry), the lease is RENEWED by the
worker's heartbeat, and an expired lease is STOLEN — the generation bumps,
so a late ``complete`` from the original holder is recognized and rejected
(the result may still be content-correct and cached; only the *ledger
credit* goes to the new holder). This table is the single source of truth
for who owns what; it is deliberately dumb — no I/O, no sockets, injectable
clock — so every expiry/steal/late-complete rule is unit-testable with a
fake clock.

Invariants:
  - at most one ACTIVE lease per item;
  - ``complete`` is accepted iff (item, worker, generation) all match the
    active lease — anything else is a stale echo;
  - a steal bumps the item's generation forever (generations never reset,
    even across re-grants), so no ABA confusion between steal cycles;
  - ``renew`` touches every lease a worker holds — the heartbeat is
    per-worker, not per-item, so a worker deep in one long item keeps its
    whole grant set alive (the OverlapStats.add hook beats on every stage
    transition, which is far more often than lease_s).

The same grant/renew/expire/steal + monotonic-counter-fencing protocol,
lifted from in-process items to whole PROCESSES, is ``parallel/
election.py`` (ISSUE 14): one leader lease per serving root instead of
one lease per item, the takeover epoch instead of the generation, and
``FencedWrite`` instead of the stale-``complete`` rejection — the
late-echo rule here is literally the fencing rule there.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass

__all__ = ["Lease", "LeaseTable", "LocalityIndex"]


@dataclass
class Lease:
    """One active grant: ``item`` is leased to ``worker`` until
    ``expires_at`` (clock units of the table's injected clock), under
    ``gen`` — the item's steal generation at grant time."""

    item: str
    worker: str
    gen: int
    expires_at: float


class LeaseTable:
    """Thread-safe lease ledger with an injectable monotonic clock."""

    def __init__(self, lease_s: float, clock=time.monotonic):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s!r}")
        self.lease_s = float(lease_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._active: dict[str, Lease] = {}      # item -> lease
        self._gen: dict[str, int] = {}           # item -> generation
        self._steals: dict[str, int] = {}        # item -> steal count

    # ---- grant / renew / complete ---------------------------------------

    def grant(self, item: str, worker: str) -> Lease:
        """Lease ``item`` to ``worker`` at the item's current generation.
        Granting an item with an active lease is a coordinator bug."""
        with self._lock:
            if item in self._active:
                raise RuntimeError(
                    f"item {item!r} already leased to "
                    f"{self._active[item].worker!r}")
            lease = Lease(item=item, worker=worker,
                          gen=self._gen.get(item, 0),
                          expires_at=self._clock() + self.lease_s)
            self._active[item] = lease
            return lease

    def renew(self, worker: str) -> int:
        """Heartbeat: push every lease ``worker`` holds out by a full
        ``lease_s`` from now. Returns how many leases were renewed (0 is
        the worker's signal that everything it held was stolen)."""
        with self._lock:
            now = self._clock()
            n = 0
            for lease in self._active.values():
                if lease.worker == worker:
                    lease.expires_at = now + self.lease_s
                    n += 1
            return n

    def complete(self, item: str, worker: str, gen: int) -> bool:
        """Settle ``item``: True iff the active lease matches (worker,
        gen) exactly — the lease is then released. False means the echo is
        stale (lease stolen, worker dropped, or double-complete); the
        caller must NOT credit it."""
        with self._lock:
            lease = self._active.get(item)
            if lease is None or lease.worker != worker or lease.gen != gen:
                return False
            del self._active[item]
            return True

    # ---- expiry / steal / drop ------------------------------------------

    def expired(self) -> list[Lease]:
        """Every active lease whose expiry has passed (snapshot; stealing
        is the caller's explicit second step so it can journal first)."""
        with self._lock:
            now = self._clock()
            return [lease for lease in self._active.values()
                    if lease.expires_at <= now]

    def steal(self, item: str) -> int:
        """Revoke ``item``'s active lease and bump its generation; returns
        the new generation (the one the next grant will carry). Idempotent
        on an already-stolen item — the generation still bumps, which is
        harmless (monotonic) and keeps the call safe under races between
        the expiry sweep and an observed-dead drop."""
        with self._lock:
            self._active.pop(item, None)
            g = self._gen.get(item, 0) + 1
            self._gen[item] = g
            self._steals[item] = self._steals.get(item, 0) + 1
            return g

    def drop_worker(self, worker: str) -> list[str]:
        """Revoke every lease ``worker`` holds (observed-dead fast path —
        no need to wait out lease_s when the coordinator reaped the
        worker's exit). Bumps each item's generation exactly like a steal.
        Returns the released items, oldest grant first."""
        with self._lock:
            items = [lease.item for lease in self._active.values()
                     if lease.worker == worker]
            for item in items:
                del self._active[item]
                g = self._gen.get(item, 0) + 1
                self._gen[item] = g
                self._steals[item] = self._steals.get(item, 0) + 1
            return items

    # ---- introspection ---------------------------------------------------

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def holder(self, item: str) -> str | None:
        with self._lock:
            lease = self._active.get(item)
            return lease.worker if lease is not None else None

    def steals(self, item: str) -> int:
        """How many times ``item``'s lease has been revoked — the
        coordinator's max_steals circuit breaker reads this."""
        with self._lock:
            return self._steals.get(item, 0)

    def gen(self, item: str) -> int:
        """``item``'s current steal generation (the one the next grant
        will carry) — journaled by the fleet supervisor's observed-dead
        steal so replay sees the same fence the live table enforces."""
        with self._lock:
            return self._gen.get(item, 0)

    def worker_items(self, worker: str) -> list[str]:
        """Items ``worker`` currently holds leases on. The fleet
        supervisor reads this before retiring a worker — a retire with
        zero held leases drains for free; anything held steals away on
        reap exactly like a death."""
        with self._lock:
            return [lease.item for lease in self._active.values()
                    if lease.worker == worker]


class LocalityIndex:
    """Which blob names each worker's L1 already holds, and the grant
    policy that reads it.

    Workers piggyback inventory diffs (names of payloads they just put)
    on their heartbeats/next requests; the coordinator folds them in with
    :meth:`update` and asks :meth:`choose` at grant time. The policy is
    deliberately mild: a *pair* item whose two cleaned-view payloads are
    BOTH in the requesting worker's inventory jumps the FIFO queue
    (locality hit — registration reads straight from L1, zero fabric
    fetches); anything else falls back to plain FIFO order (miss), so a
    cold worker — empty inventory — still gets the front of the queue and
    can never starve. Locality only reorders *which eligible item this
    worker takes first*; it never withholds work, and it is entirely
    orthogonal to the lease/generation machinery above (a stolen pair
    regrants through the same policy at its bumped generation).

    Like :class:`LeaseTable`: no I/O, thread-safe, unit-testable.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._inv: dict[str, set[str]] = {}      # worker -> blob names
        self.hits = 0
        self.misses = 0

    def update(self, worker: str, names) -> None:
        """Fold an inventory diff (iterable of blob names) into
        ``worker``'s holdings. Diffs are additive — content-addressed
        payloads are immutable, so stale entries are impossible; an
        evicted blob just costs one wasted preference."""
        if not names:
            return
        with self._lock:
            self._inv.setdefault(worker, set()).update(names)

    def holds(self, worker: str, name: str) -> bool:
        with self._lock:
            return name in self._inv.get(worker, ())

    def drop_worker(self, worker: str) -> None:
        with self._lock:
            self._inv.pop(worker, None)

    def choose(self, worker: str, candidates) -> tuple[int, bool]:
        """Pick which of ``candidates`` to grant ``worker``.

        ``candidates`` is an ordered list of ``(item_id, needed_names)``
        where ``needed_names`` is the tuple of blob names the item will
        read (``None`` for items with no fabric inputs — view items).
        Returns ``(index, locality_hit)``: the first candidate whose
        every needed name is in ``worker``'s inventory, else index 0
        (FIFO head). Only a candidate with needs counts toward the
        hit/miss counters — granting a view item is not a locality
        decision."""
        with self._lock:
            inv = self._inv.get(worker, set())
            scored = None
            for i, (_item, needs) in enumerate(candidates):
                if needs and all(n in inv for n in needs):
                    scored = i
                    break
            if scored is not None:
                self.hits += 1
                return scored, True
            if candidates and candidates[0][1]:
                self.misses += 1
            return 0, False

    def counters(self) -> dict[str, int]:
        with self._lock:
            return {"locality_hits": self.hits,
                    "locality_misses": self.misses}
