"""Leader election for the multi-gateway serving group (ISSUE 14).

N ``sl3d serve`` gateways share one root; exactly one may own the engine
(admission + lanes + assembler) at a time. The coordination primitive is
the same shape PR 8 proved in-process with :class:`~.lease.LeaseTable`
— time-bounded ownership with a monotonic counter that fences stale
holders — lifted to the filesystem so it survives and spans processes:

  lease file   ``<root>/leader.json`` (schema ``sl3d-leader-v1``): the
               current leader's identity, address, and wall-clock expiry.
               Written atomically (tmp + fsync + rename, the ``io.atomic``
               discipline) so a reader sees either the previous complete
               lease or the new one, never a torn line.
  epoch        a monotonic integer that bumps on every TAKEOVER (never on
               self-renewal). The epoch is the fencing token: every ledger
               append and request record the leader writes is stamped with
               it, and :meth:`LeaderLease.fence` rejects a write the moment
               a newer epoch exists on disk — a deposed leader waking from
               a stall cannot interleave credit into the new leader's
               segment. Replay (:func:`..admission.replay_serving`) applies
               the same rule offline: records carrying an epoch older than
               the newest one seen so far are ignored.
  lock file    ``leader.json.lock``: an flock held only across the
               read-check-write of acquire/renew, so two standbys racing an
               expired lease cannot both bump to the same epoch. flock is
               advisory and per open-file-description, which also makes two
               handles in ONE process (the in-process soak twins) mutually
               exclusive.

Clock discipline: expiry uses WALL time (``time.time``) because monotonic
clocks are not comparable across processes. The clock is injectable for
tests. This targets gateways on one host or a shared POSIX filesystem
with coherent flock (the container / k8s-volume deployment shape); the
fence + replay rule is the safety net that holds even where lease timing
is sloppy.

Chaos sites: ``election.acquire`` / ``election.renew`` fire BEFORE the
flock is taken (a stalled renew must not wedge the standby's takeover —
the stall is exactly how the soak manufactures a zombie leader).
"""
from __future__ import annotations

import fcntl
import json
import os
import time

from structured_light_for_3d_model_replication_tpu.io.atomic import (
    atomic_write,
)
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["LeaderLease", "FencedWrite", "LEADER_SCHEMA"]

LEADER_SCHEMA = "sl3d-leader-v1"


class FencedWrite(RuntimeError):
    """A write stamped with epoch E was refused because the lease file
    already shows epoch > E: the writer was deposed while it wasn't
    looking. The only correct reaction is to self-demote — the scan now
    belongs to the new leader, whose replay restores it from the ledger
    prefix this writer DID get journaled."""


class LeaderLease:
    """One gateway's handle on the shared leader lease.

    ``epoch > 0`` iff this handle currently believes it is the leader;
    :meth:`renew` and :meth:`fence` are where that belief gets corrected
    against disk. All methods are safe to call from any thread; the
    flock'd read-modify-write serializes across processes."""

    def __init__(self, path: str, owner: str, lease_s: float = 5.0,
                 clock=time.time, info: dict | None = None):
        self.path = path
        self.owner = str(owner)
        self.lease_s = float(lease_s)
        self.info = dict(info or {})     # advertised address etc.
        self.epoch = 0                   # our held epoch (0 = not leader)
        self._clock = clock
        self._lock_path = path + ".lock"
        # fence() stat-cache: re-read the lease file only when it changed
        # (one os.stat per append on the hot path, not a read+parse)
        self._seen_stat: tuple | None = None
        self._seen: dict | None = None

    # ---- file plumbing ---------------------------------------------------

    def _read(self) -> dict | None:
        """Parse the lease file; None when absent or torn (a torn lease is
        treated as free — atomic_write makes torn effectively impossible,
        but a hand-damaged file must not wedge the group forever)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                cur = json.load(f)
        except (OSError, ValueError):
            return None
        if cur.get("schema") != LEADER_SCHEMA:
            return None
        return cur

    def _write(self, rec: dict) -> None:
        with atomic_write(self.path) as tmp:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rec, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
        self._seen_stat = None           # our own write invalidates the cache

    class _Flock:
        def __init__(self, lock_path: str):
            self._path = lock_path
            self._f = None

        def __enter__(self):
            self._f = open(self._path, "a")
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
            return self

        def __exit__(self, *exc):
            try:
                fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
            finally:
                self._f.close()
            return False

    def _locked(self) -> "LeaderLease._Flock":
        return LeaderLease._Flock(self._lock_path)

    def _rec(self, epoch: int, now: float) -> dict:
        rec = {"schema": LEADER_SCHEMA, "epoch": int(epoch),
               "owner": self.owner, "pid": os.getpid(),
               "lease_s": self.lease_s,
               "renewed_unix": round(now, 6),
               "expires_unix": round(now + self.lease_s, 6)}
        rec.update(self.info)
        return rec

    # ---- protocol --------------------------------------------------------

    def acquire(self) -> bool:
        """Try to become (or stay) leader. Succeeds when the lease is
        free, expired, or already ours; a takeover from another owner
        bumps the epoch, re-acquiring our own lease keeps it. On success
        ``self.epoch`` holds the fencing token."""
        faults.fire("election.acquire", item=self.owner)
        with self._locked():
            cur = self._read()
            now = self._clock()
            if (cur is not None and cur.get("owner") != self.owner
                    and float(cur.get("expires_unix", 0.0)) > now):
                return False             # live lease held by someone else
            epoch = int(cur.get("epoch", 0)) if cur is not None else 0
            if cur is None or cur.get("owner") != self.owner:
                epoch += 1               # takeover: bump the fencing token
            self._write(self._rec(epoch, now))
            self.epoch = epoch
            return True

    def renew(self) -> bool:
        """Extend our lease. False — and ``epoch`` drops to 0 — when the
        file no longer shows our (owner, epoch): someone stole an expired
        lease while we stalled. The caller must demote; its in-flight
        appends will be fenced regardless (belt and suspenders)."""
        faults.fire("election.renew", item=self.owner)
        with self._locked():
            cur = self._read()
            if (cur is None or cur.get("owner") != self.owner
                    or int(cur.get("epoch", 0)) != self.epoch
                    or self.epoch <= 0):
                self.epoch = 0
                return False
            self._write(self._rec(self.epoch, self._clock()))
            return True

    def release(self) -> None:
        """Voluntary step-down (graceful stop): expire the lease NOW so a
        standby takes over on its next poll instead of waiting out the
        lease. Only touches the file while it is still ours."""
        with self._locked():
            cur = self._read()
            if (cur is not None and cur.get("owner") == self.owner
                    and int(cur.get("epoch", 0)) == self.epoch
                    and self.epoch > 0):
                cur["expires_unix"] = round(self._clock(), 6)
                self._write(cur)
        self.epoch = 0

    def current(self) -> dict | None:
        """The lease file as last written (atomic rename: no lock needed
        to read). None when no leader has ever been elected."""
        return self._read()

    def fence(self) -> None:
        """The write barrier: raise :class:`FencedWrite` when the lease
        file shows an epoch newer than ours. Called before every ledger
        append by the serving layer; one ``os.stat`` per call, the parse
        only re-runs when the file actually changed."""
        try:
            st = os.stat(self.path)
        except OSError:
            return                       # no lease file -> nothing newer
        key = (st.st_mtime_ns, st.st_size, st.st_ino)
        if key != self._seen_stat:
            self._seen = self._read()
            self._seen_stat = key
        cur = self._seen
        if cur is not None and int(cur.get("epoch", 0)) > self.epoch:
            raise FencedWrite(
                f"epoch {self.epoch} fenced by epoch "
                f"{int(cur.get('epoch', 0))} "
                f"(leader {cur.get('owner')!r})")

    def superseded(self) -> bool:
        """Non-raising :meth:`fence`: True when a newer epoch exists on
        disk. The fleet supervisor polls this at the top of every tick so
        a deposed leader's autoscaler stops DECIDING (spawn/retire are
        side effects no fence on the ledger append can un-run) the moment
        the takeover lands, not just when its next journal write fails."""
        try:
            self.fence()
        except FencedWrite:
            return True
        return False
