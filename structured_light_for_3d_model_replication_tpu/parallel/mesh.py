"""Device-mesh construction and axis conventions.

The reference is single-node (SURVEY.md section 2 checklist: no DP/TP/SP and no
collective backend); parallel scale-out is new capability in this framework.
Axis conventions used everywhere:

  - ``"data"``  — data parallelism over turntable *views* (the reference's
    per-folder batch loop, processing.py:314-334, becomes this axis)
  - ``"model"`` — spatial parallelism over *pixel rows* within a view (decode,
    triangulation) and over *point blocks* (cloud ops); the long-sequence axis
    analog, so ring/all-to-all style exchanges live on it

Cross-chip communication is XLA collectives (psum / all_gather / ppermute)
over ICI; nothing here assumes a particular topology beyond a 2D mesh.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["AXIS_DATA", "AXIS_MODEL", "make_mesh", "merge_mesh",
           "views_mesh", "view_sharding", "batch_sharding", "P"]

AXIS_DATA = "data"
AXIS_MODEL = "model"


def make_mesh(n_data: int | None = None, n_model: int | None = None,
              devices=None) -> Mesh:
    """Build a (data, model) mesh.

    Defaults: use every available device, favoring the data (views) axis —
    views are embarrassingly parallel, so they absorb chips first; pass
    ``n_model > 1`` to also split pixel rows / point blocks within a view.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n_data is None and n_model is None:
        n_data, n_model = n, 1
    elif n_data is None:
        if n % n_model:
            raise ValueError(f"{n} devices not divisible by n_model={n_model}")
        n_data = n // n_model
    elif n_model is None:
        if n % n_data:
            raise ValueError(f"{n} devices not divisible by n_data={n_data}")
        n_model = n // n_data
    if n_data * n_model > n:
        raise ValueError(f"mesh {n_data}x{n_model} needs more than {n} devices")
    grid = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(grid, (AXIS_DATA, AXIS_MODEL))


def merge_mesh(parallel_cfg=None) -> Mesh | None:
    """The mesh for the sharded 360 merge, resolved in ONE place so every
    merge_360 call site (CLI stage, warmup's cache priming, embedders)
    compiles the same program: a full-device make_mesh() when
    ``parallel.merge_mesh`` is on and >1 device is attached, else None
    (single-device hosts and the default config are unaffected)."""
    if parallel_cfg is not None and not getattr(parallel_cfg, "merge_mesh",
                                                False):
        return None
    if len(jax.devices()) < 2:
        return None
    return make_mesh()


def view_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [V, F, H, W] view-batch: views over data, rows over model."""
    return NamedSharding(mesh, P(AXIS_DATA, None, AXIS_MODEL, None))


def views_mesh(parallel_cfg=None) -> Mesh | None:
    """The mesh for view-batched reconstruct, resolved in ONE place (the
    merge_mesh pattern) so the batch executor, warmup's cache priming, and
    bench compile the same sharded program: a full-device make_mesh() when
    ``parallel.shard_views`` is on and >1 device is attached, else None —
    single-device hosts and the numpy backend run the unsharded lane."""
    if parallel_cfg is not None and not getattr(parallel_cfg, "shard_views",
                                                True):
        return None
    if len(jax.devices()) < 2:
        return None
    return make_mesh()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the batch executor's [V, F, H, W] bucket: the view axis
    data-major over EVERY mesh axis (matching _sharded_views_fn's in_specs),
    so the host->device transfer lands each shard on its device directly
    instead of uploading to one chip and resharding."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))
