"""Host-level fault domains: the preemption-tolerant multiprocess coordinator.

``run_coordinated`` splits one scan into leased work items (per-view
reconstruct+clean, per-pair registration — the same bucket-laddered view
programs and chain-position pair ids the fused pipeline uses), grants them
to N local worker processes over a loopback lease protocol, and steals back
expired leases so a killed / preempted / wedged / partitioned worker costs
only its in-flight items. Workers are *cache warmers*: every result lands in
the content-addressed StageCache under the exact key the single-process
pipeline would use (``stages._view_plan`` is shared), so the final assembly
pass IS a plain single-process ``run_pipeline`` over the warmed cache —
coordinated output is byte-identical to a single-process run by
construction, a lost item merely recomputes in assembly, and DEGRADED
completion means exactly what it means single-process (assembly owns the
``pipeline.min_views`` floor and every abort/degrade decision).

Crash safety: every grant / complete / steal / failed / lost is journaled
to an append-only JSONL ledger (``ledger.jsonl``, tmp-free line appends +
fsync — the trace-journal discipline). A coordinator that crashes mid-run
resumes from the stage cache plus the ledger with ZERO recompute of
completed items: replay unions completed ids across segments, and the
cache already holds their bytes.

Lease protocol (see parallel/lease.py for the bookkeeping invariants):

  worker                         coordinator
    | -- hello {worker, pid} -->  | registers, returns lease_s/heartbeat_s
    | -- next {worker} -------->  | journal grant, lease item, send spec
    | ... computes; OverlapStats.add's heartbeat hook sends ...
    | -- beat {worker} -------->  | renews ALL the worker's leases
    | -- complete {item, gen} ->  | journal + settle iff (worker, gen)
    |                             |   still hold the lease, else "stolen"
    | -- failed {item, ...} --->  | journal; item recomputes in assembly
    | <- shutdown --------------  | when every item is settled

A worker that misses ``lease_s`` of heartbeats has its items stolen
(generation bump) and re-granted to survivors; an item stolen more than
``coordinator.max_steals`` times is declared LOST and left to assembly.

**Pod fabric** (``coordinator.listen`` non-empty): the same protocol over a
real TCP endpoint instead of an ephemeral loopback port, so ``sl3d worker``
processes on OTHER hosts can join. What changes:

  - the server binds ``coordinator.listen`` (netutil endpoint grammar,
    IPv6-safe) and, when ``coordinator.secret`` is set, requires a matching
    ``hello`` as a connection's first request (anything else answers
    ``{"error": "unauthorized"}``);
  - a content-addressed blob service (pipeline/blobstore.py) co-hosts next
    to the coordinator, backed by the SAME cache directory assembly reads —
    spawned workers get private L1 roots and the fabric is their L2, so the
    cache-warmer parity construction carries over to hosts that do not
    share a disk (a missing payload is a recompute, never a wrong byte);
  - ``hello``/``next``/``beat`` carry inventory diffs (which blob names the
    worker's L1 holds) into a :class:`~.lease.LocalityIndex`, and pair
    grants prefer the worker already holding BOTH cleaned-view payloads
    (``locality: hit`` on the grant event; plain FIFO fallback means a
    cold worker never starves).
"""
from __future__ import annotations

import copy
import json
import os
import socket
import subprocess
import sys
import threading
import time

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.utils import deadline as dl
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import telemetry as tel

__all__ = ["Ledger", "run_coordinated", "LEDGER_SCHEMA"]

LEDGER_SCHEMA = "sl3d-ledger-v1"

# item lifecycle: pending -> granted -> completed | failed | lost
# (failed/lost items are NOT errors at run scope — assembly recomputes them)
_SETTLED = ("completed", "failed", "lost")


class Ledger:
    """Append-only, crash-safe work ledger (one JSONL line per event).

    Segment discipline mirrors the trace journal: every coordinator start
    appends a ``meta`` head line, so one file accumulates segments across
    crashes and replay can attribute events to attempts. Events are
    line-buffered and fsynced — a torn final line (kill -9 mid-write) is
    tolerated by replay, never repaired in place."""

    def __init__(self, path: str, run_id: str, meta: dict | None = None,
                 epoch=None, fence=None):
        # HA serving (ISSUE 14): ``epoch`` is a callable returning the
        # writer's current fencing token — every line gets stamped with
        # it; ``fence`` is called before each append and raises (e.g.
        # election.FencedWrite) to REJECT the write of a deposed leader.
        # Both default off: solo pipelines and the PR-8 coordinator pay
        # nothing.
        self.path = path
        self._lock = threading.Lock()
        self._epoch = epoch
        self._fence = fence
        self._f = open(path, "a", encoding="utf-8")
        head = {"type": "meta", "schema": LEDGER_SCHEMA, "run_id": run_id,
                "t0_unix": time.time()}
        if epoch is not None:
            head["epoch"] = int(epoch())
        head.update(meta or {})
        self._append(head)

    def _append(self, rec: dict) -> None:
        with self._lock:
            self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def event(self, type_: str, **fields) -> None:
        # chaos hook BEFORE the append: a crash here loses the event (the
        # torn-tail / lost-line case replay must tolerate), a transient
        # here surfaces to the caller exactly like a full-disk write
        faults.fire("ledger.append", item=type_)
        if self._fence is not None:
            self._fence()
        rec = {"type": type_, "t": round(time.time(), 6)}
        if self._epoch is not None:
            rec["epoch"] = int(self._epoch())
        rec.update(fields)
        self._append(rec)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            finally:
                self._f.close()

    @staticmethod
    def replay(path: str) -> dict:
        """Fold a ledger back into resume state: the union of completed
        item ids across every segment (a completed item never un-completes
        — its bytes are in the stage cache), plus segment/event counts for
        reporting. Unparseable lines (the torn tail) are skipped."""
        completed: set[str] = set()
        segments = 0
        events = 0
        if not os.path.exists(path):
            return {"completed": completed, "segments": 0, "events": 0}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # torn tail from a crash mid-append
                t = rec.get("type")
                if t == "meta":
                    if rec.get("schema") != LEDGER_SCHEMA:
                        raise ValueError(
                            f"ledger {path}: unknown schema "
                            f"{rec.get('schema')!r} (want {LEDGER_SCHEMA})")
                    segments += 1
                    continue
                events += 1
                if t == "complete":
                    completed.add(rec["item"])
        return {"completed": completed, "segments": segments,
                "events": events}


class _Item:
    __slots__ = ("id", "kind", "spec", "state", "deps", "worker")

    def __init__(self, id: str, kind: str, spec: dict,
                 deps: tuple[str, ...] = ()):
        self.id = id
        self.kind = kind
        self.spec = spec
        self.state = "pending"
        self.deps = deps
        self.worker: str | None = None


class _Coordinator:
    """Shared state between the socket server threads and the poll loop."""

    def __init__(self, cfg: Config, items: list[_Item], ledger: Ledger,
                 run_id: str, view_done: set[str], log):
        from structured_light_for_3d_model_replication_tpu.parallel.lease import (
            LeaseTable,
            LocalityIndex,
        )

        self.cfg = cfg
        self.items = {it.id: it for it in items}
        self.order = [it.id for it in items]
        self.ledger = ledger
        self.run_id = run_id
        self.view_done = view_done      # settled-successfully view item ids
        self.log = log
        self.leases = LeaseTable(cfg.coordinator.lease_s)
        self.lock = threading.Lock()
        self.done = threading.Event()
        self.crash: BaseException | None = None   # injected coord crash
        self.workers_seen: dict[str, int] = {}    # worker -> pid
        self.worker_addrs: dict[str, str] = {}    # worker -> advertised addr
        self.completed_by: dict[str, int] = {}
        self.steal_count = 0
        self.late_completes = 0
        # fabric mode only: inventory-driven grant preference. Off-fabric
        # (shared disk) the index stays None and grants are exactly the
        # PR-8 FIFO — zero behavior drift for `--workers` without listen
        self.locality = (LocalityIndex() if cfg.coordinator.listen
                         else None)
        self.blob_endpoint = ""         # set by run_coordinated in fabric mode
        # incremental assembly lane (merge.incremental): fed every
        # successfully settled item id; None when the knob is off
        self.assembler = None

    # ---- queue logic (call under self.lock) ------------------------------

    def _pair_needs(self, it: _Item) -> tuple[str, ...] | None:
        """The blob names a pair item reads (its endpoints' cleaned-view
        payloads) — what locality scoring matches against inventories."""
        if it.kind != "pair":
            return None
        return (f"view-{it.spec['key_dst'][:16]}",
                f"view-{it.spec['key_src'][:16]}")

    def _grantable(self, worker: str | None = None) \
            -> tuple[_Item | None, str | None]:
        """First grantable item for ``worker`` plus the locality verdict
        ("hit"/"miss"/None). Without a locality index (off-fabric) this is
        plain FIFO over dep-ready pending items."""
        cands: list[_Item] = []
        for iid in self.order:
            it = self.items[iid]
            if it.state != "pending":
                continue
            if all(d in self.view_done for d in it.deps):
                if self.locality is None or worker is None:
                    return it, None
                cands.append(it)
        if not cands:
            return None, None
        idx, hit = self.locality.choose(
            worker, [(it.id, self._pair_needs(it)) for it in cands])
        chosen = cands[idx]
        if chosen.kind != "pair":
            return chosen, None
        return chosen, ("hit" if hit else "miss")

    def _dep_blocked_forever(self, it: _Item) -> bool:
        """A pending pair whose endpoint view FAILED or was LOST can never
        have its deps met — assembly will recompute the whole chain link."""
        for d in it.deps:
            dep = self.items.get(d)
            if dep is not None and dep.state in ("failed", "lost"):
                return True
        return False

    def unsettled(self) -> int:
        with self.lock:
            return sum(1 for it in self.items.values()
                       if it.state not in _SETTLED)

    def _check_done(self) -> None:
        if all(it.state in _SETTLED for it in self.items.values()):
            self.done.set()

    # ---- protocol ops (any server thread) --------------------------------

    def _fold_inventory(self, req: dict) -> None:
        """Inventory diffs piggyback on hello/next/beat; additive, so a
        replayed or reordered diff is harmless."""
        if self.locality is not None:
            inv = req.get("inventory")
            if inv:
                self.locality.update(req["worker"], inv)

    def op_hello(self, req: dict) -> dict:
        w = req["worker"]
        with self.lock:
            self.workers_seen[w] = int(req.get("pid", 0))
            if req.get("addr"):
                self.worker_addrs[w] = str(req["addr"])
        self._fold_inventory(req)
        c = self.cfg.coordinator
        out = {"ok": True, "run_id": self.run_id,
               "lease_s": c.lease_s, "heartbeat_s": c.heartbeat_s}
        if self.blob_endpoint:
            out["blob"] = self.blob_endpoint
        return out

    def op_next(self, req: dict) -> dict:
        w = req["worker"]
        self.leases.renew(w)
        self._fold_inventory(req)
        if self.done.is_set():
            return {"shutdown": True}
        with self.lock:
            it, loc = self._grantable(w)
            if it is None:
                # settle dep-dead pairs while we are here, so the run
                # drains instead of idling on unreachable work
                for iid in self.order:
                    cand = self.items[iid]
                    if (cand.state == "pending"
                            and self._dep_blocked_forever(cand)):
                        cand.state = "lost"
                        self.ledger.event("lost", item=cand.id,
                                          reason="dep-failed")
                self._check_done()
                if self.done.is_set():
                    return {"shutdown": True}
                return {"wait": max(0.05, self.cfg.coordinator.heartbeat_s
                                    / 4.0)}
            # injected coordinator-crash site: fires BEFORE the grant is
            # journaled, so resume sees a clean prefix (the crash-safety
            # contract is about completed work, never in-flight grants)
            faults.fire("coord.grant", item=f"{w}:{it.id}")
            lease = self.leases.grant(it.id, w)
            it.state = "granted"
            it.worker = w
            ev = {"item": it.id, "worker": w, "gen": lease.gen}
            if loc is not None:
                ev["locality"] = loc
            self.ledger.event("grant", **ev)
        return {"grant": {"id": it.id, "gen": lease.gen, "kind": it.kind,
                          "spec": it.spec}}

    def op_beat(self, req: dict) -> dict:
        self._fold_inventory(req)
        return {"ok": self.leases.renew(req["worker"])}

    def op_complete(self, req: dict) -> dict:
        w, iid, gen = req["worker"], req["item"], int(req["gen"])
        accepted = self.leases.complete(iid, w, gen)
        with self.lock:
            it = self.items.get(iid)
            if accepted and it is not None:
                it.state = "completed"
                if it.kind == "view":
                    self.view_done.add(iid)
                self.completed_by[w] = self.completed_by.get(w, 0) + 1
                self.ledger.event("complete", item=iid, worker=w, gen=gen)
                if self.assembler is not None:
                    # enqueue-only (the fold runs on the assembler's own
                    # worker) — never blocks the server thread
                    self.assembler.note_item(iid)
                self._check_done()
                return {"ok": "accepted"}
            # stale echo after a steal: the RESULT may still be perfectly
            # good (content-addressed cache put), only the credit is void
            self.late_completes += 1
            self.ledger.event("late-complete", item=iid, worker=w, gen=gen)
            return {"ok": "stolen"}

    def op_failed(self, req: dict) -> dict:
        w, iid = req["worker"], req["item"]
        self.leases.complete(iid, w, int(req.get("gen", 0)))
        with self.lock:
            it = self.items.get(iid)
            if it is not None and it.state not in _SETTLED:
                it.state = "failed"
                self.ledger.event("failed", item=iid, worker=w,
                                  error=str(req.get("error", ""))[:500],
                                  error_type=req.get("error_type", ""),
                                  transient=bool(req.get("transient")))
                self.log(f"[coord] item {iid} FAILED on {w} "
                         f"({req.get('error_type')}); assembly will "
                         f"recompute it")
                self._check_done()
        return {"ok": True}

    # ---- expiry / dead-worker sweeps (poll loop) -------------------------

    def _revoke(self, iid: str, why: str) -> None:
        """Under self.lock: return a stolen/dropped item to the queue, or
        declare it lost past the steal budget."""
        gen = self.leases.steal(iid)
        self.steal_count += 1
        it = self.items[iid]
        self.ledger.event("steal", item=iid, worker=it.worker, gen=gen,
                          reason=why)
        if self.leases.steals(iid) > self.cfg.coordinator.max_steals:
            it.state = "lost"
            self.ledger.event("lost", item=iid, reason="max-steals")
            self.log(f"[coord] item {iid} exceeded max_steals="
                     f"{self.cfg.coordinator.max_steals} — LOST "
                     f"(assembly recomputes it)")
        else:
            it.state = "pending"
            it.worker = None

    def sweep_expired(self) -> None:
        for lease in self.leases.expired():
            with self.lock:
                if self.items[lease.item].state != "granted":
                    continue
                self.log(f"[coord] lease on {lease.item} (held by "
                         f"{lease.worker}) expired — stealing")
                self._revoke(lease.item, "lease-expired")
                self._check_done()

    def drop_worker(self, worker: str, why: str) -> None:
        if self.locality is not None:
            self.locality.drop_worker(worker)
        items = self.leases.drop_worker(worker)
        with self.lock:
            for iid in items:
                if self.items[iid].state != "granted":
                    continue
                self.steal_count += 1
                # generation == lifetime steal count (bumps only on
                # revocation), and drop_worker already bumped it
                gen = self.leases.steals(iid)
                it = self.items[iid]
                self.ledger.event("steal", item=iid, worker=worker,
                                  gen=gen, reason=why)
                if gen > self.cfg.coordinator.max_steals:
                    it.state = "lost"
                    self.ledger.event("lost", item=iid,
                                      reason="max-steals")
                else:
                    it.state = "pending"
                    it.worker = None
            self._check_done()
        if items:
            self.log(f"[coord] reclaimed {len(items)} item(s) from "
                     f"{worker} ({why})")


class _Server:
    """Newline-JSON lease server; one daemon thread per worker connection.
    Binds loopback + an ephemeral port by default (the PR-8 shape);
    ``coordinator.listen`` rebinds it to a real endpoint so remote
    ``sl3d worker`` processes can dial in, and ``coordinator.secret``
    gates every connection behind a matching first-``hello``. Injected
    coordinator crashes raised in a handler are STORED (the socket thread
    must not die silently) and re-raised by the poll loop — the
    coordinator process then actually crashes."""

    def __init__(self, coord: _Coordinator, port: int, log,
                 listen: str = "", secret: str = ""):
        self.coord = coord
        self.log = log
        self.secret = secret
        host, bind_port = netutil.parse_endpoint(listen, default_port=port)
        self._sock = socket.create_server((host, bind_port))
        self._sock.settimeout(0.2)
        self.host = self._sock.getsockname()[0]
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="sl3d-coord-accept", daemon=True)
        self._accept_thread.start()

    @property
    def endpoint(self) -> str:
        return netutil.format_endpoint(self.host, self.port)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 name="sl3d-coord-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        ops = {"hello": self.coord.op_hello, "next": self.coord.op_next,
               "beat": self.coord.op_beat, "complete": self.coord.op_complete,
               "failed": self.coord.op_failed}
        # per-connection auth: with a secret set, the FIRST request must
        # be a hello presenting it; until then every op answers
        # unauthorized and the connection closes (fail-closed — an
        # unauthenticated peer learns nothing about the run)
        authed = not self.secret
        try:
            with conn, conn.makefile("rw", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        if not authed:
                            if (req.get("op") != "hello"
                                    or req.get("secret") != self.secret):
                                f.write(json.dumps(
                                    {"error": "unauthorized"}) + "\n")
                                f.flush()
                                return
                            authed = True
                        resp = ops[req["op"]](req)
                    except faults.InjectedCrash as e:
                        # surface on the poll loop; tell the worker to
                        # idle so it doesn't spin on a dying coordinator
                        self.coord.crash = e
                        self.coord.done.set()
                        resp = {"wait": 0.5}
                    except Exception as e:
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    f.write(json.dumps(resp) + "\n")
                    f.flush()
        except (OSError, ValueError):
            pass    # worker vanished mid-exchange; lease expiry covers it

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# run_coordinated
# ---------------------------------------------------------------------------


def _build_items(cfg: Config, sources: list[str], view_keys: list[str],
                 cache, completed: set[str]) -> tuple[list[_Item], set[str]]:
    """The work ledger: one view item per cache-miss view, one pair item
    per chain link when the streamed register lane would run. ``completed``
    (ledger replay) and cache hits both exclude items — zero recompute on
    resume."""
    items: list[_Item] = []
    view_done: set[str] = set()
    for i, src in enumerate(sources):
        iid = f"view:{i}"
        if iid in completed or cache.get("view", view_keys[i]) is not None:
            view_done.add(iid)
            continue
        items.append(_Item(iid, "view",
                           {"index": i, "src": src, "key": view_keys[i]}))
    streamed = cfg.merge.stream and cfg.merge.method != "posegraph"
    if streamed:
        for i in range(len(sources) - 1):
            iid = f"pair:{i}"
            if iid in completed:
                continue
            # pair caching is digest-keyed on the endpoint OUTPUTS, so the
            # worker resolves the key itself once both views are in cache
            items.append(_Item(
                iid, "pair",
                {"pid": i, "dst": i, "src": i + 1,
                 "key_dst": view_keys[i], "key_src": view_keys[i + 1]},
                deps=(f"view:{i}", f"view:{i + 1}")))
    return items, view_done


def _spawn_worker(rank: int, n: int, port: int, spec_dir: str,
                  cfg_path: str, calib_path: str, target: str, out_dir: str,
                  steps: tuple[str, ...],
                  fabric: dict | None = None, name: str | None = None,
                  generation: int = 0,
                  cache_root: str | None = None) -> subprocess.Popen:
    wname = name or f"w{rank}"
    spec = {"config": cfg_path, "calib": calib_path, "target": target,
            "out": out_dir, "steps": list(steps), "port": port,
            "worker": wname, "num_workers": n}
    if generation:
        # fleet respawn stamp (ISSUE 18): the rank's lease identity is
        # reused, the generation tells report/soak THIS incarnation from
        # the one the supervisor reaped
        spec["generation"] = int(generation)
    if fabric:
        # networked mode: dial the real endpoint, authenticate, use the
        # blob fabric as L2 — and warm a PRIVATE L1 root, so each spawned
        # worker honestly simulates a host with its own disk (fabric
        # traffic, dedup, and locality are real and measurable on one box)
        spec.update(fabric)
        spec["cache_root"] = os.path.join(out_dir,
                                          f".slscan-cache.{wname}")
    if cache_root:
        # fleet loopback mode: warm the SERVING store directly (the
        # shared-disk construction — byte parity is the PR-8 argument)
        spec["cache_root"] = cache_root
    spec_path = os.path.join(spec_dir, f"worker{rank}.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f, indent=2)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] + [env.get("PYTHONPATH", "")]).rstrip(
            os.pathsep)
    log_path = os.path.join(spec_dir, f"worker{rank}.log")
    logf = open(log_path, "ab")
    pkg = "structured_light_for_3d_model_replication_tpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", f"{pkg}.cli", "worker", "--spec", spec_path],
        stdout=logf, stderr=subprocess.STDOUT, env=env)
    logf.close()     # the child holds its own fd
    return proc


def run_coordinated(calib_path: str, target: str, out_dir: str,
                    cfg: Config, steps: tuple[str, ...],
                    merged_name: str = "merged.ply",
                    stl_name: str = "model.stl", log=print):
    """Coordinate one scan across ``cfg.coordinator.workers`` local worker
    processes, then assemble the final artifacts with a single-process
    ``run_pipeline`` pass over the warmed stage cache (workers=0 — no
    recursion). Returns that pass's PipelineReport with a ``coordinator``
    summary dict attached."""
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        stages,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        StageCache,
    )

    n = int(cfg.coordinator.workers)
    t0 = time.monotonic()
    os.makedirs(out_dir, exist_ok=True)
    run_id = tel.new_run_id()
    budget = dl.Deadline.after(cfg.pipeline.run_budget_s,
                               "coordinated run")
    cache = StageCache(os.path.join(out_dir, ".slscan-cache"),
                       enabled=True, log=log,
                       verify=cfg.pipeline.verify_cache)
    if not cfg.pipeline.cache:
        # workers hand results over THROUGH the cache; a cache-off
        # coordinated run would compute everything twice for nothing
        log("[coord] NOTICE: pipeline.cache is off but coordinated mode "
            "requires the stage cache as the result channel — enabling it "
            "for this run")
        cfg = copy.deepcopy(cfg)
        cfg.pipeline.cache = True
    steps = tuple(steps)
    calib, sources, _view_cfg, view_keys = stages._view_plan(
        calib_path, target, cfg, steps, cache, log)

    ledger_path = os.path.join(out_dir, "ledger.jsonl")
    resume = Ledger.replay(ledger_path)
    if resume["completed"]:
        log(f"[coord] resume: ledger already credits "
            f"{len(resume['completed'])} completed item(s) across "
            f"{resume['segments']} segment(s) — zero recompute for those")
    items, view_done = _build_items(cfg, sources, view_keys, cache,
                                    resume["completed"])
    ledger = Ledger(ledger_path, run_id,
                    meta={"workers": n, "items": len(items),
                          "views": len(sources),
                          "resumed_completed": len(resume["completed"])})
    coord = _Coordinator(cfg, items, ledger, run_id, view_done, log)
    info = {"workers": n, "items_total": len(items),
            "resumed_completed": len(resume["completed"]),
            "ledger": ledger_path}

    if not items:
        log("[coord] nothing to lease (cache + ledger cover every item); "
            "going straight to assembly")
        ledger.close()
        return _assemble(calib_path, target, out_dir, cfg, steps,
                         merged_name, stl_name, log, coord, info, t0,
                         settled_unix=time.time())

    assembler = None
    if (cfg.merge.incremental and cfg.merge.stream
            and cfg.merge.method != "posegraph"):
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            assembly,
        )

        assembler = assembly.IncrementalAssembler(cfg, view_keys, cache,
                                                  log=log)
        coord.assembler = assembler
        # pre-settled work (ledger resume + cache-hit views) folds now, so
        # the lane starts from the same state a fresh observer would see
        pre = sorted(set(view_done) | set(resume["completed"]))
        for iid in pre:
            assembler.note_item(iid)
        log(f"[coord] incremental assembly lane up "
            f"({len(pre)} pre-settled item(s) fed)")

    fabric = bool(cfg.coordinator.listen)
    server = _Server(coord, cfg.coordinator.port, log,
                     listen=cfg.coordinator.listen,
                     secret=cfg.coordinator.secret)
    blob = None
    if fabric:
        from structured_light_for_3d_model_replication_tpu.pipeline.blobstore import (
            BlobServer,
        )

        blob = BlobServer(cache.root, host=server.host, port=0,
                          secret=cfg.coordinator.secret, log=log,
                          on_blob=(assembler.note_blob
                                   if assembler is not None else None))
        coord.blob_endpoint = blob.endpoint
    log(f"[coord] run {run_id}: {len(items)} item(s) "
        f"({sum(1 for i in items if i.kind == 'view')} view, "
        f"{sum(1 for i in items if i.kind == 'pair')} pair) across "
        f"{n} worker(s); lease {cfg.coordinator.lease_s:g}s, "
        + (f"listening on {server.endpoint} (blob {blob.endpoint}), "
           if fabric else f"port {server.port}, ")
        + f"ledger -> {ledger_path}")

    spec_dir = os.path.join(out_dir, ".coord")
    os.makedirs(spec_dir, exist_ok=True)
    wcfg = copy.deepcopy(cfg)
    wcfg.coordinator.workers = 0
    cfg_path = os.path.join(spec_dir, "cfg.json")
    wcfg.save(cfg_path)
    fabric_spec = None
    if fabric:
        fabric_spec = {"connect": server.endpoint,
                       "secret": cfg.coordinator.secret,
                       "blob": blob.endpoint}
        # the two-terminal walkthrough: `sl3d worker --spec
        # <out>/.coord/join.json` joins this run from another shell (or,
        # with listen on a routable address, another machine — copy the
        # spec and adjust the paths it names)
        join = {"config": cfg_path, "calib": calib_path, "target": target,
                "out": out_dir, "steps": list(steps), "port": server.port,
                "worker": "ext0", "num_workers": n, **fabric_spec,
                "cache_root": os.path.join(out_dir, ".slscan-cache.ext0")}
        with open(os.path.join(spec_dir, "join.json"), "w") as f:
            json.dump(join, f, indent=2)
    procs: dict[str, subprocess.Popen] = {}
    t_settled = None
    try:
        for r in range(n):
            procs[f"w{r}"] = _spawn_worker(
                r, n, server.port, spec_dir, cfg_path, calib_path, target,
                out_dir, steps, fabric=fabric_spec)
        poll_s = max(0.05, min(0.5, cfg.coordinator.heartbeat_s / 4.0))
        reaped: set[str] = set()
        while not coord.done.is_set():
            if coord.crash is not None:
                raise coord.crash
            if budget is not None and budget.remaining() <= 0:
                raise dl.DeadlineExceeded(
                    f"coordinated run still has {coord.unsettled()} "
                    f"unsettled item(s) past the "
                    f"pipeline.run_budget_s={cfg.pipeline.run_budget_s:g}s "
                    f"budget")
            coord.sweep_expired()
            alive = 0
            for w, p in procs.items():
                rc = p.poll()
                if rc is None:
                    alive += 1
                elif w not in reaped:
                    reaped.add(w)
                    log(f"[coord] worker {w} (pid {p.pid}) exited rc={rc} "
                        f"with work unsettled — reclaiming its leases")
                    coord.drop_worker(w, f"worker-exit rc={rc}")
            # fabric runs may be fed by EXTERNAL workers the coordinator
            # never spawned (joined via coordinator.listen) — with one
            # seen, or with none spawned at all (n=0 waits for joins),
            # zero live children does not mean zero workers; lease expiry
            # + max_steals + the run budget still bound the run
            externals = any(w not in procs for w in coord.workers_seen)
            if (alive == 0 and not coord.done.is_set()
                    and not (fabric and (n == 0 or externals))):
                # no survivors: whatever is left can never be granted
                with coord.lock:
                    for iid in coord.order:
                        it = coord.items[iid]
                        if it.state not in _SETTLED:
                            it.state = "lost"
                            ledger.event("lost", item=iid,
                                         reason="no-workers")
                    coord._check_done()
                log("[coord] every worker is gone; remaining items marked "
                    "LOST — assembly recomputes them")
            coord.done.wait(poll_s)
        if coord.crash is not None:
            raise coord.crash
        t_settled = time.time()   # last item settled: the tail anchor
    except Exception as e:
        # abort contract: a run that dies during coordination must be
        # diagnosable from disk. InjectedCrash is a BaseException and
        # deliberately bypasses this — crash-safety (ledger + cache)
        # covers it instead.
        mpath = os.path.join(out_dir, tel.host_scoped("failures.json"))
        stages._write_json_atomic(mpath, {
            "run_id": run_id, "aborted": True, "degraded": False,
            "reason": str(e),
            "run_budget_s": cfg.pipeline.run_budget_s,
            "failures": [faults.FailureRecord.from_exception(
                "coordinator", "run", e).as_dict()],
        })
        log(f"[coord] ABORTED ({type(e).__name__}: {e}); "
            f"manifest -> {mpath}")
        raise
    finally:
        # bounded, idempotent teardown: survivors get shutdown on their
        # next poll; stragglers are terminated, then killed
        coord.done.set()
        deadline = dl.Deadline.after(
            max(2.0, 2 * cfg.coordinator.heartbeat_s), "worker drain")
        for p in procs.values():
            while p.poll() is None and deadline.remaining() > 0:
                time.sleep(0.05)
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        if blob is not None:
            blob.close()
        server.close()
        if assembler is not None:
            assembler.close()
        ledger.close()

    with coord.lock:
        states = {}
        for it in coord.items.values():
            states[it.state] = states.get(it.state, 0) + 1
    info.update({
        "completed_by_worker": dict(coord.completed_by),
        "steals": coord.steal_count,
        "late_completes": coord.late_completes,
        "item_states": states,
        "coordination_wall_s": round(time.monotonic() - t0, 3),
    })
    if coord.worker_addrs:
        info["worker_addrs"] = dict(coord.worker_addrs)
    if fabric:
        info["listen"] = server.endpoint
        info["fabric"] = blob.counters() if blob is not None else {}
        if coord.locality is not None:
            info.update(coord.locality.counters())
    lost = states.get("lost", 0) + states.get("failed", 0)
    prefold = None
    if assembler is not None:
        prefold = assembler.prefold(t_settled if t_settled is not None
                                    else time.time())
        info["assembly_lane"] = {"folded_views": prefold.offered_views,
                                 "folded_pairs": len(prefold.T_pairs)}
        log(f"[coord] assembly lane folded {prefold.offered_views}/"
            f"{len(sources)} view(s) before the last item settled")
    log(f"[coord] coordination done in {info['coordination_wall_s']:.2f}s: "
        f"{states} (steals={coord.steal_count}); "
        + (f"{lost} item(s) fall to assembly recompute; " if lost else "")
        + "assembling final artifacts single-process")
    return _assemble(calib_path, target, out_dir, cfg, steps, merged_name,
                     stl_name, log, coord, info, t0, prefold=prefold,
                     settled_unix=t_settled)


def _assemble(calib_path, target, out_dir, cfg, steps, merged_name,
              stl_name, log, coord, info, t0, prefold=None,
              settled_unix=None):
    """The assembly pass: the proven single-process pipeline over the
    warmed cache. Every floor/degrade/abort rule runs HERE, on exactly the
    state a clean run on the survivors would see — which is the
    degraded ≡ clean-run-on-survivors byte-identity argument. A
    ``prefold`` (incremental assembly lane) only SEEDS the accumulate with
    already-validated state; everything it carries is re-validated against
    this pass's own order/digests/transforms before use."""
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        stages,
    )

    acfg = copy.deepcopy(cfg)
    acfg.coordinator.workers = 0
    acfg.coordinator.listen = ""     # fabric is torn down; plain run now
    acfg.pipeline.cache = True
    report = stages.run_pipeline(calib_path, target, out_dir, cfg=acfg,
                                 steps=steps, merged_name=merged_name,
                                 stl_name=stl_name, log=log,
                                 prefold=prefold)
    info["total_wall_s"] = round(time.monotonic() - t0, 3)
    anchor = (prefold.settled_unix if prefold is not None
              else settled_unix)
    asm = {"enabled": prefold is not None}
    if anchor is not None:
        # wall from last-item-settled to artifacts-on-disk: the quantity
        # bench.py --assembly-only certifies ≈ postprocess-only
        asm["tail_s"] = round(time.time() - anchor, 3)
    if prefold is not None:
        asm["folded_views"] = prefold.offered_views
        asm["folded_pairs"] = len(prefold.T_pairs)
        if getattr(report, "assembly", None):
            asm.update(report.assembly)
    info["assembly"] = asm
    report.coordinator = info
    return report
