"""Endpoint parsing shared by every fabric dial/bind site.

The coordinator server, the worker's ``CoordClient``, and the blobstore
client/server all take their addresses from config strings; this module is
the ONE place those strings are interpreted, so a worker never dials an
address the coordinator didn't bind (the PR-8 bug: ``worker.py`` hardcoded
``("127.0.0.1", self.port)`` while the server bound whatever it was told).

Accepted forms (all return ``(host, port)``):

  - ``"host:port"``        — ``"10.0.0.7:9100"``
  - ``"[v6]:port"``        — ``"[::1]:9100"`` (brackets required for IPv6
    literals, like a URL authority — a bare ``::1:9100`` is ambiguous)
  - ``":port"`` / ``"port"`` — host defaults to ``default_host``
  - ``"host:"`` / ``"host"`` — port defaults to ``default_port``

``format_endpoint`` is the inverse: it re-brackets IPv6 literals so a
round-trip through config strings (e.g. the coordinator advertising its
blobstore endpoint in the ``hello`` reply) always re-parses.
"""
from __future__ import annotations

__all__ = ["parse_endpoint", "format_endpoint", "worker_tag",
           "parse_worker_tag"]


def parse_endpoint(text: str, default_host: str = "127.0.0.1",
                   default_port: int = 0) -> tuple[str, int]:
    """Parse ``text`` into ``(host, port)``; see module docstring for the
    accepted forms. Raises ``ValueError`` with the offending text on
    anything else — a fabric dial site must never guess."""
    s = (text or "").strip()
    if not s:
        return default_host, default_port
    if s.startswith("["):                      # [v6]:port or [v6]
        close = s.find("]")
        if close < 0:
            raise ValueError(f"unclosed '[' in endpoint {text!r}")
        host = s[1:close]
        rest = s[close + 1:]
        if rest == "":
            return host or default_host, default_port
        if not rest.startswith(":"):
            raise ValueError(f"garbage after ']' in endpoint {text!r}")
        return host or default_host, _port(rest[1:], text)
    if s.count(":") > 1:                       # unbracketed IPv6 literal
        raise ValueError(
            f"IPv6 literal in endpoint {text!r} must be bracketed, "
            f"e.g. '[::1]:9100'")
    if ":" in s:
        host, _, port = s.partition(":")
        return (host or default_host,
                _port(port, text) if port else default_port)
    if s.isdigit():                            # bare port
        return default_host, _port(s, text)
    return s, default_port                     # bare host


def format_endpoint(host: str, port: int) -> str:
    """``(host, port)`` back to a parseable string; IPv6 literals get
    their brackets back."""
    if ":" in host and not host.startswith("["):
        return f"[{host}]:{port}"
    return f"{host}:{port}"


def worker_tag(worker: str, generation: int = 0) -> str:
    """Display identity of one worker INCARNATION: ``fw0`` for the first
    spawn, ``fw0#g2`` for its second respawn. The fleet supervisor reuses
    the RANK (the lease/ledger identity stays ``fw0`` — steals and grants
    fence on generations already) while the generation stamp lets ``sl3d
    report`` tell a healed worker from a flapping one."""
    g = int(generation)
    return f"{worker}#g{g}" if g > 0 else str(worker)


def parse_worker_tag(tag: str) -> tuple[str, int]:
    """Inverse of :func:`worker_tag`: ``(worker, generation)``. A tag
    without a ``#g`` suffix (pre-fleet workers, rank-0 incarnations) is
    generation 0; a malformed suffix stays part of the name rather than
    guessing."""
    s = str(tag or "")
    base, sep, rest = s.rpartition("#g")
    if sep and rest.isdigit():
        return base, int(rest)
    return s, 0


def _port(s: str, original: str) -> int:
    try:
        p = int(s)
    except ValueError:
        raise ValueError(f"non-numeric port in endpoint {original!r}") \
            from None
    if not 0 <= p <= 65535:
        raise ValueError(f"port out of range in endpoint {original!r}")
    return p
