"""The coordinated-run worker: one crash domain, leased work, cache puts.

Spawned by ``parallel/coordinator.run_coordinated`` as
``sl3d worker --spec <json>`` (one process per host fault domain). The loop
is deliberately dumb: ask the coordinator for the next leased item, run the
EXACT single-process item program (``stages._load_fired`` →
``_compute_fired`` → ``compact_cloud`` → ``_clean_arrays`` for views;
``prep_view`` + ``register_prep_pairs`` for pairs), publish the result to
the content-addressed StageCache (atomic tmp+rename put — the natural
cross-process handoff), and report ``complete``. The coordinator's assembly
pass then finds the bytes under the same keys a clean single-process run
would compute — workers never touch merged artifacts, so they cannot break
byte parity; the worst a dead worker costs is recompute.

Liveness: the lease renews from *inside* ``OverlapStats.add`` via the
``profiling.set_heartbeat_hook`` ambient hook — the same can't-drift
call site the deadline watchdog beats from, so progress accounting and
lease renewal can never disagree. A worker wedged inside one stage stops
beating and loses its leases; there is deliberately NO background beat
thread that would keep a zombie's leases alive.

Host-scope fault kinds (utils/faults.py) get real semantics here:

  worker.kill        -> os._exit(137) mid-item (SIGKILL'd host)
  worker.preempt(T)  -> grace sleep, then os._exit(143) (spot preemption)
  net.partition(T)   -> drop the coordinator link for T seconds but KEEP
                        computing (compute is local; only coordination is
                        partitioned), then reconnect and report late — the
                        lease may have been stolen, exercising the
                        late-complete/"stolen" protocol arm.
  net.slowlink(T)    -> (at site ``worker.sock``) every control frame on
                        the coordinator/blobstore wire straggles T seconds;
                        nothing raises, throughput just sags.

Pod fabric: a spec carrying ``connect``/``secret`` dials a real TCP
endpoint (netutil grammar — `sl3d worker --spec <out>/.coord/join.json`
joins a listening coordinator from another shell or machine), and one
carrying ``blob``/``cache_root`` warms a PRIVATE L1 StageCache with the
coordinator-hosted blobstore as L2 (pipeline/blobstore.py). Heartbeats
and ``next`` requests piggyback inventory diffs (which blob names this
L1 holds) so pair grants can prefer the worker that already has both
endpoint views.
"""
from __future__ import annotations

import json
import os
import socket
import time

import numpy as np

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.utils import deadline as dl
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import profiling as prof
from structured_light_for_3d_model_replication_tpu.utils import telemetry as tel

__all__ = ["CoordClient", "run_worker"]


class CoordClient:
    """Persistent newline-JSON connection to the coordinator. Every call
    is synchronous request/response; socket errors propagate — the caller
    decides between reconnect (partition) and exit (dead coordinator)."""

    def __init__(self, port: int, worker: str, connect_timeout_s: float,
                 io_timeout_s: float = 60.0, connect: str = "",
                 secret: str = ""):
        # ONE resolved endpoint, shared grammar with the coordinator bind
        # and the blobstore (parallel/netutil.py) — `connect` wins, bare
        # `port` keeps the PR-8 loopback default. IPv6 literals must be
        # bracketed ("[::1]:9100") and survive the round trip.
        self.host, self.port = netutil.parse_endpoint(connect,
                                                      default_port=port)
        self.worker = worker
        self.secret = secret
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.addr = ""      # this side of the socket, once connected
        self._sock: socket.socket | None = None
        self._f = None

    def connect(self) -> None:
        """Bounded connect: retry until the coordinator answers or the
        deadline passes — a vanished coordinator must strand no worker."""
        deadline = dl.Deadline.after(self.connect_timeout_s,
                                     "coordinator connect")
        last: Exception | None = None
        while True:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0)
                self._sock.settimeout(self.io_timeout_s)
                self._f = self._sock.makefile("rw", encoding="utf-8")
                name = self._sock.getsockname()
                self.addr = netutil.format_endpoint(name[0], name[1])
                return
            except OSError as e:
                last = e
                if deadline is not None and deadline.remaining() <= 0:
                    raise dl.DeadlineExceeded(
                        f"worker {self.worker}: no coordinator at "
                        f"{netutil.format_endpoint(self.host, self.port)} "
                        f"within {self.connect_timeout_s:g}s "
                        f"({type(e).__name__}: {e})") from last
                time.sleep(0.1)

    def request(self, obj: dict) -> dict:
        if self._f is None:
            raise ConnectionError("not connected")
        # per-frame wire site: `worker.sock:net.slowlink(T)` delays every
        # control frame here (heartbeats still land — late, not lost)
        faults.fire("worker.sock", item=f"coord:{obj.get('op')}")
        self._f.write(json.dumps(obj) + "\n")
        self._f.flush()
        line = self._f.readline()
        if not line:
            raise ConnectionError("coordinator closed the connection")
        resp = json.loads(line)
        if resp.get("error") == "unauthorized":
            raise PermissionError(
                f"worker {self.worker}: coordinator at "
                f"{netutil.format_endpoint(self.host, self.port)} rejected "
                f"the handshake (bad or missing coordinator.secret)")
        return resp

    def hello(self, pid: int, inventory=None, generation: int = 0) -> dict:
        req = {"op": "hello", "worker": self.worker, "pid": pid,
               "addr": self.addr}
        if generation:
            req["generation"] = int(generation)
        if self.secret:
            req["secret"] = self.secret
        if inventory:
            req["inventory"] = list(inventory)
        return self.request(req)

    def next(self, inventory=None) -> dict:
        req = {"op": "next", "worker": self.worker}
        if inventory:
            req["inventory"] = list(inventory)
        return self.request(req)

    def beat(self, inventory=None) -> dict:
        req = {"op": "beat", "worker": self.worker}
        if inventory:
            req["inventory"] = list(inventory)
        return self.request(req)

    def complete(self, item: str, gen: int) -> str:
        return self.request({"op": "complete", "worker": self.worker,
                             "item": item, "gen": gen}).get("ok", "")

    def failed(self, item: str, gen: int, exc: BaseException) -> None:
        self.request({"op": "failed", "worker": self.worker, "item": item,
                      "gen": gen, "error": str(exc),
                      "error_type": type(exc).__name__,
                      "transient": faults.is_transient(exc)})

    def close(self) -> None:
        for x in (self._f, self._sock):
            try:
                if x is not None:
                    x.close()
            except OSError:
                pass
        self._f = self._sock = None


class _WorkerCtx:
    """Everything one worker process holds: config, calib, cache, retry
    policy, the shared OverlapStats whose add() renews the lease."""

    def __init__(self, cfg: Config, spec: dict, client: CoordClient,
                 heartbeat_s: float, blob_endpoint: str = ""):
        from structured_light_for_3d_model_replication_tpu.io import (
            matfile,
        )
        from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
            StageCache,
        )

        self.cfg = cfg
        self.spec = spec
        self.client = client
        self.heartbeat_s = heartbeat_s
        self.worker = spec["worker"]
        self.generation = int(spec.get("generation", 0))
        self.steps = tuple(spec["steps"])
        # fleet workers (ISSUE 18) serve MANY scans: their spec carries no
        # scan-level calib, each granted item names its own instead
        self.calib = (matfile.load_calibration(spec["calib"])
                      if spec.get("calib") else None)
        self._calibs: dict[str, object] = {}
        self._load_calibration = matfile.load_calibration
        self.stats = prof.OverlapStats()
        root = spec.get("cache_root") or os.path.join(spec["out"],
                                                      ".slscan-cache")
        if blob_endpoint or spec.get("connect"):
            # fabric mode: private L1 root + the blobstore as L2. A blob
            # endpoint advertising a wildcard bind resolves to the host
            # we actually dialed the coordinator on
            from structured_light_for_3d_model_replication_tpu.pipeline.blobstore import (
                BlobClient,
                FabricCache,
            )

            bclient = None
            if blob_endpoint:
                bhost, bport = netutil.parse_endpoint(blob_endpoint)
                if bhost in ("0.0.0.0", "::"):
                    bhost = client.host
                bclient = BlobClient(
                    netutil.format_endpoint(bhost, bport),
                    secret=spec.get("secret", ""),
                    connect_timeout_s=cfg.coordinator.connect_timeout_s)
            self.cache = FabricCache(
                root, bclient, enabled=True,
                verify=cfg.pipeline.verify_cache, log=lambda *_: None,
                stats=self.stats)
        else:
            self.cache = StageCache(
                root, enabled=True,
                verify=cfg.pipeline.verify_cache, log=lambda *_: None)
        self._scanners: dict[str, object] = {}   # calib path -> scanner
        self._last_beat = 0.0

    def inventory(self) -> list[str] | None:
        """Pending inventory diff to piggyback on the next control frame
        (None off-fabric or when nothing new was published)."""
        drain = getattr(self.cache, "drain_inventory", None)
        if drain is None:
            return None
        return drain() or None

    def heartbeat(self, stage: str) -> None:
        """The ``OverlapStats.add`` hook: renew every lease this worker
        holds, rate-limited, NEVER raising — a beat that fails (partition,
        dying coordinator) simply lets the lease age toward a steal, which
        is the correct outcome for both. Fabric heartbeats carry the
        inventory diff; a failed beat requeues it (diffs are additive,
        replay-safe)."""
        now = time.monotonic()
        if now - self._last_beat < self.heartbeat_s / 2.0:
            return
        self._last_beat = now
        inv = self.inventory()
        try:
            self.client.beat(inventory=inv)
        except Exception:
            if inv:
                self.cache.requeue_inventory(inv)

    def calib_for(self, path: str):
        """The item's calibration: the spec-level one when the item names
        none (PR-8/15 single-scan workers), else loaded once per distinct
        path — a fleet worker hops between tenants' scans without
        re-reading calib files."""
        if not path:
            if self.calib is None:
                raise RuntimeError(
                    f"worker {self.worker}: item carries no calib and the "
                    f"spec has none either")
            return self.calib
        c = self._calibs.get(path)
        if c is None:
            c = self._calibs[path] = self._load_calibration(path)
        return c

    def scanner(self, src: str, calib=None, ckey: str = ""):
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            stages,
        )

        sc = self._scanners.get(ckey)
        if sc is None:
            sc = stages._build_scanner(
                [src], self.calib if calib is None else calib, self.cfg)
            self._scanners[ckey] = sc
        return sc

    def retries(self, lane: str):
        def on_retry(n, e):
            self.stats.add_retry(lane)
        return on_retry


def _do_view(ctx: _WorkerCtx, ispec: dict) -> None:
    from structured_light_for_3d_model_replication_tpu.ops import (
        triangulate as tri,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    src, key, idx = ispec["src"], ispec["key"], ispec["index"]
    cpath = ispec.get("calib") or ""
    calib = ctx.calib_for(cpath)
    policy = stages._retry_policy(ctx.cfg)
    t0 = time.perf_counter()
    frames, texture = stages._retry_stage(
        "load", lambda: stages._load_fired(src, ctx.cfg), policy,
        ctx.retries("load"))
    ctx.stats.add("load", time.perf_counter() - t0, view=idx)
    t0 = time.perf_counter()
    pts, cols = stages._retry_stage(
        "compute",
        lambda: tri.compact_cloud(stages._compute_fired(
            frames, texture, calib, ctx.cfg,
            ctx.scanner(src, calib, cpath), src)),
        policy, ctx.retries("compute"))
    ctx.stats.add("compute", time.perf_counter() - t0, view=idx)
    t0 = time.perf_counter()
    pts, cols, _ = stages._clean_arrays(pts, cols, ctx.cfg, ctx.steps)
    ctx.stats.add("clean", time.perf_counter() - t0, view=idx)
    t0 = time.perf_counter()
    ctx.cache.put("view", key, points=pts, colors=cols)
    ctx.stats.add("write", time.perf_counter() - t0, view=idx)


def _do_pair(ctx: _WorkerCtx, ispec: dict) -> None:
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as recon,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.pipeline.stagecache import (
        StageCache,
    )

    cfg = ctx.cfg
    pid, dst, src = ispec["pid"], ispec["dst"], ispec["src"]
    hd = ctx.cache.get("view", ispec["key_dst"])
    hs = ctx.cache.get("view", ispec["key_src"])
    if hd is None or hs is None:
        raise RuntimeError(
            f"pair {dst}->{src}: endpoint view(s) missing from the stage "
            f"cache (dep gating should have prevented this grant)")
    pts_d = np.asarray(hd["points"], np.float32)
    cols_d = np.asarray(hd["colors"], np.uint8)
    pts_s = np.asarray(hs["points"], np.float32)
    cols_s = np.asarray(hs["colors"], np.uint8)
    # identical key derivation to _StreamRegistrar._enqueue: endpoint
    # OUTPUT digests + merge numerics + chain position
    dig_d = StageCache.digest_arrays(points=pts_d, colors=cols_d)
    dig_s = StageCache.digest_arrays(points=pts_s, colors=cols_s)
    pair_cfg = stages._merge_numeric_json(cfg) + json.dumps(
        {"backend": cfg.parallel.backend,
         "force_bf16": cfg.parallel.force_bf16_features})
    key = ctx.cache.key("pair", digests=[dig_d, dig_s],
                        config_json=pair_cfg + json.dumps({"pair": pid}))
    if ctx.cache.get("pair", key) is not None:
        return      # already warm (another worker, or a previous run)
    policy = stages._retry_policy(cfg)
    on_retry = ctx.retries("register")
    # same injection site + retry envelope as the streaming register lane
    faults.retry_call(
        lambda: faults.fire("register.pair", item=f"{dst}->{src}"),
        policy, on_retry=on_retry)
    voxel = float(cfg.merge.voxel_size)
    fb16 = True if cfg.parallel.force_bf16_features else None
    t0 = time.perf_counter()
    prep_s = recon.prep_view(pts_s, voxel, cfg.merge.sample_before)
    ctx.heartbeat("register")
    prep_d = recon.prep_view(pts_d, voxel, cfg.merge.sample_before)
    ctx.heartbeat("register")
    T, gf, fi, ir = faults.retry_call(
        lambda: recon.register_prep_pairs(
            [(prep_s, prep_d)], [pid], cfg.merge, voxel, mesh=None,
            feat_bf16=fb16, batch=max(1, cfg.merge.pair_batch)),
        policy, on_retry=on_retry)
    ctx.stats.add("register", time.perf_counter() - t0, view=dst)
    ctx.cache.put("pair", key, T=np.asarray(T[0], np.float32),
                  gfit=np.float32(gf[0]), ifit=np.float32(fi[0]),
                  irmse=np.float32(ir[0]))


def _run_item(ctx: _WorkerCtx, kind: str, iid: str, ispec: dict) -> None:
    # the per-item host-fault site: specs match on "<worker>:<item>", so
    # `worker.item~w0:worker.kill` kills exactly worker w0's first item
    faults.fire("worker.item", item=f"{ctx.worker}:{iid}")
    if kind == "view":
        _do_view(ctx, ispec)
    else:
        _do_pair(ctx, ispec)


def run_worker(spec_path: str, log=print) -> int:
    """The ``sl3d worker`` entry: join the coordinator, drain leased items
    until shutdown. Exit codes: 0 clean, 137 injected kill, 143 injected
    preemption, 1 protocol/connect failure."""
    with open(spec_path, encoding="utf-8") as f:
        spec = json.load(f)
    from structured_light_for_3d_model_replication_tpu import load_config

    cfg = load_config(spec["config"])
    worker = spec["worker"]
    generation = int(spec.get("generation", 0))
    # host tag: rank+pid into every artifact filename this process writes
    # (trace journal, stalls, failures) — N workers share out_dir safely
    tel.set_host_tag(f"{worker}-{os.getpid()}")
    faults.configure_from(cfg.faults)
    client = CoordClient(spec["port"], worker,
                         cfg.coordinator.connect_timeout_s,
                         connect=spec.get("connect", ""),
                         secret=spec.get("secret", ""))
    # connect BEFORE the tracer opens so the journal meta can advertise
    # this worker's wire address (the `sl3d report` host column)
    client.connect()
    tracer = prev_tr = None
    if cfg.observability.trace:
        tracer = tel.Tracer(
            os.path.join(spec["out"],
                         tel.host_scoped(cfg.observability.trace_file)),
            run_id=tel.new_run_id(),
            meta={"tool": "worker", "host": tel.host_tag(),
                  "worker": worker, "pid": os.getpid(),
                  "generation": generation or None,
                  "addr": client.addr or None,
                  "backend": cfg.parallel.backend,
                  "host_cpus": os.cpu_count()})
        prev_tr = tel.activate(tracer)

    # inventory bootstrap: a resumed fabric worker may already hold L1
    # entries from a prior attempt — advertise them in the handshake
    boot: list[str] = []
    root = spec.get("cache_root")
    if root and os.path.isdir(root):
        boot = sorted(f[:-4] for f in os.listdir(root)
                      if f.endswith(".npz"))
    try:
        hello = client.hello(os.getpid(), inventory=boot,
                             generation=generation)
    except PermissionError as e:
        log(f"[worker {worker}] {e}")
        if tracer is not None:
            tel.deactivate(prev_tr)
            tracer.close()
        client.close()
        return 1
    heartbeat_s = float(hello.get("heartbeat_s",
                                  cfg.coordinator.heartbeat_s))
    blob_endpoint = hello.get("blob") or spec.get("blob", "")
    ctx = _WorkerCtx(cfg, spec, client, heartbeat_s,
                     blob_endpoint=blob_endpoint)
    prev_hook = prof.set_heartbeat_hook(ctx.heartbeat)
    log(f"[worker {netutil.worker_tag(worker, generation)}] joined run "
        f"{hello.get('run_id')} "
        f"(pid {os.getpid()}, addr {client.addr or '?'}, "
        f"lease {hello.get('lease_s')}s"
        + (f", blob {blob_endpoint}" if blob_endpoint else "") + ")")
    rc = 0
    try:
        while True:
            inv = ctx.inventory()
            try:
                resp = client.next(inventory=inv)
            except (OSError, ConnectionError, ValueError):
                if inv:
                    ctx.cache.requeue_inventory(inv)
                # coordinator gone mid-run: bounded reconnect, then give up
                client.close()
                try:
                    client.connect()
                    client.hello(os.getpid(), inventory=_full_inv(ctx))
                    continue
                except Exception:
                    log(f"[worker {worker}] coordinator unreachable; "
                        f"exiting")
                    rc = 1
                    break
            if resp.get("shutdown"):
                log(f"[worker {worker}] shutdown received; exiting clean")
                break
            if "grant" not in resp:
                time.sleep(float(resp.get("wait", 0.2)))
                continue
            grant = resp["grant"]
            iid, gen = grant["id"], int(grant["gen"])
            kind, ispec = grant["kind"], grant["spec"]
            if tracer is not None:
                tracer.instant("worker.grant", item=iid, gen=gen)
            try:
                _run_item(ctx, kind, iid, ispec)
            except faults.WorkerKilled:
                # simulated SIGKILL: no complete, no cleanup, no flush —
                # the lease MUST expire and the item MUST be stolen
                os._exit(137)
            except faults.WorkerPreempted as e:
                log(f"[worker {worker}] preemption notice: exiting in "
                    f"{e.grace_s:g}s grace")
                time.sleep(max(0.0, e.grace_s))
                os._exit(143)
            except faults.NetPartition as e:
                _partitioned(ctx, e, kind, iid, gen, ispec, tracer, log)
                continue
            except faults.InjectedCrash:
                os._exit(134)
            except Exception as e:
                log(f"[worker {worker}] item {iid} failed: "
                    f"{type(e).__name__}: {e}")
                if tracer is not None:
                    tracer.instant("worker.failed", item=iid,
                                   error=type(e).__name__)
                try:
                    client.failed(iid, gen, e)
                except Exception:
                    pass    # lease expiry covers an unreportable failure
                continue
            status = client.complete(iid, gen)
            if tracer is not None:
                tracer.instant("worker.complete", item=iid, status=status)
            if status == "stolen":
                log(f"[worker {worker}] item {iid} completed late — "
                    f"lease was stolen; result stays in cache")
    finally:
        prof.set_heartbeat_hook(prev_hook)
        client.close()
        if tracer is not None:
            tel.deactivate(prev_tr)
            tracer.close(os.path.join(
                spec["out"],
                tel.host_scoped(cfg.observability.metrics_file)))
    return rc


def _full_inv(ctx: _WorkerCtx) -> list[str] | None:
    """Full L1 inventory for a (re)handshake — the coordinator's index for
    this worker may be gone (restart) or stale (lost diffs)."""
    names = getattr(ctx.cache, "local_names", None)
    return names() or None if names is not None else None


def _partitioned(ctx: _WorkerCtx, e, kind: str, iid: str, gen: int,
                 ispec: dict, tracer, log) -> None:
    """net.partition semantics: coordination is cut for ``duration_s`` but
    compute is local — finish the item anyway, reconnect, report late. The
    coordinator may answer "stolen" (lease expired during the partition);
    the content-addressed cache makes the double-compute harmless."""
    w = ctx.worker
    log(f"[worker {w}] PARTITIONED from coordinator for "
        f"{e.duration_s:g}s (item {iid} continues locally)")
    ctx.client.close()
    time.sleep(max(0.0, e.duration_s))
    err: Exception | None = None
    try:
        if kind == "view":
            _do_view(ctx, ispec)
        else:
            _do_pair(ctx, ispec)
    except Exception as ie:
        err = ie
    ctx.client.connect()
    ctx.client.hello(os.getpid(), inventory=_full_inv(ctx))
    if err is not None:
        ctx.client.failed(iid, gen, err)
        return
    status = ctx.client.complete(iid, gen)
    if tracer is not None:
        tracer.instant("worker.complete", item=iid, status=status,
                       after_partition=True)
    log(f"[worker {w}] reconnected; late complete of {iid} -> {status}")
