"""Multi-scan admission control: the PR-8 work ledger across tenants.

``parallel/coordinator.py`` coordinates ONE scan's items across worker
processes. The serving gateway needs the same machinery one level up —
many tenants' scans multiplexing onto one device mesh — so this module
generalizes the work ledger instead of reinventing it:

  - items gain a tenant/scan scope: ids are ``<scan_id>/view:<i>`` (the
    coordinator's ``view:<i>`` namespaced by scan), so one ledger and one
    lease table cover every in-flight request at once;
  - grants go through the SAME ``LeaseTable`` (grant / renew / steal /
    generation bump) — the grantee is an in-process engine lane instead
    of a worker process, and a lane that wedges past ``lease_s`` has its
    items swept back to pending exactly like a dead worker;
  - every submit / admit / grant / complete / failed / abort is journaled
    to the coordinator's fsync'd ``Ledger`` (schema ``sl3d-ledger-v1``)
    with ``tenant=``/``scan=`` fields, and ``Ledger.replay`` folds it
    back unchanged (completed ids already embed their scan).

What is NEW at this level is policy: per-tenant quotas (queued + active
caps → a submit over quota is REJECTED at the door, never silently
queued) and weighted-fair scheduling. Fairness is stride-style: every
tenant accumulates ``served / weight`` virtual time, and both scan
admission and item grants pick the eligible tenant with the lowest
virtual time — a weight-3 tenant gets 3x the grant rate of a weight-1
tenant under contention and exactly its demand otherwise.

Durability policy (ISSUE 13) also lives here: overload shedding (a
queued scan whose wait already blew its SLO — or the service-wide
``max_queue_wait_s`` — is shed with a ``shed`` ledger event BEFORE it
wastes engine time), a per-tenant circuit breaker (N consecutive
failed/aborted scans open it; submits fast-fail with a retry hint until
a half-open probe closes it), and ``replay_serving`` — the serving-level
ledger fold that a restarted gateway resumes from: per-scan last state,
the union of completed item ids, and each tenant's consecutive-failure
streak so breakers survive restarts too.

No HTTP, no device code, no stages import — policy stays unit-testable
with fake items, the way ``lease.py`` keeps expiry testable with a fake
clock.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time

from structured_light_for_3d_model_replication_tpu.parallel.coordinator import (
    LEDGER_SCHEMA,
    Ledger,
)
from structured_light_for_3d_model_replication_tpu.parallel.lease import (
    LeaseTable,
)

__all__ = ["ScanJob", "AdmissionController", "replay_serving", "TERMINAL",
           "TenantAuth", "RateLimiter", "fold_usage", "hash_key",
           "write_tenant", "TENANTS_SCHEMA"]

# scan lifecycle (the request's /status surface):
#   queued -> admitted -> warmed -> assembling -> done|degraded|failed|aborted
# plus two durability states: ``shed`` (terminal — dropped from the queue
# before starting, it could no longer meet its SLO) and ``checkpointed``
# (NON-terminal — parked by a drain-budget breach; the next start()
# replays it back to queued with its warmed views already cached)
_TERMINAL = ("done", "degraded", "failed", "aborted", "rejected", "shed")
TERMINAL = _TERMINAL


class ScanJob:
    """One tenant's scan request, from submit to terminal state."""

    def __init__(self, scan_id: str, tenant: str, target: str,
                 calib: str, out_dir: str, weight: float = 1.0,
                 budget_s: float = 0.0, meta: dict | None = None):
        self.scan_id = scan_id
        self.tenant = tenant
        self.target = target
        self.calib = calib
        self.out_dir = out_dir
        self.weight = max(0.1, float(weight))
        self.budget_s = float(budget_s)      # 0 = no per-request SLO
        self.meta = dict(meta or {})
        self.state = "queued"
        self.error = ""
        self.submitted_mono = time.monotonic()
        self.submitted_unix = time.time()
        self.finished_mono: float | None = None
        self.report: dict = {}               # assembly summary for /status

    def elapsed_s(self) -> float:
        end = self.finished_mono or time.monotonic()
        return end - self.submitted_mono

    def budget_remaining(self) -> float | None:
        """Remaining per-request SLO budget, None when no budget armed.
        The clock starts at SUBMIT — queue wait burns budget too, which is
        what makes it a request SLO rather than a compute budget."""
        if self.budget_s <= 0:
            return None
        return self.budget_s - self.elapsed_s()

    def as_dict(self) -> dict:
        d = {"scan_id": self.scan_id, "tenant": self.tenant,
             "state": self.state, "elapsed_s": round(self.elapsed_s(), 3),
             "weight": self.weight, "budget_s": self.budget_s,
             "submitted_unix": self.submitted_unix}
        if self.error:
            d["error"] = self.error
        if self.report:
            d["report"] = self.report
        return d


class _Item:
    __slots__ = ("id", "scan_id", "tenant", "spec", "state")

    def __init__(self, id: str, scan_id: str, tenant: str, spec: dict):
        self.id = id
        self.scan_id = scan_id
        self.tenant = tenant
        self.spec = spec
        self.state = "pending"      # pending -> granted -> done|failed


class _Breaker:
    """One tenant's circuit-breaker state. closed: ``opened_at is None``;
    open: set to the monotonic open time; half-open: open past cooldown
    with ``probe`` holding the single in-flight probe scan_id."""

    __slots__ = ("fails", "opened_at", "probe")

    def __init__(self):
        self.fails = 0          # consecutive failed/aborted finishes
        self.opened_at: float | None = None
        self.probe: str | None = None


class AdmissionController:
    """Quotas + weighted-fair scheduling over the multi-scan ledger."""

    def __init__(self, ledger_path: str, run_id: str, lease_s: float = 30.0,
                 max_active_scans: int = 4, tenant_active_quota: int = 2,
                 tenant_queue_quota: int = 8, queue_depth: int = 64,
                 max_queue_wait_s: float = 0.0, breaker_threshold: int = 0,
                 breaker_cooldown_s: float = 30.0, clock=time.monotonic,
                 epoch=None, fence=None, log=print):
        self.lock = threading.RLock()
        self.log = log
        self.max_active_scans = int(max_active_scans)
        self.tenant_active_quota = int(tenant_active_quota)
        self.tenant_queue_quota = int(tenant_queue_quota)
        self.queue_depth = int(queue_depth)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._clock = clock                      # injectable for tests
        self.leases = LeaseTable(lease_s)
        # HA (ISSUE 14): the gateway's election handle supplies ``epoch``
        # (stamps every journal line with the writer's fencing token) and
        # ``fence`` (rejects the append of a deposed leader). Solo
        # gateways pass neither and journal exactly as before.
        self.ledger = Ledger(ledger_path, run_id, meta={"mode": "serving"},
                             epoch=epoch, fence=fence)
        self.jobs: dict[str, ScanJob] = {}       # scan_id -> job
        self.queue: list[str] = []               # queued scan_ids, FIFO/tenant
        self.items: dict[str, _Item] = {}        # item id -> item
        self._scan_items: dict[str, list[str]] = {}
        self._vtime: dict[str, float] = {}       # tenant -> virtual time
        self._breakers: dict[str, _Breaker] = {}
        self._seq = itertools.count(1)

    # ---- submit / quotas -------------------------------------------------

    def submit(self, job: ScanJob, persist=None) -> tuple[bool, dict]:
        """Admit-or-reject at the door. Over-quota submissions are refused
        with a machine-readable ``reason`` (the gateway's 429/503), never
        silently queued — a rejected request costs the service nothing.
        ``persist``, when given, runs AFTER every check passes and BEFORE
        the scan is journaled or queued (the durable-record write: if it
        raises, nothing was admitted and the caller can 503-retry)."""
        with self.lock:
            allowed, info, is_probe = self._breaker_check(job.tenant)
            if not allowed:
                return False, info
            queued = [j for j in self.jobs.values() if j.state == "queued"]
            if len(queued) >= self.queue_depth:
                return False, {"reason": "queue-full",
                               "error": (f"service queue full "
                                         f"({self.queue_depth} queued)")}
            t_queued = sum(1 for j in queued if j.tenant == job.tenant)
            if t_queued >= self.tenant_queue_quota:
                return False, {"reason": "tenant-queue-quota",
                               "error": (f"tenant {job.tenant!r} queue "
                                         f"quota reached "
                                         f"({self.tenant_queue_quota})")}
            if persist is not None:
                persist(job)
            # journal BEFORE any in-memory mutation: a failed append
            # (full disk, injected transient) leaves nothing admitted, so
            # the caller's "retry" answer is actually true
            self.ledger.event("submit", scan=job.scan_id, tenant=job.tenant,
                              target=job.target, calib=job.calib,
                              out_dir=job.out_dir, weight=job.weight,
                              budget_s=job.budget_s)
            self.jobs[job.scan_id] = job
            self.queue.append(job.scan_id)
            self._vtime.setdefault(job.tenant, self._min_vtime())
            if is_probe:
                self._breakers[job.tenant].probe = job.scan_id
                self.ledger.event("breaker-probe", scan=job.scan_id,
                                  tenant=job.tenant)
        return True, {"reason": "queued"}

    # ---- circuit breaker -------------------------------------------------

    def _breaker_check(self, tenant: str) -> tuple[bool, dict, bool]:
        """(allowed, rejection-info, is_half_open_probe). Caller holds the
        lock. An open breaker fast-fails submits with the cooldown
        remainder as the retry hint; once cooled down, exactly ONE probe
        scan is let through and its outcome closes or re-opens."""
        if self.breaker_threshold <= 0:
            return True, {}, False
        b = self._breakers.get(tenant)
        if b is None or b.opened_at is None:
            return True, {}, False
        waited = self._clock() - b.opened_at
        if waited < self.breaker_cooldown_s:
            rem = self.breaker_cooldown_s - waited
            return False, {"reason": "circuit-open",
                           "retry_after_s": round(max(0.001, rem), 3),
                           "error": (f"tenant {tenant!r} circuit open "
                                     f"({b.fails} consecutive failures); "
                                     f"retry in {rem:.1f}s")}, False
        if b.probe is not None:
            return False, {"reason": "circuit-open",
                           "retry_after_s": round(self.breaker_cooldown_s,
                                                  3),
                           "error": (f"tenant {tenant!r} circuit half-open"
                                     f"; probe {b.probe!r} in flight")}, \
                False
        return True, {}, True

    def _breaker_record(self, job: ScanJob, state: str) -> None:
        """Fold one terminal outcome into the tenant's breaker. Caller
        holds the lock. Shed/checkpointed scans never count — they carry
        no evidence about the tenant's inputs."""
        if self.breaker_threshold <= 0:
            return
        b = self._breakers.setdefault(job.tenant, _Breaker())
        if state in ("done", "degraded"):
            b.fails = 0
            if b.opened_at is not None:
                b.opened_at = None
                b.probe = None
                self.ledger.event("breaker-close", tenant=job.tenant,
                                  scan=job.scan_id)
        elif state in ("failed", "aborted"):
            if b.opened_at is not None and b.probe == job.scan_id:
                b.probe = None
                b.opened_at = self._clock()
                self.ledger.event("breaker-open", tenant=job.tenant,
                                  scan=job.scan_id, reason="probe-failed",
                                  fails=b.fails)
            else:
                b.fails += 1
                if (b.opened_at is None
                        and b.fails >= self.breaker_threshold):
                    b.opened_at = self._clock()
                    self.ledger.event("breaker-open", tenant=job.tenant,
                                      scan=job.scan_id, fails=b.fails)

    def restore_breaker(self, tenant: str, fails: int) -> None:
        """Re-arm a tenant's breaker from a replayed failure streak (a
        restart must not grant a broken tenant a fresh threshold)."""
        if self.breaker_threshold <= 0 or fails <= 0:
            return
        with self.lock:
            b = self._breakers.setdefault(tenant, _Breaker())
            b.fails = int(fails)
            if b.fails >= self.breaker_threshold and b.opened_at is None:
                b.opened_at = self._clock()
                self.ledger.event("breaker-open", tenant=tenant,
                                  fails=b.fails, reason="restored")

    def _min_vtime(self) -> float:
        """New tenants join at the floor of current virtual time so they
        can't bank unfair credit from before they existed."""
        return min(self._vtime.values(), default=0.0)

    def _active(self) -> list[ScanJob]:
        return [j for j in self.jobs.values()
                if j.state in ("admitted", "warmed", "assembling")]

    # ---- weighted-fair admission ----------------------------------------

    def admit_next(self) -> list[ScanJob]:
        """Move queued scans into the admitted set while capacity allows,
        picking the lowest-virtual-time tenant each round. Returns the
        newly admitted jobs (the engine plans their items)."""
        out: list[ScanJob] = []
        with self.lock:
            while True:
                active = self._active()
                if len(active) >= self.max_active_scans:
                    break
                per_tenant: dict[str, int] = {}
                for j in active:
                    per_tenant[j.tenant] = per_tenant.get(j.tenant, 0) + 1
                eligible: dict[str, str] = {}    # tenant -> first scan_id
                for sid in self.queue:
                    j = self.jobs[sid]
                    if j.tenant in eligible:
                        continue
                    if (per_tenant.get(j.tenant, 0)
                            >= self.tenant_active_quota):
                        continue
                    eligible[j.tenant] = sid
                if not eligible:
                    break
                tenant = min(eligible,
                             key=lambda t: (self._vtime.get(t, 0.0), t))
                sid = eligible[tenant]
                self.queue.remove(sid)
                job = self.jobs[sid]
                job.state = "admitted"
                self.ledger.event("admit", scan=sid, tenant=tenant,
                                  wait_s=round(job.elapsed_s(), 3))
                out.append(job)
        return out

    # ---- items -----------------------------------------------------------

    def add_items(self, scan_id: str, specs: list[dict]) -> list[str]:
        """Register a newly admitted scan's work items (one per cache-miss
        view). Ids are ``<scan_id>/view:<i>`` — the coordinator's item ids
        namespaced by scan, so one ledger covers every tenant."""
        job = self.jobs[scan_id]
        ids = []
        with self.lock:
            for spec in specs:
                iid = f"{scan_id}/view:{spec['index']}"
                self.items[iid] = _Item(iid, scan_id, job.tenant, spec)
                ids.append(iid)
            self._scan_items[scan_id] = list(ids)
            self.ledger.event("plan", scan=scan_id, tenant=job.tenant,
                              items=len(ids))
        return ids

    def next_views(self, lane: str, max_n: int) -> list[tuple[str, int, dict]]:
        """Grant up to ``max_n`` pending view items to an engine lane,
        interleaved weighted-fair across tenants — THE cross-tenant
        batching hook: one bucket launch is assembled from exactly one of
        these grant sets, so views from different scans fill the same
        launch whenever more than one tenant has pending work. Returns
        [(item_id, lease_gen, spec), ...]; charges each grant to its
        tenant's virtual time."""
        grants: list[tuple[str, int, dict]] = []
        with self.lock:
            self.leases.renew(lane)
            pending: dict[str, list[_Item]] = {}
            for iid in sorted(self.items):
                it = self.items[iid]
                if it.state == "pending":
                    pending.setdefault(it.tenant, []).append(it)
            while len(grants) < max_n and pending:
                tenant = min(pending,
                             key=lambda t: (self._vtime.get(t, 0.0), t))
                it = pending[tenant].pop(0)
                if not pending[tenant]:
                    del pending[tenant]
                lease = self.leases.grant(it.id, lane)
                it.state = "granted"
                w = self.jobs[it.scan_id].weight
                self._vtime[tenant] = self._vtime.get(tenant, 0.0) + 1.0 / w
                self.ledger.event("grant", item=it.id, scan=it.scan_id,
                                  tenant=tenant, worker=lane,
                                  gen=lease.gen)
                grants.append((it.id, lease.gen, it.spec))
        return grants

    def beat(self, lane: str) -> int:
        return self.leases.renew(lane)

    def complete(self, item_id: str, lane: str, gen: int) -> bool:
        with self.lock:
            it = self.items.get(item_id)
            accepted = self.leases.complete(item_id, lane, gen)
            if accepted and it is not None:
                it.state = "done"
                self.ledger.event("complete", item=item_id,
                                  scan=it.scan_id, tenant=it.tenant,
                                  worker=lane, gen=gen)
            elif it is not None:
                self.ledger.event("late-complete", item=item_id,
                                  scan=it.scan_id, worker=lane, gen=gen)
            return accepted

    def failed(self, item_id: str, lane: str, gen: int,
               error: str = "") -> None:
        """A failed item settles as failed and is NOT retried here — the
        assembly pass recomputes it through the full per-view
        retry/quarantine lane, so failure policy lives in exactly one
        place (the PR-8 construction)."""
        with self.lock:
            it = self.items.get(item_id)
            self.leases.complete(item_id, lane, gen)
            if it is not None and it.state != "done":
                it.state = "failed"
                self.ledger.event("failed", item=item_id, scan=it.scan_id,
                                  tenant=it.tenant, worker=lane,
                                  error=str(error)[:500])

    def sweep_expired(self) -> int:
        """Steal expired lane leases back to pending (a wedged engine lane
        is the in-process twin of a dead worker)."""
        n = 0
        for lease in self.leases.expired():
            with self.lock:
                it = self.items.get(lease.item)
                if it is None or it.state != "granted":
                    continue
                gen = self.leases.steal(lease.item)
                it.state = "pending"
                self.ledger.event("steal", item=lease.item,
                                  worker=lease.worker, gen=gen,
                                  reason="lease-expired")
                n += 1
        return n

    def drop_lane(self, lane: str, reason: str = "worker-dead") -> int:
        """Immediately steal everything a dead lane/worker holds back to
        pending — the fleet supervisor's fast path when it REAPS a worker
        (no need to wait ``lease_s`` for the leases to age out). Safe by
        the same construction as sweep_expired: the steal bumps each
        item's generation, so a late complete from the corpse is refused
        by the exact-triple match."""
        n = 0
        with self.lock:
            for item_id in self.leases.drop_worker(lane):
                it = self.items.get(item_id)
                if it is None or it.state != "granted":
                    continue
                it.state = "pending"
                self.ledger.event("steal", item=item_id, worker=lane,
                                  gen=self.leases.gen(item_id),
                                  reason=reason)
                n += 1
        return n

    def open_breakers(self) -> int:
        """How many tenants currently have an OPEN circuit breaker — one
        of the fleet supervisor's scale signals (a breaker storm means
        failures, not load; scaling out would add fuel)."""
        with self.lock:
            return sum(1 for b in self._breakers.values()
                       if b.opened_at is not None)

    def signals(self) -> dict:
        """One consistent snapshot of the live scale signals the fleet
        supervisor decides from (ISSUE 18). Everything here is already
        exported via /metrics — this is the same data read under one
        lock so a decision journals a coherent snapshot."""
        with self.lock:
            pending = granted = 0
            for it in self.items.values():
                if it.state == "pending":
                    pending += 1
                elif it.state == "granted":
                    granted += 1
            waits = [self.jobs[sid].elapsed_s() for sid in self.queue]
            waits.sort()

            def pct(p: float) -> float:
                if not waits:
                    return 0.0
                i = min(len(waits) - 1, int(p * (len(waits) - 1)))
                return round(waits[i], 3)

            return {"queued_scans": len(self.queue),
                    "active_scans": len(self._active()),
                    "pending_items": pending,
                    "granted_items": granted,
                    "queue_wait_p50_s": pct(0.5),
                    "queue_wait_p99_s": pct(0.99),
                    "open_breakers": sum(
                        1 for b in self._breakers.values()
                        if b.opened_at is not None)}

    def scan_settled(self, scan_id: str) -> bool:
        """True when every item of ``scan_id`` is done or failed — the
        scan is WARMED and ready for its assembly pass."""
        with self.lock:
            return all(self.items[iid].state in ("done", "failed")
                       for iid in self._scan_items.get(scan_id, []))

    def scan_item_states(self, scan_id: str) -> dict:
        with self.lock:
            out: dict[str, int] = {}
            for iid in self._scan_items.get(scan_id, []):
                s = self.items[iid].state
                out[s] = out.get(s, 0) + 1
            return out

    # ---- terminal transitions -------------------------------------------

    def finish(self, scan_id: str, state: str, error: str = "",
               report: dict | None = None) -> None:
        with self.lock:
            job = self.jobs[scan_id]
            job.state = state
            job.error = error
            job.finished_mono = time.monotonic()
            if report:
                job.report = report
            for iid in self._scan_items.pop(scan_id, []):
                self.items.pop(iid, None)
            # the report summary rides the finish event so a restarted
            # service can serve /status for already-terminal scans
            # straight from the replayed ledger
            self.ledger.event("finish", scan=scan_id, tenant=job.tenant,
                              state=state, error=str(error)[:500],
                              elapsed_s=round(job.elapsed_s(), 3),
                              report=job.report or {})
            self._breaker_record(job, state)

    def checkpoint(self, scan_id: str, reason: str = "drain") -> bool:
        """Park a non-terminal scan at drain time: its items are dropped
        (warmed views live on in the stage cache — that work is kept),
        the state goes CHECKPOINTED, and the journaled event tells the
        next start() to replay it back into the queue."""
        with self.lock:
            job = self.jobs.get(scan_id)
            if job is None or job.state in _TERMINAL:
                return False
            if scan_id in self.queue:
                self.queue.remove(scan_id)
            job.state = "checkpointed"
            job.error = reason
            for iid in self._scan_items.pop(scan_id, []):
                self.items.pop(iid, None)
            self.ledger.event("checkpoint", scan=scan_id,
                              tenant=job.tenant, reason=reason)
            return True

    def shed_expired(self) -> list[ScanJob]:
        """Drop queued scans that can no longer start usefully: their SLO
        budget is already gone, or they out-waited ``max_queue_wait_s``.
        Shedding at the queue head is the overload valve — a scan that
        would only burn engine time to abort later is refused work NOW,
        while the client can still retry elsewhere."""
        out: list[ScanJob] = []
        with self.lock:
            for sid in list(self.queue):
                job = self.jobs[sid]
                wait = job.elapsed_s()
                rem = job.budget_remaining()
                if rem is not None and rem <= 0:
                    reason = (f"queue wait {wait:.1f}s consumed the "
                              f"{job.budget_s:g}s SLO budget")
                elif 0 < self.max_queue_wait_s < wait:
                    reason = (f"queue wait {wait:.1f}s exceeded "
                              f"max_queue_wait_s="
                              f"{self.max_queue_wait_s:g}")
                else:
                    continue
                self.queue.remove(sid)
                job.state = "shed"
                job.error = reason
                job.finished_mono = time.monotonic()
                self.ledger.event("shed", scan=sid, tenant=job.tenant,
                                  reason=reason, wait_s=round(wait, 3))
                out.append(job)
        return out

    # ---- restart-resume --------------------------------------------------

    def restore(self, job: ScanJob) -> None:
        """Re-enqueue a replayed non-terminal scan, bypassing the door
        quotas — a previous incarnation of the service already accepted
        (and journaled) it; refusing it now would break the 202 the
        client holds. Journals a ``resume`` event so the ledger reads as
        the scan's full history across process generations."""
        with self.lock:
            job.state = "queued"
            self.jobs[job.scan_id] = job
            self.queue.append(job.scan_id)
            self._vtime.setdefault(job.tenant, self._min_vtime())
            self.ledger.event("resume", scan=job.scan_id,
                              tenant=job.tenant)

    def restore_terminal(self, job: ScanJob) -> None:
        """Re-register an already-terminal scan (state set by the caller
        from the replayed ledger) so /status and /result keep answering
        across restarts. Nothing to journal — nothing changed."""
        with self.lock:
            self.jobs[job.scan_id] = job

    def snapshot(self) -> dict:
        with self.lock:
            states: dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            return {"scans": {sid: j.as_dict()
                              for sid, j in self.jobs.items()},
                    "states": states,
                    "queued": len(self.queue),
                    "active": len(self._active()),
                    "vtime": dict(self._vtime)}

    def close(self) -> None:
        self.ledger.close()


def replay_serving(path: str) -> dict:
    """Fold a serving ledger into restart-resume state. Torn-tail
    tolerant like :meth:`Ledger.replay` (a crash mid-append loses at most
    the line being written), and a superset of it: besides the union of
    completed item ids this folds each scan's LAST journaled state —
    submit → queued, admit → admitted, warmed, finish → its terminal
    state (with error/report), shed, checkpoint → checkpointed, resume →
    queued again — plus each tenant's consecutive failed/aborted streak,
    so circuit breakers survive restarts.

    Epoch fencing (ISSUE 14): HA gateways stamp every line with the
    writer's election epoch. The fold tracks the newest epoch seen so
    far and IGNORES any later line carrying an older one — the append a
    zombie leader raced past the live fence cannot resurrect state or
    credit items the new leader's segment already owns. Lines without an
    epoch (solo gateways, pre-HA ledgers) are never fenced. Returns::

        {"scans": {scan_id: {"tenant", "state", "target", "calib",
                             "out_dir", "weight", "budget_s",
                             "submitted_unix", "error", "report",
                             "elapsed_s"}},
         "completed": set[item_id], "tenant_fails": {tenant: int},
         "segments": int, "events": int,
         "max_epoch": int, "stale_ignored": int}
    """
    scans: dict[str, dict] = {}
    completed: set[str] = set()
    tenant_fails: dict[str, int] = {}
    segments = events = 0
    max_epoch = stale_ignored = 0
    if not os.path.exists(path):
        return {"scans": scans, "completed": completed,
                "tenant_fails": tenant_fails, "segments": 0, "events": 0,
                "max_epoch": 0, "stale_ignored": 0}

    def rec_for(rec: dict) -> dict:
        sid = rec["scan"]
        r = scans.get(sid)
        if r is None:
            r = scans[sid] = {"tenant": rec.get("tenant", ""),
                              "state": "queued", "target": "",
                              "calib": "", "out_dir": "", "weight": 1.0,
                              "budget_s": 0.0, "submitted_unix": 0.0,
                              "error": "", "report": {},
                              "elapsed_s": 0.0}
        return r

    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue        # torn tail from a crash mid-append
            e = ev.get("epoch")
            if e is not None:
                e = int(e)
                if e < max_epoch:
                    stale_ignored += 1   # fenced-out zombie append
                    continue
                max_epoch = e
            t = ev.get("type")
            if t == "meta":
                if ev.get("schema") != LEDGER_SCHEMA:
                    raise ValueError(
                        f"ledger {path}: unknown schema "
                        f"{ev.get('schema')!r} (want {LEDGER_SCHEMA})")
                segments += 1
                continue
            events += 1
            if t == "complete":
                completed.add(ev["item"])
                continue
            if "scan" not in ev:
                continue
            if t == "submit":
                r = rec_for(ev)
                r.update(state="queued",
                         target=ev.get("target", ""),
                         calib=ev.get("calib", ""),
                         out_dir=ev.get("out_dir", ""),
                         weight=float(ev.get("weight", 1.0)),
                         budget_s=float(ev.get("budget_s", 0.0)),
                         submitted_unix=float(ev.get("t", 0.0)))
            elif t == "admit":
                rec_for(ev)["state"] = "admitted"
            elif t == "warmed":
                rec_for(ev)["state"] = "warmed"
            elif t == "finish":
                r = rec_for(ev)
                r.update(state=ev.get("state", "failed"),
                         error=ev.get("error", ""),
                         report=ev.get("report") or {},
                         elapsed_s=float(ev.get("elapsed_s", 0.0)))
            elif t == "shed":
                r = rec_for(ev)
                r.update(state="shed", error=ev.get("reason", ""))
            elif t == "checkpoint":
                rec_for(ev)["state"] = "checkpointed"
            elif t == "resume":
                rec_for(ev)["state"] = "queued"
            if t in ("finish", "shed"):
                tenant = ev.get("tenant", "")
                st = ev.get("state", "shed" if t == "shed" else "")
                if st in ("failed", "aborted"):
                    tenant_fails[tenant] = tenant_fails.get(tenant, 0) + 1
                elif st in ("done", "degraded"):
                    tenant_fails[tenant] = 0
    return {"scans": scans, "completed": completed,
            "tenant_fails": tenant_fails, "segments": segments,
            "events": events, "max_epoch": max_epoch,
            "stale_ignored": stale_ignored}


def fold_usage(rs: dict) -> dict:
    """Per-tenant usage metering folded from a :func:`replay_serving`
    result (ISSUE 18): the /usage surface. Metering reads the SAME
    epoch-fenced fold that restart-resume and the follower read model
    use, so a bill can never disagree with what the service actually
    credited — and a zombie leader's fenced-out lines never meter.

    Returns ``{tenant: {"submitted", "done", "degraded", "failed",
    "aborted", "shed", "in_flight", "views_completed", "compute_s"}}``
    where ``compute_s`` sums terminal scans' elapsed_s (queue wait burns
    SLO budget, so it bills — the same clock /status reports)."""
    usage: dict[str, dict] = {}

    def row(tenant: str) -> dict:
        r = usage.get(tenant)
        if r is None:
            r = usage[tenant] = {"submitted": 0, "done": 0, "degraded": 0,
                                 "failed": 0, "aborted": 0, "shed": 0,
                                 "in_flight": 0, "views_completed": 0,
                                 "compute_s": 0.0}
        return r

    scan_tenant: dict[str, str] = {}
    for sid, r in rs["scans"].items():
        tenant = r.get("tenant", "") or "anon"
        scan_tenant[sid] = tenant
        u = row(tenant)
        u["submitted"] += 1
        state = r.get("state", "")
        if state in ("done", "degraded", "failed", "aborted", "shed"):
            u[state] += 1
            u["compute_s"] = round(
                u["compute_s"] + float(r.get("elapsed_s", 0.0)), 3)
        elif state != "rejected":
            u["in_flight"] += 1
    for item_id in rs["completed"]:
        sid = item_id.rsplit("/", 1)[0]
        tenant = scan_tenant.get(sid)
        if tenant is not None:
            row(tenant)["views_completed"] += 1
    return usage


# ---- front-door auth (ISSUE 18) -------------------------------------------

TENANTS_SCHEMA = "sl3d-tenants-v1"


def hash_key(key: str) -> str:
    """sha256 of an API key — the only form ever at rest or compared."""
    return hashlib.sha256(key.encode("utf-8")).hexdigest()


class TenantAuth:
    """Per-tenant API keys, verified against sha256 hashes at rest in
    ``<root>/tenants.json`` (``sl3d tenant add`` writes it; the plaintext
    key is printed exactly once at creation). The file is re-read only
    when its stat changes — key rotation needs no restart — and a
    missing/unreadable file with auth enabled fails CLOSED (every submit
    401s) rather than silently opening the door."""

    def __init__(self, path: str, clock=time.monotonic):
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        self._stat: tuple | None = None
        self._tenants: dict[str, dict] = {}

    def _load(self) -> dict[str, dict]:
        try:
            st = os.stat(self.path)
            key = (st.st_mtime_ns, st.st_size, st.st_ino)
        except OSError:
            key = None
        with self._lock:
            if key is not None and key == self._stat:
                return self._tenants
            tenants: dict[str, dict] = {}
            if key is not None:
                try:
                    with open(self.path, encoding="utf-8") as f:
                        doc = json.load(f)
                    if doc.get("schema") == TENANTS_SCHEMA:
                        tenants = dict(doc.get("tenants") or {})
                except (OSError, ValueError):
                    tenants = {}     # unreadable = no keys = fail closed
            self._stat = key
            self._tenants = tenants
            return tenants

    def known(self) -> list[str]:
        return sorted(self._load())

    def tenant_limits(self, tenant: str) -> tuple[int, float] | None:
        """Per-tenant (rate_limit, rate_window_s) override from
        tenants.json, None when the tenant carries none."""
        rec = self._load().get(tenant)
        if rec is None or "rate_limit" not in rec:
            return None
        return (int(rec.get("rate_limit", 0)),
                float(rec.get("rate_window_s", 60.0)))

    def check(self, tenant: str, key: str) -> dict | None:
        """None = authenticated; otherwise a machine-readable rejection
        body (``reason`` ∈ auth-required | auth-invalid | auth-forbidden
        — the gateway maps them to 401/401/403). A key that IS valid for
        a different tenant is 403 (we know who you are — you may not act
        as someone else); an unknown key is 401."""
        if not key:
            return {"reason": "auth-required",
                    "error": "missing API key (X-API-Key header or "
                             "api_key field)"}
        tenants = self._load()
        h = hash_key(key)
        rec = tenants.get(tenant)
        if rec is not None and rec.get("key_sha256") == h:
            return None
        for other, orec in tenants.items():
            if orec.get("key_sha256") == h:
                return {"reason": "auth-forbidden",
                        "error": f"key belongs to tenant {other!r}, "
                                 f"not {tenant!r}"}
        return {"reason": "auth-invalid",
                "error": f"unknown API key for tenant {tenant!r}"}


def write_tenant(path: str, tenant: str, key: str,
                 rate_limit: int | None = None,
                 rate_window_s: float | None = None) -> None:
    """Add/update one tenant's hashed key in ``tenants.json`` (atomic
    rewrite; creates the file). CLI-facing — the server only reads."""
    from structured_light_for_3d_model_replication_tpu.io.atomic import (
        atomic_write,
    )

    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != TENANTS_SCHEMA:
            doc = {"schema": TENANTS_SCHEMA, "tenants": {}}
    except (OSError, ValueError):
        doc = {"schema": TENANTS_SCHEMA, "tenants": {}}
    rec = doc["tenants"].setdefault(tenant, {})
    rec["key_sha256"] = hash_key(key)
    if rate_limit is not None:
        rec["rate_limit"] = int(rate_limit)
    if rate_window_s is not None:
        rec["rate_window_s"] = float(rate_window_s)
    with atomic_write(path) as tmp:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())


class RateLimiter:
    """Per-tenant sliding-window submit limiter, expressed in the quota
    vocabulary: over the limit answers ``rate-limited`` + retry_after_s
    (HTTP 429), exactly like ``tenant-queue-quota``. Injectable clock —
    the 429 matrix unit-tests with zero real sleeps."""

    def __init__(self, limit: int, window_s: float = 60.0,
                 clock=time.monotonic):
        self.limit = int(limit)
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._hits: dict[str, list[float]] = {}   # tenant -> admit times

    def allow(self, tenant: str,
              limit: int | None = None,
              window_s: float | None = None) -> dict | None:
        """None = allowed (and counted); otherwise the rejection body.
        Per-tenant overrides (tenants.json) ride in as arguments."""
        lim = self.limit if limit is None else int(limit)
        win = self.window_s if window_s is None else float(window_s)
        if lim <= 0:
            return None
        now = self._clock()
        with self._lock:
            hits = self._hits.setdefault(tenant, [])
            cut = now - win
            while hits and hits[0] <= cut:
                hits.pop(0)
            if len(hits) >= lim:
                retry = max(0.001, hits[0] + win - now)
                return {"reason": "rate-limited",
                        "retry_after_s": round(retry, 3),
                        "error": (f"tenant {tenant!r} over rate limit "
                                  f"({lim} submits per {win:g}s)")}
            hits.append(now)
            return None
