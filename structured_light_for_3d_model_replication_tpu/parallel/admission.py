"""Multi-scan admission control: the PR-8 work ledger across tenants.

``parallel/coordinator.py`` coordinates ONE scan's items across worker
processes. The serving gateway needs the same machinery one level up —
many tenants' scans multiplexing onto one device mesh — so this module
generalizes the work ledger instead of reinventing it:

  - items gain a tenant/scan scope: ids are ``<scan_id>/view:<i>`` (the
    coordinator's ``view:<i>`` namespaced by scan), so one ledger and one
    lease table cover every in-flight request at once;
  - grants go through the SAME ``LeaseTable`` (grant / renew / steal /
    generation bump) — the grantee is an in-process engine lane instead
    of a worker process, and a lane that wedges past ``lease_s`` has its
    items swept back to pending exactly like a dead worker;
  - every submit / admit / grant / complete / failed / abort is journaled
    to the coordinator's fsync'd ``Ledger`` (schema ``sl3d-ledger-v1``)
    with ``tenant=``/``scan=`` fields, and ``Ledger.replay`` folds it
    back unchanged (completed ids already embed their scan).

What is NEW at this level is policy: per-tenant quotas (queued + active
caps → a submit over quota is REJECTED at the door, never silently
queued) and weighted-fair scheduling. Fairness is stride-style: every
tenant accumulates ``served / weight`` virtual time, and both scan
admission and item grants pick the eligible tenant with the lowest
virtual time — a weight-3 tenant gets 3x the grant rate of a weight-1
tenant under contention and exactly its demand otherwise.

No HTTP, no device code, no stages import — policy stays unit-testable
with fake items, the way ``lease.py`` keeps expiry testable with a fake
clock.
"""
from __future__ import annotations

import itertools
import threading
import time

from structured_light_for_3d_model_replication_tpu.parallel.coordinator import (
    Ledger,
)
from structured_light_for_3d_model_replication_tpu.parallel.lease import (
    LeaseTable,
)

__all__ = ["ScanJob", "AdmissionController"]

# scan lifecycle (the request's /status surface):
#   queued -> admitted -> warmed -> assembling -> done|degraded|failed|aborted
_TERMINAL = ("done", "degraded", "failed", "aborted", "rejected")


class ScanJob:
    """One tenant's scan request, from submit to terminal state."""

    def __init__(self, scan_id: str, tenant: str, target: str,
                 calib: str, out_dir: str, weight: float = 1.0,
                 budget_s: float = 0.0, meta: dict | None = None):
        self.scan_id = scan_id
        self.tenant = tenant
        self.target = target
        self.calib = calib
        self.out_dir = out_dir
        self.weight = max(0.1, float(weight))
        self.budget_s = float(budget_s)      # 0 = no per-request SLO
        self.meta = dict(meta or {})
        self.state = "queued"
        self.error = ""
        self.submitted_mono = time.monotonic()
        self.submitted_unix = time.time()
        self.finished_mono: float | None = None
        self.report: dict = {}               # assembly summary for /status

    def elapsed_s(self) -> float:
        end = self.finished_mono or time.monotonic()
        return end - self.submitted_mono

    def budget_remaining(self) -> float | None:
        """Remaining per-request SLO budget, None when no budget armed.
        The clock starts at SUBMIT — queue wait burns budget too, which is
        what makes it a request SLO rather than a compute budget."""
        if self.budget_s <= 0:
            return None
        return self.budget_s - self.elapsed_s()

    def as_dict(self) -> dict:
        d = {"scan_id": self.scan_id, "tenant": self.tenant,
             "state": self.state, "elapsed_s": round(self.elapsed_s(), 3),
             "weight": self.weight, "budget_s": self.budget_s,
             "submitted_unix": self.submitted_unix}
        if self.error:
            d["error"] = self.error
        if self.report:
            d["report"] = self.report
        return d


class _Item:
    __slots__ = ("id", "scan_id", "tenant", "spec", "state")

    def __init__(self, id: str, scan_id: str, tenant: str, spec: dict):
        self.id = id
        self.scan_id = scan_id
        self.tenant = tenant
        self.spec = spec
        self.state = "pending"      # pending -> granted -> done|failed


class AdmissionController:
    """Quotas + weighted-fair scheduling over the multi-scan ledger."""

    def __init__(self, ledger_path: str, run_id: str, lease_s: float = 30.0,
                 max_active_scans: int = 4, tenant_active_quota: int = 2,
                 tenant_queue_quota: int = 8, queue_depth: int = 64,
                 log=print):
        self.lock = threading.RLock()
        self.log = log
        self.max_active_scans = int(max_active_scans)
        self.tenant_active_quota = int(tenant_active_quota)
        self.tenant_queue_quota = int(tenant_queue_quota)
        self.queue_depth = int(queue_depth)
        self.leases = LeaseTable(lease_s)
        self.ledger = Ledger(ledger_path, run_id, meta={"mode": "serving"})
        self.jobs: dict[str, ScanJob] = {}       # scan_id -> job
        self.queue: list[str] = []               # queued scan_ids, FIFO/tenant
        self.items: dict[str, _Item] = {}        # item id -> item
        self._scan_items: dict[str, list[str]] = {}
        self._vtime: dict[str, float] = {}       # tenant -> virtual time
        self._seq = itertools.count(1)

    # ---- submit / quotas -------------------------------------------------

    def submit(self, job: ScanJob) -> tuple[bool, str]:
        """Admit-or-reject at the door. Over-quota submissions are refused
        with a reason (the gateway's 429), never silently queued — a
        rejected request costs the service nothing."""
        with self.lock:
            queued = [j for j in self.jobs.values() if j.state == "queued"]
            if len(queued) >= self.queue_depth:
                return False, (f"service queue full "
                               f"({self.queue_depth} queued)")
            t_queued = sum(1 for j in queued if j.tenant == job.tenant)
            if t_queued >= self.tenant_queue_quota:
                return False, (f"tenant {job.tenant!r} queue quota reached "
                               f"({self.tenant_queue_quota})")
            self.jobs[job.scan_id] = job
            self.queue.append(job.scan_id)
            self._vtime.setdefault(job.tenant, self._min_vtime())
            self.ledger.event("submit", scan=job.scan_id, tenant=job.tenant,
                              target=job.target, weight=job.weight,
                              budget_s=job.budget_s)
        return True, "queued"

    def _min_vtime(self) -> float:
        """New tenants join at the floor of current virtual time so they
        can't bank unfair credit from before they existed."""
        return min(self._vtime.values(), default=0.0)

    def _active(self) -> list[ScanJob]:
        return [j for j in self.jobs.values()
                if j.state in ("admitted", "warmed", "assembling")]

    # ---- weighted-fair admission ----------------------------------------

    def admit_next(self) -> list[ScanJob]:
        """Move queued scans into the admitted set while capacity allows,
        picking the lowest-virtual-time tenant each round. Returns the
        newly admitted jobs (the engine plans their items)."""
        out: list[ScanJob] = []
        with self.lock:
            while True:
                active = self._active()
                if len(active) >= self.max_active_scans:
                    break
                per_tenant: dict[str, int] = {}
                for j in active:
                    per_tenant[j.tenant] = per_tenant.get(j.tenant, 0) + 1
                eligible: dict[str, str] = {}    # tenant -> first scan_id
                for sid in self.queue:
                    j = self.jobs[sid]
                    if j.tenant in eligible:
                        continue
                    if (per_tenant.get(j.tenant, 0)
                            >= self.tenant_active_quota):
                        continue
                    eligible[j.tenant] = sid
                if not eligible:
                    break
                tenant = min(eligible,
                             key=lambda t: (self._vtime.get(t, 0.0), t))
                sid = eligible[tenant]
                self.queue.remove(sid)
                job = self.jobs[sid]
                job.state = "admitted"
                self.ledger.event("admit", scan=sid, tenant=tenant,
                                  wait_s=round(job.elapsed_s(), 3))
                out.append(job)
        return out

    # ---- items -----------------------------------------------------------

    def add_items(self, scan_id: str, specs: list[dict]) -> list[str]:
        """Register a newly admitted scan's work items (one per cache-miss
        view). Ids are ``<scan_id>/view:<i>`` — the coordinator's item ids
        namespaced by scan, so one ledger covers every tenant."""
        job = self.jobs[scan_id]
        ids = []
        with self.lock:
            for spec in specs:
                iid = f"{scan_id}/view:{spec['index']}"
                self.items[iid] = _Item(iid, scan_id, job.tenant, spec)
                ids.append(iid)
            self._scan_items[scan_id] = list(ids)
            self.ledger.event("plan", scan=scan_id, tenant=job.tenant,
                              items=len(ids))
        return ids

    def next_views(self, lane: str, max_n: int) -> list[tuple[str, int, dict]]:
        """Grant up to ``max_n`` pending view items to an engine lane,
        interleaved weighted-fair across tenants — THE cross-tenant
        batching hook: one bucket launch is assembled from exactly one of
        these grant sets, so views from different scans fill the same
        launch whenever more than one tenant has pending work. Returns
        [(item_id, lease_gen, spec), ...]; charges each grant to its
        tenant's virtual time."""
        grants: list[tuple[str, int, dict]] = []
        with self.lock:
            self.leases.renew(lane)
            pending: dict[str, list[_Item]] = {}
            for iid in sorted(self.items):
                it = self.items[iid]
                if it.state == "pending":
                    pending.setdefault(it.tenant, []).append(it)
            while len(grants) < max_n and pending:
                tenant = min(pending,
                             key=lambda t: (self._vtime.get(t, 0.0), t))
                it = pending[tenant].pop(0)
                if not pending[tenant]:
                    del pending[tenant]
                lease = self.leases.grant(it.id, lane)
                it.state = "granted"
                w = self.jobs[it.scan_id].weight
                self._vtime[tenant] = self._vtime.get(tenant, 0.0) + 1.0 / w
                self.ledger.event("grant", item=it.id, scan=it.scan_id,
                                  tenant=tenant, worker=lane,
                                  gen=lease.gen)
                grants.append((it.id, lease.gen, it.spec))
        return grants

    def beat(self, lane: str) -> int:
        return self.leases.renew(lane)

    def complete(self, item_id: str, lane: str, gen: int) -> bool:
        with self.lock:
            it = self.items.get(item_id)
            accepted = self.leases.complete(item_id, lane, gen)
            if accepted and it is not None:
                it.state = "done"
                self.ledger.event("complete", item=item_id,
                                  scan=it.scan_id, tenant=it.tenant,
                                  worker=lane, gen=gen)
            elif it is not None:
                self.ledger.event("late-complete", item=item_id,
                                  scan=it.scan_id, worker=lane, gen=gen)
            return accepted

    def failed(self, item_id: str, lane: str, gen: int,
               error: str = "") -> None:
        """A failed item settles as failed and is NOT retried here — the
        assembly pass recomputes it through the full per-view
        retry/quarantine lane, so failure policy lives in exactly one
        place (the PR-8 construction)."""
        with self.lock:
            it = self.items.get(item_id)
            self.leases.complete(item_id, lane, gen)
            if it is not None and it.state != "done":
                it.state = "failed"
                self.ledger.event("failed", item=item_id, scan=it.scan_id,
                                  tenant=it.tenant, worker=lane,
                                  error=str(error)[:500])

    def sweep_expired(self) -> int:
        """Steal expired lane leases back to pending (a wedged engine lane
        is the in-process twin of a dead worker)."""
        n = 0
        for lease in self.leases.expired():
            with self.lock:
                it = self.items.get(lease.item)
                if it is None or it.state != "granted":
                    continue
                gen = self.leases.steal(lease.item)
                it.state = "pending"
                self.ledger.event("steal", item=lease.item,
                                  worker=lease.worker, gen=gen,
                                  reason="lease-expired")
                n += 1
        return n

    def scan_settled(self, scan_id: str) -> bool:
        """True when every item of ``scan_id`` is done or failed — the
        scan is WARMED and ready for its assembly pass."""
        with self.lock:
            return all(self.items[iid].state in ("done", "failed")
                       for iid in self._scan_items.get(scan_id, []))

    def scan_item_states(self, scan_id: str) -> dict:
        with self.lock:
            out: dict[str, int] = {}
            for iid in self._scan_items.get(scan_id, []):
                s = self.items[iid].state
                out[s] = out.get(s, 0) + 1
            return out

    # ---- terminal transitions -------------------------------------------

    def finish(self, scan_id: str, state: str, error: str = "",
               report: dict | None = None) -> None:
        with self.lock:
            job = self.jobs[scan_id]
            job.state = state
            job.error = error
            job.finished_mono = time.monotonic()
            if report:
                job.report = report
            for iid in self._scan_items.pop(scan_id, []):
                self.items.pop(iid, None)
            self.ledger.event("finish", scan=scan_id, tenant=job.tenant,
                              state=state, error=str(error)[:500],
                              elapsed_s=round(job.elapsed_s(), 3))

    def snapshot(self) -> dict:
        with self.lock:
            states: dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            return {"scans": {sid: j.as_dict()
                              for sid, j in self.jobs.items()},
                    "states": states,
                    "queued": len(self.queue),
                    "active": len(self._active()),
                    "vtime": dict(self._vtime)}

    def close(self) -> None:
        self.ledger.close()
