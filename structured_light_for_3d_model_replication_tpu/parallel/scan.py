"""Sharded multi-view scan pipeline: shard_map over (views, pixel rows).

The full 360-degree reconstruction step distributed over a device mesh:
each chip decodes + triangulates its (view-shard, row-shard) block, then mesh
collectives (psum) reduce the global cloud statistics every chip needs for the
downstream merge (counts, centroid, bounding box). This is the step
``__graft_entry__.dryrun_multichip`` compiles over an N-virtual-device mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from structured_light_for_3d_model_replication_tpu.utils.jax_compat import shard_map

from structured_light_for_3d_model_replication_tpu.ops.graycode import _decode_impl
from structured_light_for_3d_model_replication_tpu.ops.triangulate import (
    CloudResult,
    _triangulate_impl,
)
from structured_light_for_3d_model_replication_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    P,
)

__all__ = ["build_sharded_scan_step"]


def build_sharded_scan_step(mesh, *, proj_size, n_sets_col: int = 11,
                            n_sets_row: int = 11, row_mode: int = 1,
                            epipolar_tol: float = 2.0, downsample: int = 1):
    """Returns a jitted step: (frames_v, rays_hw, oc, plane_col, plane_row,
    shadow_v, contrast_v) -> (CloudResult [V, Npix(,x2)], stats dict).

    Sharding: frames [V, F, H, W] split (views -> data, rows -> model); the ray
    field [H, W, 3] splits with the rows; plane tables and Oc are replicated
    (they are KB-scale). Stats are psum-reduced over the whole mesh so every
    chip holds the global values.
    """
    pw, ph = proj_size

    def _local(frames_v, rays_hw, oc, plane_col, plane_row, shadow_v, contrast_v):
        def one_view(frames, shadow, contrast):
            # gray frame-0 texture, replicated to RGB host-side at the
            # export boundary (same contract as SLScanner._forward_math)
            texture = frames[0][..., None].astype(jnp.uint8)
            dec = _decode_impl(frames, texture, shadow, contrast,
                               n_cols=pw, n_rows=ph, n_sets_col=n_sets_col,
                               n_sets_row=n_sets_row, downsample=downsample, xp=jnp)
            return _triangulate_impl(
                dec.col_map, dec.row_map, dec.mask, dec.texture,
                rays_hw.reshape(-1, 3), oc, plane_col, plane_row,
                row_mode=row_mode, epipolar_tol=epipolar_tol, xp=jnp,
            )

        cloud = jax.vmap(one_view)(frames_v, shadow_v, contrast_v)
        # global cloud statistics for the merge stage — collectives over both axes
        valid_f = cloud.valid.astype(jnp.float32)
        n_valid = jax.lax.psum(valid_f.sum(), (AXIS_DATA, AXIS_MODEL))
        centroid = jax.lax.psum(
            (cloud.points * valid_f[..., None]).sum((0, 1)), (AXIS_DATA, AXIS_MODEL)
        ) / jnp.maximum(n_valid, 1.0)
        big = jnp.float32(1e30)
        masked = jnp.where(cloud.valid[..., None], cloud.points, big)
        bb_min = jax.lax.pmin(masked.min((0, 1)), (AXIS_DATA, AXIS_MODEL))
        masked = jnp.where(cloud.valid[..., None], cloud.points, -big)
        bb_max = jax.lax.pmax(masked.max((0, 1)), (AXIS_DATA, AXIS_MODEL))
        stats = {"n_valid": n_valid, "centroid": centroid,
                 "bb_min": bb_min, "bb_max": bb_max}
        return cloud, stats

    spec_frames = P(AXIS_DATA, None, AXIS_MODEL, None)
    spec_rays = P(AXIS_MODEL, None, None)
    spec_perview = P(AXIS_DATA)
    spec_cloud = CloudResult(
        points=P(AXIS_DATA, AXIS_MODEL, None),
        colors=P(AXIS_DATA, AXIS_MODEL, None),
        valid=P(AXIS_DATA, AXIS_MODEL),
    )
    spec_stats = {"n_valid": P(), "centroid": P(), "bb_min": P(), "bb_max": P()}

    step = shard_map(
        _local, mesh=mesh,
        in_specs=(spec_frames, spec_rays, P(), P(), P(), spec_perview, spec_perview),
        out_specs=(spec_cloud, spec_stats),
    )
    return jax.jit(step)
