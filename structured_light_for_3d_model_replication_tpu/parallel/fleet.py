"""Elastic worker fleet: the leader gateway as fabric coordinator (ISSUE 18).

The serving stack (PRs 11/12/14) and the pod fabric (PR 15) were two
halves of one production story that nothing joined: the gateway ran its
engine lanes in-process, while networked ``sl3d worker`` processes had to
be hand-started against a coordinator. This module closes the loop — the
LEADER gateway owns a :class:`FleetSupervisor` that

  signal    samples the admission controller's live scale signals once
            per tick under one lock (queue depth, pending grantable
            items, queue-wait p50/p99, open breakers — the same numbers
            /metrics exports), so every decision journals a coherent
            snapshot of WHY;
  decide    runs the pure :func:`decide` function over that snapshot:
            scale up toward ceil(backlog / fleet_scale_up_queue) clamped
            into [fleet_min_workers, fleet_max_workers], never scale in
            while any work is queued/active, retire down to the floor
            only after ``fleet_scale_in_idle_s`` of verified idleness
            (hysteresis against thrash), and never scale UP on a breaker
            storm (failures are not load — more workers is more fuel);
  enact     spawns ``sl3d worker --spec`` processes (rank-named ``fw0,
            fw1, ...``) that dial a :class:`_FleetBridge` — the
            coordinator wire protocol (PR-8/15 ``_Server``, reused
            verbatim) adapted onto the admission controller, so fleet
            workers drain the SAME weighted-fair grant pool as the
            in-process engine lanes. Their grants carry the scan's calib
            path (a fleet worker hops between tenants' scans) and their
            completes warm the SAME content-addressed store the assembly
            pass reads — byte parity with a solo run is the unchanged
            PR-8 cache-warmer construction, which is also why scale-in
            is safe: a retired/killed worker just loses its leases, the
            items steal back to pending, and the bytes it DID put remain
            valid cache entries.
  journal   writes every decision — scale-up, scale-in, spawn, respawn,
            retire, worker-exit, backoff-after-flap — to the SAME
            fsync'd epoch-fenced ledger as the admission events, with
            the deciding signal snapshot attached. :func:`replay_fleet`
            folds it back (stale epochs ignored, the PR-14 rule) so the
            scaling history replays like everything else and a promoted
            follower resumes the fleet its predecessor ran.
  heal      detects worker death through ``Popen.poll`` + the PR-8 lease
            machinery (a dead worker's leases are dropped immediately —
            ``drop_lane`` steals its items back with a generation bump,
            so the corpse's late completes are refused), then respawns
            the RANK under a capped exponential backoff; a rank that
            dies ``fleet_flap_threshold`` times inside
            ``fleet_flap_window_s`` is FLAPPING and holds at the max
            backoff until the window drains. Respawns reuse the rank but
            bump a GENERATION stamp carried in the worker spec, hello,
            and trace meta, so ``sl3d report`` tells a healed worker
            from a flapping one.

Epoch fencing: the supervisor belongs to one reign. Its journal writes go
through the admission ledger's fence (a deposed leader's append raises
``FencedWrite`` → the supervisor stops and the service demotes), and it
additionally polls :meth:`LeaderLease.superseded` at the top of every
tick — spawn/retire are side effects no fence on the ledger can un-run,
so a zombie supervisor must stop DECIDING the moment the takeover lands,
not merely fail to journal.

Chaos sites: ``fleet.decide`` fires before each decision (a transient
skips the tick, an injected crash fells the service exactly like an
engine-loop crash) and ``worker.spawn`` fires between the journaled
spawn decision and the actual ``Popen`` (a transient schedules a
backoff retry; a crash simulates the supervisor dying mid-action — the
journaled-but-unspawned rank is exactly what resume respawns).
"""
from __future__ import annotations

import copy
import json
import os
import threading
import time

from structured_light_for_3d_model_replication_tpu.parallel import (
    coordinator as coord_mod,
)
from structured_light_for_3d_model_replication_tpu.parallel import election
from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.utils import faults

__all__ = ["FleetSupervisor", "FlapTracker", "decide", "replay_fleet",
           "FleetParams"]


class FleetParams:
    """The decision function's knobs, lifted from ``ServingConfig`` so
    :func:`decide` stays a pure function unit-testable without a config
    object (the lease.py discipline)."""

    __slots__ = ("min_workers", "max_workers", "scale_up_queue",
                 "scale_in_idle_s")

    def __init__(self, min_workers: int = 0, max_workers: int = 4,
                 scale_up_queue: int = 4, scale_in_idle_s: float = 5.0):
        self.min_workers = max(0, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.scale_up_queue = max(1, int(scale_up_queue))
        self.scale_in_idle_s = float(scale_in_idle_s)

    @classmethod
    def from_serving(cls, scfg) -> "FleetParams":
        return cls(min_workers=scfg.fleet_min_workers,
                   max_workers=scfg.fleet_max_workers,
                   scale_up_queue=scfg.fleet_scale_up_queue,
                   scale_in_idle_s=scfg.fleet_scale_in_idle_s)


def decide(sig: dict, live: int, idle_s: float, p: FleetParams) -> dict:
    """One scaling decision from one signal snapshot. Pure — no clock, no
    I/O; ``idle_s`` is how long the caller has observed the service fully
    idle. Returns ``{"action": "scale-up"|"scale-in"|"hold", "target",
    "reason"}`` where ``target`` is the worker count to converge on.

    Rules, in order:
      - never drop below the floor: ``live < min_workers`` scales up
        regardless of load;
      - a breaker storm (open breakers, no backlog growth to serve)
        never scales UP — failures are not load;
      - backlog scales up toward ``ceil(pending / scale_up_queue)`` plus
        one worker per queued-but-unplanned scan, clamped to the cap;
      - any work in flight (pending, granted, queued, active) HOLDS —
        scale-in under load would thrash;
      - a fully idle service retires to the floor only after
        ``scale_in_idle_s`` of continuous idleness (hysteresis).
    """
    backlog = int(sig.get("pending_items", 0))
    queued = int(sig.get("queued_scans", 0))
    active = int(sig.get("active_scans", 0))
    granted = int(sig.get("granted_items", 0))
    breakers = int(sig.get("open_breakers", 0))
    lo, hi = p.min_workers, p.max_workers
    if live < lo:
        return {"action": "scale-up", "target": lo,
                "reason": f"below floor ({live} < {lo})"}
    if backlog or queued:
        desired = (backlog + p.scale_up_queue - 1) // p.scale_up_queue
        desired += queued          # each unplanned scan will add items
        desired = max(lo, min(hi, desired))
        if desired > live:
            if breakers:
                return {"action": "hold", "target": live,
                        "reason": (f"{breakers} open breaker(s): "
                                   f"failures are not load")}
            return {"action": "scale-up", "target": desired,
                    "reason": (f"backlog {backlog} item(s) + {queued} "
                               f"queued scan(s) wants {desired} "
                               f"(p99 wait {sig.get('queue_wait_p99_s', 0)}"
                               f"s)")}
        return {"action": "hold", "target": live,
                "reason": f"backlog served by {live} worker(s)"}
    if active or granted:
        return {"action": "hold", "target": live,
                "reason": f"{active} active scan(s), {granted} granted "
                          f"item(s) in flight"}
    if live > lo and idle_s >= p.scale_in_idle_s:
        return {"action": "scale-in", "target": lo,
                "reason": f"idle {idle_s:.1f}s >= {p.scale_in_idle_s:g}s"}
    return {"action": "hold", "target": live,
            "reason": (f"idle {idle_s:.1f}s" if live > lo
                       else "at floor, nothing to do")}


class FlapTracker:
    """Per-rank death accounting: capped exponential backoff, flap
    damping. Injectable clock — unit-testable with zero real sleeps.

    The backoff derives from how many deaths the rank has inside the
    sliding window: ``backoff_s * 2**(deaths-1)`` capped at
    ``backoff_max_s``; at ``threshold`` deaths the rank is FLAPPING and
    pins to the cap until the window drains (a rank that keeps dying
    gets capacity back slowly, never a tight respawn loop). A clean
    retirement clears the rank's history — a deliberate scale-in is not
    evidence of trouble."""

    def __init__(self, window_s: float = 60.0, threshold: int = 3,
                 backoff_s: float = 0.5, backoff_max_s: float = 30.0,
                 clock=time.monotonic):
        self.window_s = float(window_s)
        self.threshold = int(threshold)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self._deaths: dict[int, list[float]] = {}

    def _pruned(self, rank: int) -> list[float]:
        d = self._deaths.setdefault(rank, [])
        cut = self._clock() - self.window_s
        while d and d[0] <= cut:
            d.pop(0)
        return d

    def record_exit(self, rank: int, clean: bool = False) -> None:
        if clean:
            self._deaths.pop(rank, None)
            return
        self._pruned(rank).append(self._clock())

    def deaths(self, rank: int) -> int:
        return len(self._pruned(rank))

    def flapping(self, rank: int) -> bool:
        return self.threshold > 0 and self.deaths(rank) >= self.threshold

    def backoff(self, rank: int) -> float:
        n = self.deaths(rank)
        if n <= 0:
            return 0.0
        if self.flapping(rank):
            return self.backoff_max_s
        return min(self.backoff_max_s,
                   self.backoff_s * (2.0 ** (n - 1)))


class _FleetBridge:
    """The coordinator wire protocol served FROM the admission
    controller: fleet workers speak the exact PR-8/15 newline-JSON ops
    (hello/next/beat/complete/failed) against the serving lease table,
    so ``worker.py`` needed no new client code. Hosted by the reused
    ``coordinator._Server`` — which expects ``crash``/``done`` for its
    injected-crash plumbing; the supervisor re-raises a stored crash on
    its next tick (the coordinator-poll-loop shape)."""

    def __init__(self, sup: "FleetSupervisor"):
        self.sup = sup
        self.adm = sup.adm
        self.crash: BaseException | None = None
        self.done = threading.Event()

    def op_hello(self, req: dict) -> dict:
        w = str(req.get("worker", ""))
        self.sup.note_hello(w, pid=int(req.get("pid", 0)),
                            generation=int(req.get("generation", 0)),
                            addr=req.get("addr") or "")
        return {"ok": True, "run_id": self.sup.run_id,
                "lease_s": self.adm.leases.lease_s,
                "heartbeat_s": self.sup.heartbeat_s}

    def op_next(self, req: dict) -> dict:
        w = str(req.get("worker", ""))
        if self.sup.is_retiring(w):
            # the scale-in drain: the worker exits clean on this answer;
            # anything it still held steals away at reap (safe by the
            # lease construction)
            return {"shutdown": True}
        grants = self.adm.next_views(w, 1)
        if not grants:
            return {"wait": self.sup.idle_wait_s}
        iid, gen, spec = grants[0]
        with self.adm.lock:
            job = self.adm.jobs.get(spec["scan"])
            calib = job.calib if job is not None else ""
        # fleet workers serve MANY scans: the grant carries the item's
        # calib (the engine lanes read it from their in-process _ScanCtx)
        return {"grant": {"id": iid, "gen": gen, "kind": "view",
                          "spec": dict(spec, calib=calib)}}

    def op_beat(self, req: dict) -> dict:
        return {"ok": self.adm.beat(str(req.get("worker", "")))}

    def op_complete(self, req: dict) -> dict:
        ok = self.adm.complete(req["item"], str(req.get("worker", "")),
                               int(req.get("gen", 0)))
        return {"ok": "accepted" if ok else "stolen"}

    def op_failed(self, req: dict) -> dict:
        self.adm.failed(req["item"], str(req.get("worker", "")),
                        int(req.get("gen", 0)),
                        error=req.get("error", ""))
        return {"ok": True}


class FleetSupervisor:
    """One reign's autoscaler. Owned by the leader (or solo) gateway:
    constructed with that reign's admission controller, started after
    promotion, closed on demotion — its ledger writes fence exactly like
    the engine's. See the module docstring for the loop."""

    def __init__(self, root: str, cfg, adm, store_root: str,
                 steps: tuple = (), log=print, registry=None,
                 lease: "election.LeaderLease | None" = None,
                 on_demote=None, on_crash=None, run_id: str = "",
                 clock=time.monotonic, spawn_fn=None):
        scfg = cfg.serving
        self.root = os.path.abspath(root)
        self.fleet_dir = os.path.join(self.root, "fleet")
        self.cfg = cfg
        self.adm = adm
        self.store_root = store_root
        self.steps = tuple(steps)
        self.log = log
        self.registry = registry
        self.lease = lease
        self.on_demote = on_demote
        self.on_crash = on_crash
        self._clock = clock
        self._spawn_fn = spawn_fn        # injectable for unit tests
        self.params = FleetParams.from_serving(scfg)
        self.poll_s = max(0.05, float(scfg.fleet_poll_s))
        self.idle_wait_s = min(0.2, self.poll_s)
        self.heartbeat_s = float(cfg.coordinator.heartbeat_s)
        self.run_id = run_id or "fleet"
        self.flap = FlapTracker(window_s=scfg.fleet_flap_window_s,
                                threshold=scfg.fleet_flap_threshold,
                                backoff_s=scfg.fleet_backoff_s,
                                backoff_max_s=scfg.fleet_backoff_max_s,
                                clock=clock)
        self.target = self.params.min_workers
        self._lock = threading.Lock()
        self._gen: dict[int, int] = {}          # rank -> latest generation
        self._workers: dict[int, dict] = {}     # rank -> {proc, gen, ...}
        self._retiring: set[str] = set()
        self._respawn_at: dict[int, float] = {}
        self._hellos: dict[str, dict] = {}      # worker -> pid/gen/addr
        self._idle_since = self._clock()
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.bridge: _FleetBridge | None = None
        self.server = None
        self._cfg_path = ""

    # ---- bridge-facing state ---------------------------------------------

    def is_retiring(self, worker: str) -> bool:
        with self._lock:
            return worker in self._retiring

    def note_hello(self, worker: str, pid: int = 0, generation: int = 0,
                   addr: str = "") -> None:
        with self._lock:
            self._hellos[worker] = {"pid": pid, "generation": generation,
                                    "addr": addr}

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.fleet_dir, exist_ok=True)
        # the workers' config: the EXACT serving config (clean steps and
        # numerics are view-cache key material — any drift would cache
        # wrong bytes under right keys), minus anything recursive
        wcfg = copy.deepcopy(self.cfg)
        wcfg.coordinator.workers = 0
        wcfg.serving.fleet_enabled = False
        wcfg.serving.ha_enabled = False
        self._cfg_path = os.path.join(self.fleet_dir, "cfg.json")
        wcfg.save(self._cfg_path)
        self.bridge = _FleetBridge(self)
        self.server = coord_mod._Server(
            self.bridge, 0, self.log,
            listen=self.cfg.serving.fleet_listen,
            secret=self.cfg.serving.fleet_secret)
        inherited = replay_fleet(self.adm.ledger.path)
        self._gen.update(inherited["generations"])
        resume = [r for r in inherited["live"]
                  if r < self.params.max_workers]
        if resume:
            # a promoted follower resumes the fleet it inherited: same
            # ranks, bumped generations (the old incarnations either died
            # with the old leader or are dialing its dead bridge)
            self._journal("resume", ranks=resume,
                          target=int(inherited["target"]))
            self.target = max(self.params.min_workers, len(resume))
            for rank in resume:
                try:
                    self._spawn(rank, action="respawn",
                                sig={"resumed": True})
                except (faults.InjectedCrash, election.FencedWrite):
                    raise
                except BaseException as e:
                    self.log(f"[fleet] resume spawn fw{rank} failed: {e}")
        self._thread = threading.Thread(target=self._loop,
                                        name="sl3d-fleet", daemon=True)
        self._thread.start()
        self.log(f"[fleet] supervisor up on {self.server.endpoint} "
                 f"(target {self.target}, bounds "
                 f"[{self.params.min_workers}, {self.params.max_workers}]"
                 + (f", resumed {resume}" if resume else "") + ")")

    def close(self, kill_budget_s: float = 5.0) -> None:
        """Retire every worker (clean shutdown answers first, SIGTERM
        then SIGKILL past the budget), stop the bridge and the loop.
        Called on demotion and service stop; never journals — a deposed
        supervisor's ledger writes would be fenced anyway, and the new
        leader's resume owns the fleet's story from here."""
        self._stop.set()
        with self._lock:
            self._retiring.update(f"fw{r}" for r in self._workers)
            procs = {r: w["proc"] for r, w in self._workers.items()}
            self._respawn_at.clear()
        t_end = self._clock() + max(0.0, kill_budget_s)
        while procs and self._clock() < t_end:
            for r in [r for r, p in procs.items() if p.poll() is not None]:
                procs.pop(r)
            if procs:
                time.sleep(0.05)
        for p in procs.values():
            try:
                p.terminate()
            except OSError:
                pass
        for p in procs.values():
            try:
                p.wait(timeout=2.0)
            except Exception:
                try:
                    p.kill()
                except OSError:
                    pass
        with self._lock:
            for rank in list(self._workers):
                self.adm.drop_lane(f"fw{rank}", "fleet-stop")
            self._workers.clear()
        if self.server is not None:
            self.server.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ---- the loop --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except faults.InjectedCrash as e:
                # the supervisor dies like the engine: simulated process
                # death, restart-resume (or a takeover) is the recovery
                if self.on_crash is not None:
                    self.on_crash("fleet", e)
                return
            except election.FencedWrite as e:
                self.log(f"[fleet] decision fenced ({e}) — stopping")
                if self.on_demote is not None:
                    self.on_demote(f"fleet: {e}")
                return
            except BaseException as e:
                self.log(f"[fleet] tick error: {type(e).__name__}: {e}")
            self._stop.wait(self.poll_s)

    def _tick(self) -> None:
        if self.bridge is not None and self.bridge.crash is not None:
            crash, self.bridge.crash = self.bridge.crash, None
            raise crash
        if self.lease is not None and self.lease.superseded():
            # a newer epoch exists: stop DECIDING now — spawn/retire are
            # side effects the ledger fence cannot un-run
            self.log("[fleet] superseded by a newer epoch — stopping")
            self._stop.set()
            if self.on_demote is not None:
                self.on_demote("fleet: epoch superseded")
            return
        now = self._clock()
        self._reap()
        self._respawn_due(now)
        sig = self.adm.signals()
        busy = (sig["pending_items"] or sig["granted_items"]
                or sig["queued_scans"] or sig["active_scans"])
        if busy:
            self._idle_since = now
        # chaos: a transient here skips the tick (the decision simply
        # doesn't happen this round), a crash fells the supervisor
        faults.fire("fleet.decide", item=str(self._ticks))
        self._ticks += 1
        with self._lock:
            live = len(self._workers) + len(self._respawn_at)
        d = decide(sig, live, now - self._idle_since, self.params)
        if d["action"] == "scale-up":
            self._journal("scale-up", target=d["target"],
                          reason=d["reason"], signals=sig)
            self.target = d["target"]
            self._scale_up(sig)
        elif d["action"] == "scale-in":
            self._journal("scale-in", target=d["target"],
                          reason=d["reason"], signals=sig)
            self.target = d["target"]
            self._scale_in()
        if self.registry is not None:
            self.registry.set_gauge("sl3d_fleet_target",
                                    float(self.target))
            self.registry.set_gauge("sl3d_fleet_live",
                                    float(len(self._workers)))

    def _reap(self) -> None:
        """Collect exited workers: drop their leases NOW (items steal
        back with a generation bump — the corpse's late completes are
        refused), then classify: a retiring worker's exit is a clean
        retirement whatever its rc; anything else is a death that
        schedules a backoff respawn."""
        with self._lock:
            exited = [(r, w) for r, w in self._workers.items()
                      if w["proc"].poll() is not None]
            for r, _ in exited:
                del self._workers[r]
        for rank, w in exited:
            name = f"fw{rank}"
            rc = w["proc"].returncode
            stolen = self.adm.drop_lane(name, reason=f"worker-exit-{rc}")
            with self._lock:
                was_retiring = name in self._retiring
                self._retiring.discard(name)
            if was_retiring:
                self.flap.record_exit(rank, clean=True)
                self._journal("retired", rank=rank, gen=w["gen"], rc=rc,
                              stolen=stolen)
                self._inc("sl3d_fleet_retired_total")
                continue
            self.flap.record_exit(rank)
            back = self.flap.backoff(rank)
            flapping = self.flap.flapping(rank)
            self._journal("worker-exit", rank=rank, gen=w["gen"], rc=rc,
                          stolen=stolen, backoff_s=round(back, 3),
                          flapping=flapping)
            self.log(f"[fleet] {netutil.worker_tag(name, w['gen'])} died "
                     f"(rc {rc}, {stolen} item(s) stolen back) — respawn "
                     f"in {back:.2f}s"
                     + (" [FLAPPING]" if flapping else ""))
            self._inc("sl3d_fleet_worker_exits_total")
            if flapping:
                self._inc("sl3d_fleet_flap_damped_total")
            with self._lock:
                self._respawn_at[rank] = self._clock() + back

    def _respawn_due(self, now: float) -> None:
        with self._lock:
            due = sorted(r for r, t in self._respawn_at.items()
                         if t <= now)
        for rank in due:
            with self._lock:
                self._respawn_at.pop(rank, None)
                over = len(self._workers) >= self.target
            if over:
                continue        # target shrank while the rank backed off
            self._spawn(rank, action="respawn")

    def _scale_up(self, sig: dict) -> None:
        while True:
            with self._lock:
                live = len(self._workers) + len(self._respawn_at)
                used = set(self._workers) | set(self._respawn_at)
            if live >= self.target:
                return
            rank = next(r for r in range(self.params.max_workers + 1)
                        if r not in used)
            self._spawn(rank, action="spawn", sig=sig)

    def _scale_in(self) -> None:
        with self._lock:
            # highest ranks first; a scheduled respawn is retired by
            # simply cancelling it
            while (len(self._workers) + len(self._respawn_at)
                   > self.target and self._respawn_at):
                self._respawn_at.pop(max(self._respawn_at))
            excess = sorted(self._workers, reverse=True)[
                :max(0, len(self._workers) + len(self._respawn_at)
                     - self.target)]
            ranks = []
            for rank in excess:
                name = f"fw{rank}"
                if name not in self._retiring:
                    self._retiring.add(name)
                    ranks.append(rank)
        for rank in ranks:
            self._journal("retire", rank=rank,
                          held=len(self.adm.leases.worker_items(
                              f"fw{rank}")))

    def _spawn(self, rank: int, action: str = "spawn",
               sig: dict | None = None) -> None:
        gen = self._gen.get(rank, -1) + 1
        self._gen[rank] = gen
        name = f"fw{rank}"
        # journal BEFORE the side effect (and through the fence): a
        # crash between journal and Popen leaves a live-but-unspawned
        # rank in the ledger — exactly what the next resume respawns
        self._journal(action, rank=rank, gen=gen, signals=sig or {})
        try:
            faults.fire("worker.spawn", item=name)
        except faults.InjectedCrash:
            raise
        except BaseException as e:
            # transient spawn failure: journal it out of the live set and
            # retry under the rank's backoff
            self.flap.record_exit(rank)
            back = self.flap.backoff(rank)
            self._journal("spawn-failed", rank=rank, gen=gen,
                          error=str(e)[:200], backoff_s=round(back, 3))
            self.log(f"[fleet] spawn {name} failed ({e}); retry in "
                     f"{back:.2f}s")
            with self._lock:
                self._respawn_at[rank] = self._clock() + back
            return
        fabric = None
        if self.cfg.serving.fleet_listen:
            fabric = {"connect": self.server.endpoint,
                      "secret": self.cfg.serving.fleet_secret}
        if self._spawn_fn is not None:
            proc = self._spawn_fn(rank, gen)
        else:
            proc = coord_mod._spawn_worker(
                rank, max(1, self.target), self.server.port,
                self.fleet_dir, self._cfg_path, "", "", self.fleet_dir,
                self.steps, fabric=fabric, name=name, generation=gen,
                cache_root=self.store_root)
        with self._lock:
            self._workers[rank] = {"proc": proc, "gen": gen,
                                   "spawned_at": self._clock()}
        self._inc("sl3d_fleet_spawns_total")
        self.log(f"[fleet] spawned {netutil.worker_tag(name, gen)} "
                 f"(pid {proc.pid})")

    # ---- plumbing --------------------------------------------------------

    def _journal(self, action: str, **fields) -> None:
        self.adm.ledger.event("fleet", action=action, **fields)

    def _inc(self, name: str) -> None:
        if self.registry is not None:
            self.registry.inc(name)

    def state(self) -> dict:
        """Live fleet state in the same shape :func:`replay_fleet`
        returns — the soak's replay-parity assertion compares the two."""
        with self._lock:
            return {"target": self.target,
                    "live": sorted(self._workers),
                    "generations": {r: w["gen"]
                                    for r, w in self._workers.items()},
                    "pids": {r: w["proc"].pid
                             for r, w in self._workers.items()},
                    "retiring": sorted(self._retiring),
                    "respawning": sorted(self._respawn_at),
                    "hellos": dict(self._hellos)}


def replay_fleet(path: str) -> dict:
    """Fold the ledger's ``fleet`` events into the final fleet state,
    under the same epoch fence as :func:`replay_serving` (a zombie
    supervisor's raced-in decisions are ignored). Returns ``{"target",
    "live": [ranks], "generations": {rank: gen}, "events", "max_epoch",
    "stale_ignored"}`` — what a promoted follower resumes and what the
    soak compares against the live supervisor's :meth:`state`."""
    target = 0
    live: set[int] = set()
    gens: dict[int, int] = {}
    events = max_epoch = stale_ignored = 0
    if not os.path.exists(path):
        return {"target": 0, "live": [], "generations": {}, "events": 0,
                "max_epoch": 0, "stale_ignored": 0}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue            # torn tail
            e = ev.get("epoch")
            if e is not None:
                e = int(e)
                if e < max_epoch:
                    stale_ignored += 1
                    continue
                max_epoch = e
            if ev.get("type") != "fleet":
                continue
            events += 1
            action = ev.get("action")
            if "target" in ev:
                target = int(ev["target"])
            rank = ev.get("rank")
            if rank is None:
                continue
            rank = int(rank)
            if action in ("spawn", "respawn"):
                live.add(rank)
                gens[rank] = max(gens.get(rank, 0),
                                 int(ev.get("gen", 0)))
            elif action in ("worker-exit", "retired", "spawn-failed"):
                live.discard(rank)
    return {"target": target, "live": sorted(live), "generations": gens,
            "events": events, "max_epoch": max_epoch,
            "stale_ignored": stale_ignored}
