#!/usr/bin/env python
"""Incremental-assembly smoke for CI (run by tools/ci_tier1.sh).

Renders a 5-view synthetic turntable dataset and runs the same scan
three ways: single-process (the trusted baseline), a 2-worker pod with
``merge.incremental`` ON (the ISSUE-17 fold lane), and a 2-worker pod
with it OFF (the barrier arm). Asserts the incremental-assembly
contract:

  - all three runs exit clean and merged.ply + model.stl are
    BYTE-IDENTICAL across them (merge.incremental is a SCHEDULE knob:
    the fold lane only re-orders the proven computation)
  - the fold lane actually folded the whole chain before the last item
    settled (folded_views == views)
  - the tail-wall ratio holds: the incremental pod's assembly tail
    (last-item-settled -> artifacts-on-disk) is no slower than the
    barrier pod's tail * 1.25 — with every view and pair pre-folded,
    the tail is the postprocess only, so it must not regress even on a
    noisy 1-CPU CI box

Prints ``ASSEMBLY_SMOKE=ok`` (exit 0) or ``ASSEMBLY_SMOKE=FAIL (...)``
(exit 1).
"""
import os
import shutil
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

VIEWS = 5


def fail(why: str) -> int:
    print(f"ASSEMBLY_SMOKE=FAIL ({why})")
    return 1


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _cfg(workers: int = 0, incremental: bool = False):
    from structured_light_for_3d_model_replication_tpu.config import Config

    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = 128, 64
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 512
    cfg.merge.icp_iters = 10
    cfg.merge.incremental = incremental
    cfg.mesh.depth = 5
    cfg.mesh.density_trim_quantile = 0.0
    cfg.coordinator.workers = workers
    return cfg


def main() -> int:
    os.environ.pop("SL3D_FAULTS", None)
    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    tmp = tempfile.mkdtemp(prefix="slasm_")
    try:
        root = os.path.join(tmp, "dataset")
        rc = cli_main(["synth", root, "--views", str(VIEWS),
                       "--cam", "160x120", "--proj", "128x64"])
        if rc != 0:
            return fail(f"synth rc={rc}")
        calib = os.path.join(root, "calib.mat")

        def run(out, **kw):
            return stages.run_pipeline(calib, root, out, cfg=_cfg(**kw),
                                       steps=("statistical",),
                                       log=lambda m: None)

        out_sp = os.path.join(tmp, "out_single")
        rep_sp = run(out_sp)
        if rep_sp.failed or rep_sp.degraded:
            return fail("single-process run not clean")

        out_inc = os.path.join(tmp, "out_incremental")
        rep_inc = run(out_inc, workers=2, incremental=True)
        if rep_inc.degraded:
            return fail("incremental pod degraded")
        out_bar = os.path.join(tmp, "out_barrier")
        rep_bar = run(out_bar, workers=2, incremental=False)
        if rep_bar.degraded:
            return fail("barrier pod degraded")

        for name in ("merged.ply", "model.stl"):
            base = _read(os.path.join(out_sp, name))
            for out, arm in ((out_inc, "incremental"), (out_bar, "barrier")):
                p = os.path.join(out, name)
                if not os.path.exists(p):
                    return fail(f"{name} missing from {arm} pod")
                if _read(p) != base:
                    return fail(f"{name} differs in the {arm} pod vs "
                                f"single-process")

        asm = rep_inc.assembly or {}
        lane = (rep_inc.coordinator or {}).get("assembly_lane") or {}
        if lane.get("folded_views") != VIEWS:
            return fail(f"fold lane folded {lane.get('folded_views')} of "
                        f"{VIEWS} view(s) before settle")
        if asm.get("used_views") != VIEWS:
            return fail(f"assembly pass seeded only {asm.get('used_views')} "
                        f"of {VIEWS} folded view(s)")

        tail_i = ((rep_inc.coordinator or {}).get("assembly") or {}).get(
            "tail_s")
        tail_b = ((rep_bar.coordinator or {}).get("assembly") or {}).get(
            "tail_s")
        if tail_i is None or tail_b is None:
            return fail(f"tail not reported (inc={tail_i}, bar={tail_b})")
        # with the whole chain pre-folded the tail is postprocess-only —
        # it must not exceed the barrier tail (margin for 1-CPU CI noise)
        if tail_i > tail_b * 1.25 + 0.5:
            return fail(f"tail-wall ratio regressed: incremental tail "
                        f"{tail_i:.2f}s vs barrier {tail_b:.2f}s")

        print(f"ASSEMBLY_SMOKE=ok (2 workers; {lane['folded_views']}/"
              f"{VIEWS} views folded in-pod; tail {tail_i:.2f}s "
              f"incremental vs {tail_b:.2f}s barrier; PLY+STL "
              f"byte-identical across incremental/barrier/single-process)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
