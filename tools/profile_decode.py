"""Profile the flagship decode+triangulate on the ambient backend.

Compares the jnp lax.map path (plane table and quadratic plane eval)
against the fused single-pass Mosaic kernel at the bench's 24-view
1080p shape. Uses the bench scene cache (.bench_cache.npz).

Self-terminating; never wrap in a kill timer near expected runtime
(SIGTERM mid-TPU-claim wedges the tunnel — BENCH_NOTES.md).
"""
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    views = int(sys.argv[1]) if len(sys.argv) > 1 else 24

    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    lock = tpulock.acquire_tpu_lock(ROOT, timeout=60)  # noqa: F841
    if lock is None:  # held for process lifetime; fd close releases
        sys.exit("another TPU client holds .tpu_lock — not opening a "
                 "concurrent claim (the lock dies with its holder)")

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from structured_light_for_3d_model_replication_tpu.models.scanner import (
        SLScanner,
    )
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    cache = os.path.join(ROOT, ".bench_cache.npz")
    if not os.path.exists(cache):
        sys.exit("no .bench_cache.npz — run `python bench.py` once first")
    with np.load(cache) as z:
        frames = z["frames"]
    cam = (frames.shape[2], frames.shape[1])
    print(f"backend={jax.default_backend()} pallas={pk.pallas_mode()} "
          f"views={views} cam={cam}")
    rig = syn.default_rig(cam_size=cam, proj_size=cam)
    base = jax.block_until_ready(jnp.asarray(frames))
    stack = jax.block_until_ready(
        jnp.stack([jnp.roll(base, i * 7, axis=2) for i in range(views)]))

    ref_pts = None
    # the fused arm forces use_fused=True (auto-dispatch now defaults to
    # jnp after the r4 on-chip A/B; the profiler measures both regardless)
    for label, plane_eval, fused in (("table-jnp", "table", False),
                                     ("quad-jnp", "quadratic", False),
                                     ("quad-fused", "quadratic", True)):
        sc = SLScanner(rig.calibration(), cam, cam, row_mode=1,
                       plane_eval=plane_eval)
        if fused and not sc._fuse_capable(stack):
            print(f"{label:10s} fused kernel unavailable for this shape")
            continue
        path = "fused" if fused else "jnp"

        def run():
            out = sc.forward_views(stack, thresh_mode="manual",
                                   shadow_val=40.0, contrast_val=10.0,
                                   use_fused=fused)
            jax.block_until_ready(out.points)
            return out

        t0 = time.perf_counter()
        out = run()
        first = time.perf_counter() - t0
        best = np.inf
        for _ in range(3):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        mpix = views * cam[0] * cam[1] / best / 1e6
        pts = np.asarray(out.points[0])[np.asarray(out.valid[0])]
        drift = ""
        if ref_pts is None:
            ref_pts = pts
        elif len(pts) == len(ref_pts):
            drift = f" max|dp|={np.abs(pts - ref_pts).max():.2e}mm"
        print(f"{label:10s} path={path:5s} first={first:6.2f}s "
              f"steady={best:6.3f}s {mpix:7.1f} Mpix/s "
              f"valid0={len(pts)}{drift}")

    # fused-kernel tile sweep: the default (8, 256) clamps to (8, 128) at
    # 1080p (1920 % 256 != 0) -> 2025 grid steps per view, plausibly
    # overhead-bound (r4 bench: fused 285 Mpix/s vs jnp 476). Bigger tiles
    # amortize grid overhead; VMEM stays comfortable (46*th*tw u8 + ~9
    # th*tw f32 planes). Measured here so the default is set from on-chip
    # evidence, not theory.
    sc = SLScanner(rig.calibration(), cam, cam, row_mode=1,
                   plane_eval="quadratic")
    if not sc._fuse_capable(stack):
        print("fused kernel unavailable for this shape — no tile sweep")
        return
    rays = sc.rays.reshape(cam[1], cam[0], 3)
    thr_v = jnp.stack([jnp.full((views,), 40.0, jnp.float32),
                       jnp.full((views,), 10.0, jnp.float32)], axis=1)
    n_cols, n_rows, n_use_col, n_use_row, downsample, row_mode, _ = sc._static
    for th, tw in ((8, 128), (8, 384), (8, 640), (16, 384), (16, 640),
                   (24, 640), (8, 1920), (40, 1920)):
        if cam[1] % th or cam[0] % tw:
            continue

        def run_tiles():
            pts, valid, tex = pk.scan_points_fused_views(
                stack, thr_v, rays, sc.oc, sc.poly_col, sc.poly_row,
                sc.epipolar_tol, n_cols=n_cols, n_rows=n_rows,
                n_use_col=n_use_col, n_use_row=n_use_row,
                row_mode=row_mode, downsample=downsample,
                tile_h=th, tile_w=tw)
            jax.block_until_ready(pts)

        try:
            t0 = time.perf_counter()
            run_tiles()
            first = time.perf_counter() - t0
            best = np.inf
            for _ in range(3):
                t0 = time.perf_counter()
                run_tiles()
                best = min(best, time.perf_counter() - t0)
        except Exception as e:
            print(f"fused tile {th:3d}x{tw:<4d}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}")
            continue
        mpix = views * cam[0] * cam[1] / best / 1e6
        print(f"fused tile {th:3d}x{tw:<4d}: first={first:6.2f}s "
              f"steady={best:6.3f}s {mpix:7.1f} Mpix/s")


if __name__ == "__main__":
    main()
