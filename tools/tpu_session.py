"""One-shot TPU validation session: run the moment the device tunnel is
healthy again, in ONE serialized process chain (one TPU client at a time —
concurrent clients and mid-claim kills are what wedge the pool tunnel, see
BENCH_NOTES.md).

Sequence (each step is a subprocess that fully exits before the next):
  1. preflight probe (3 min bound) — abort politely if the tunnel is wedged
  2. warm — bench.py's own child entry run once with a limit far above any
     compile bill, so every executable lands in the persistent cache in a
     process that is allowed to finish (round-4 lesson: a cold cache put
     the bench merge >15 min into compiles and the bench parent's child
     watchdog killed the TPU client mid-claim — the wedge trigger itself)
  3. python bench.py — the full record line, now warm end-to-end; it
     exercises the whole pipeline with per-phase provenance and its own
     CPU-fallback child, so it doubles as the smoke run
  4. tools/profile_merge.py --register — per-stage merge timings + the
     trial/ICP sweep (the round-3 wedge-window optimizations, re-measured)
  5. accelerator smoke test (pytest tests/test_tpu_smoke.py) — every device
     path at real shapes, incl. the voxelized outlier probe and the
     bitexact-on-device record
  6. write BENCH_SELF_r<N>.json from the bench line

Timeouts are deliberately FAR above expected runtimes (the wedge lesson:
never kill a TPU client anywhere near its expected finish); pass --step to
run a single step instead of the chain.
"""
import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# expected wall ~3-8 min each on a warm cache; limits are 4-10x that.
# warm FIRST: it is bench.py's own child entry point run once with a limit
# far above any compile bill, so every executable lands in the persistent
# cache in a process that is ALLOWED to finish. Round-4 lesson: a cold
# cache put bench's merge phase >15 min into compiles and the parent's
# 20-min child watchdog killed the TPU client mid-claim — the exact wedge
# trigger the watchdog exists to avoid. After the warm step, bench's own
# child runs minutes inside its watchdog instead of straddling it.
STEPS = [
    ("warm", [sys.executable, "bench.py", "--child",
              os.path.join(ROOT, ".bench_warm.json"), "--views=24"], 5400),
    ("bench", [sys.executable, "bench.py"], 4200),
    ("profile_merge", [sys.executable, "tools/profile_merge.py",
                       "--register", "--postprocess-ab", "--outlier-ab"],
     3000),
    ("smoke", [sys.executable, "-m", "pytest",
               "tests/test_tpu_smoke.py", "-x", "-q", "-rs"], 2400),
]


def log(msg: str) -> None:
    print(f"[tpu-session +{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def _save_step_log(name: str, out: str, err: str) -> None:
    """Persist a step's full streams — the 2000-char tail printed inline
    lost the phase-by-phase child log exactly when a killed step needed
    diagnosing (round-4 bench kill left no record of which merge stage the
    20 minutes went into)."""
    d = os.path.join(ROOT, "tools", "session_logs")
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{name}.{time.strftime('%m%d-%H%M%S')}.log")
        with open(path, "w") as f:
            f.write("==== stdout ====\n" + (out or "") +
                    "\n==== stderr ====\n" + (err or ""))
        log(f"step {name}: full log -> {os.path.relpath(path, ROOT)}")
    except OSError:
        pass


def run_step(name: str, cmd, limit: int) -> tuple[int, str]:
    log(f"step {name}: {' '.join(cmd)} (limit {limit}s)")
    t0 = time.time()
    # own session/process GROUP: a limit-kill must take down the step's
    # whole tree — killing only the direct child (e.g. pytest) orphans the
    # TPU-client grandchild it spawned, which then holds a claim while the
    # watcher starts new clients: the documented concurrent-client wedge
    import signal

    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    proc = subprocess.Popen(cmd, cwd=ROOT, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True,
                            env={**os.environ,
                                 # pid-valued: children watch this holder
                                 # and re-claim the flock if it dies (see
                                 # utils/tpulock._watch_holder)
                                 tpulock.HOLD_ENV: str(os.getpid())})
    try:
        out, err = proc.communicate(timeout=limit)
        rc = proc.returncode
        log(f"step {name}: rc={rc} in {time.time() - t0:.0f}s")
    except subprocess.TimeoutExpired:
        log(f"step {name} EXCEEDED {limit}s — killing its process group "
            f"(tunnel may be re-wedged; re-probe before retrying)")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        rc = -9
    # bench.py logs every phase to STDERR (stdout carries only the final
    # JSON line) — a killed bench with no stderr tail would leave zero
    # trace of which phase stalled
    _save_step_log(name, out, err)
    tail = (err or "")[-2000:]
    if tail:
        print(tail, file=sys.stderr, flush=True)
    return rc, out or ""


def parse_clean_bench_line(out: str, log=log):
    """Last JSON line of a bench run, or None if absent or degraded.

    A degraded (CPU-fallback / errored) line must NOT become the
    BENCH_SELF record: bench.py points future degraded runs at the newest
    BENCH_SELF_r*.json as the clean first-party TPU line.
    """
    line = None
    for cand in reversed(out.strip().splitlines()):
        try:
            parsed = json.loads(cand)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):  # skip stray scalar JSON noise
            line = parsed
            break
    if line is None:
        return None
    if line.get("backend") != "tpu" or line.get("error"):
        log(f"bench line degraded (backend={line.get('backend')}, "
            f"error={line.get('error')!r}) — not recording it")
        return None
    return line


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--step", choices=[s[0] for s in STEPS], default=None,
                    help="run one step instead of the whole chain")
    ap.add_argument("--round", type=int, default=4,
                    help="round number for the BENCH_SELF_r<N>.json record")
    ap.add_argument("--skip-smoke", action="store_true")
    ap.add_argument("--skip-preflight", action="store_true",
                    help="caller (tools/tpu_watch.py) just probed; don't "
                         "spend a second claim cycle re-probing")
    args = ap.parse_args()

    sys.path.insert(0, ROOT)
    from structured_light_for_3d_model_replication_tpu.utils.preflight import (
        accelerator_preflight,
    )
    from structured_light_for_3d_model_replication_tpu.utils.tpulock import (
        acquire_tpu_lock,
    )

    # one TPU client at a time, repo-wide: hold the claim lock for the whole
    # chain so an independent entry point (the driver's round-end bench.py,
    # a manual run) queues behind us instead of opening a concurrent claim
    lock = acquire_tpu_lock(ROOT, timeout=120)
    if lock is None:
        sys.exit("another process holds .tpu_lock (a TPU client is active) "
                 "— not starting; the lock dies with its holder, retry then")

    # on this rig "ok (cpu)" means the accelerator plugin failed FAST —
    # a wedge variant (r4), not a healthy verdict: a chain run on the cpu
    # ambient backend would burn hours measuring the wrong thing
    def healthy(status: str, detail: str) -> bool:
        return status == "ok" and detail != "cpu"

    if not args.skip_preflight:
        status, detail = accelerator_preflight()
        log(f"preflight: {status} ({detail})")
        if not healthy(status, detail):
            sys.exit(f"tunnel not healthy ({status}, {detail}) — not "
                     f"starting any TPU work")

    steps = [s for s in STEPS if args.step is None or s[0] == args.step]
    if args.skip_smoke:
        steps = [s for s in steps if s[0] != "smoke"]
    bench_line = None
    aborted = False
    for i, (name, cmd, limit) in enumerate(steps):
        if i > 0:
            # the previous step exited; confirm the tunnel still answers
            # (init + one op) before opening the next claim
            status, detail = accelerator_preflight()
            log(f"inter-step preflight: {status} ({detail})")
            if not healthy(status, detail):
                log(f"tunnel unhealthy before step {name} — aborting the "
                    f"rest of the session")
                aborted = True
                break
        rc, out = run_step(name, cmd, limit)
        if name == "bench" and rc == 0:
            bench_line = parse_clean_bench_line(out, log)
        if name != "bench" or rc != 0 or bench_line is None:
            # always keep the step's tail in the session log — a failed
            # bench during a rare recovery window is exactly when its
            # stdout matters most
            print(out[-4000:], flush=True)
        if rc == -9:
            # a step that had to be KILLED at its limit means the tunnel
            # stalled mid-claim; every further step would stall the same
            # way (round-4 lesson: smoke sat 28 min at zero I/O while the
            # chain was set to push on regardless)
            log(f"step {name} was killed at its limit — aborting the "
                f"session; re-probe before any new TPU work")
            aborted = True
            break
        if rc != 0 and name == "smoke":
            log("smoke failed — bench/profile measurements (if any) were "
                "already captured; their provenance fields tell the story")

    if bench_line is not None:
        rec = os.path.join(ROOT, f"BENCH_SELF_r{args.round:02d}.json")
        bench_line["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime())
        bench_line["recorded_by"] = "tools/tpu_session.py"
        with open(rec, "w") as f:
            json.dump(bench_line, f, indent=1)
        log(f"wrote {rec}: value={bench_line.get('value')} "
            f"backend={bench_line.get('backend')} "
            f"error={bench_line.get('error')}")
        print(json.dumps(bench_line), flush=True)
    log("session done")
    # exit status is the contract with tools/tpu_watch.py: a session that
    # produced the bench record IS complete (exit 0) even if later steps
    # aborted — re-running the whole chain would re-risk the tunnel for
    # data already captured. Without a record (and one was expected),
    # exit nonzero so the watcher keeps trying.
    want_bench = args.step in (None, "bench")
    if want_bench:
        if bench_line is None:
            sys.exit(3)
    elif aborted:
        sys.exit(3)


if __name__ == "__main__":
    main()
