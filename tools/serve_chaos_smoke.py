#!/usr/bin/env python
"""CI smoke for durable serving (ISSUE 13): a REAL ``sl3d serve``
process, felled by an injected ``serve.crash`` at the assembly boundary
(the process exits 137, a kill -9 twin, with the ledger fd dangling),
then restarted over the same state root.

Asserts, end to end over HTTP against the real CLI entry:
  * the crashed process exited 137 WITHOUT journaling a finish — the
    accepted request is non-terminal in the replayed ledger;
  * the restarted (fault-free) process resumes the request to DONE with
    ZERO recompute (``views_computed == 0`` — every view is a cache hit)
    and its /result PLY + STL are byte-identical to a solo
    ``run_pipeline`` of the same input: the PR-8 parity construction
    carried across process death;
  * the client's durable scan_id is idempotent across the crash — the
    same re-POST returns the existing (done) request, not a new scan;
  * SIGTERM on the restarted process drains and exits 0 ("stopped
    cleanly" — a container stop is a resume point, not data loss).

Prints ``SERVE_CHAOS_SMOKE=ok`` and exits 0 on success.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    TERMINAL,
    replay_serving,
)
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn
from serve_smoke import STEPS, make_cfg, post_json, get, render_scan

CAM, PROJ = (160, 120), (128, 64)

# the smoke's pipeline shape, as CLI --set overrides (the subprocess must
# run the SAME config the solo reference ran, or parity is vacuous)
_SETS = [
    "parallel.backend=numpy",
    f"decode.n_cols={PROJ[0]}", f"decode.n_rows={PROJ[1]}",
    "decode.thresh_mode=manual",
    "merge.voxel_size=4.0", "merge.ransac_trials=512",
    "merge.icp_iters=10",
    "mesh.depth=5", "mesh.density_trim_quantile=0.0",
    "serving.clean_steps=statistical",
    "serving.host=127.0.0.1", "serving.port=0",
]


def launch(root: str, ready: str, log_path: str,
           extra_sets=()) -> subprocess.Popen:
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           root, "--ready-file", ready]
    for s in list(_SETS) + list(extra_sets):
        cmd += ["--set", s]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    logf = open(log_path, "a")
    return subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                            env=env)


def wait_ready(ready: str, proc: subprocess.Popen,
               timeout_s: float = 120.0) -> str:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve exited {proc.returncode} before ready")
        if os.path.exists(ready):
            try:
                with open(ready) as f:
                    info = json.load(f)
                base = f"http://{info['host']}:{info['port']}"
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=5) as r:
                    if json.loads(r.read()).get("ok"):
                        return base
            except (ValueError, OSError, urllib.error.URLError):
                pass
        time.sleep(0.1)
    raise TimeoutError(f"serve not ready after {timeout_s}s")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="sl3d_serve_chaos_")
    try:
        rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
        calib = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib, rig.calibration())
        tgt = os.path.join(tmp, "in_tclean")
        os.makedirs(tgt)
        render_scan(tgt, views=2, shift=0.0)

        solo = os.path.join(tmp, "solo")
        rep = stages.run_pipeline(calib, tgt, solo, cfg=make_cfg(),
                                  steps=STEPS, log=lambda m: None)
        assert rep.failed == [], rep.failed
        print("[serve_chaos] solo reference done "
              f"({rep.merged_points:,} points)")

        root = os.path.join(tmp, "svc")
        log_path = os.path.join(tmp, "serve.log")
        payload = {"tenant": "tclean", "target": tgt, "calib": calib,
                   "scan_id": "c1"}

        # ---- generation 1: armed to crash at the assembly boundary ----
        ready1 = os.path.join(tmp, "ready1.json")
        proc = launch(root, ready1, log_path,
                      extra_sets=["faults.spec=serve.crash~assembly"
                                  ":crash"])
        base = wait_ready(ready1, proc)
        print(f"[serve_chaos] gen-1 up at {base} (pid {proc.pid}, "
              f"crash armed)")
        body = post_json(f"{base}/submit", payload)
        sid = body["scan_id"]
        print(f"[serve_chaos] accepted {sid}; waiting for the crash")
        rc = proc.wait(timeout=300)
        assert rc == 137, f"expected exit 137 (injected crash), got {rc}"
        print("[serve_chaos] gen-1 died 137 mid-flight (as injected)")

        # no terminal state was journaled for the accepted request, and
        # its durable record is on disk
        rs = replay_serving(os.path.join(root, "ledger.jsonl"))
        assert sid in rs["scans"], rs["scans"].keys()
        assert rs["scans"][sid]["state"] not in TERMINAL, rs["scans"][sid]
        assert os.path.exists(os.path.join(root, "requests",
                                           f"{sid}.json"))
        print(f"[serve_chaos] ledger: {sid} is "
              f"{rs['scans'][sid]['state']!r} (non-terminal), "
              f"{len(rs['completed'])} view(s) credited")

        # ---- generation 2: fault-free restart over the same root ------
        ready2 = os.path.join(tmp, "ready2.json")
        proc = launch(root, ready2, log_path)
        base = wait_ready(ready2, proc)
        print(f"[serve_chaos] gen-2 up at {base} (pid {proc.pid})")
        try:
            t0 = time.monotonic()
            while True:
                d = json.loads(get(f"{base}/status/{sid}"))
                if d["state"] in TERMINAL:
                    break
                assert time.monotonic() - t0 < 300.0, d
                time.sleep(0.25)
            assert d["state"] == "done", d
            report = d.get("report") or {}
            assert report.get("views_computed") == 0, report
            print(f"[serve_chaos] resumed to done with zero recompute "
                  f"({report.get('views_cached')} cached view(s))")

            ply = get(f"{base}/result/{sid}?artifact=ply")
            stl = get(f"{base}/result/{sid}?artifact=stl")
            with open(os.path.join(solo, "merged.ply"), "rb") as f:
                assert f.read() == ply, "PLY diverged across crash-restart"
            with open(os.path.join(solo, "model.stl"), "rb") as f:
                assert f.read() == stl, "STL diverged across crash-restart"
            print("[serve_chaos] byte parity with solo run holds across "
                  "the crash")

            # durable idempotency: the client's retry of its original
            # submit lands on the SAME (finished) request
            body = post_json(f"{base}/submit", payload)
            assert body.get("duplicate") is True, body
            assert body["scan_id"] == sid and body["state"] == "done"
            print("[serve_chaos] re-POST of the original submit is "
                  "idempotent (duplicate of the done request)")
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 0, f"SIGTERM drain should exit 0, got {rc}"
        with open(log_path) as f:
            assert "stopped cleanly" in f.read()
        print("[serve_chaos] SIGTERM drained and exited 0")
        print("SERVE_CHAOS_SMOKE=ok")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
