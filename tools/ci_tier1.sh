#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim. Run from the repo root.
# Prints DOTS_PASSED=<n> after the pytest summary; exit code is pytest's.
# Afterwards: records DOTS_PASSED into a log artifact (tools/_ci/tier1_dots.log)
# and runs the pipeline, batched-reconstruct, and chaos smokes — no
# thresholds, just "completes and the outputs are identical/recovered".
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo DOTS_PASSED=$dots

# ---- log artifact: one line per run, so regressions are greppable ----
mkdir -p tools/_ci
echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) DOTS_PASSED=$dots rc=$rc" >> tools/_ci/tier1_dots.log

# ---- pipeline smoke: completes + byte-identical outputs (no thresholds).
# 1200s: the arm aggregates every pipeline A/B (e2e, stream, faults, trace,
# deadline, multiproc, batched child, fused child) — ~735s before the fused
# child joined, ~950s with it on this box ----
smoke_rc=0
smoke=$(timeout -k 10 1200 env JAX_PLATFORMS=cpu python bench.py --pipeline-only 2>/dev/null) || smoke_rc=$?
echo "$smoke" > tools/_ci/pipeline_smoke.json
if [ $smoke_rc -eq 0 ] \
   && echo "$smoke" | grep -q '"outputs_identical": true' \
   && echo "$smoke" | grep -q '"stl_identical": true' \
   && echo "$smoke" | grep -q '"equivalent": true'; then
  echo "PIPELINE_SMOKE=ok"
else
  echo "PIPELINE_SMOKE=FAIL (rc=$smoke_rc; see tools/_ci/pipeline_smoke.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- batched reconstruct smoke: one 2-view forward_views launch must be
# byte-identical to the per-view dispatch loop (ISSUE 4) ----
batched_rc=0
batched=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py --batched-only --views=2 --compute-batch=2 2>/dev/null) || batched_rc=$?
echo "$batched" > tools/_ci/batched_smoke.json
if [ $batched_rc -eq 0 ] \
   && echo "$batched" | grep -q '"outputs_identical": true' \
   && echo "$batched" | grep -q '"launches": 1' \
   && echo "$batched" | grep -q '"views_dispatched": 2'; then
  echo "BATCHED_SMOKE=ok"
else
  echo "BATCHED_SMOKE=FAIL (rc=$batched_rc; see tools/_ci/batched_smoke.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- fused-residency smoke: the HBM-resident drain (pipeline.fused_clean)
# must produce merged PLY + STL byte-identical to the discrete arm and
# move >=3x fewer cloud-path device<->host bytes per view (ISSUE 10);
# per-kernel capability-probe verdicts land in tools/_ci/kernel_probes.json ----
fused_rc=0
fused=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py --fused-only --views=2 --compute-batch=2 2>/dev/null) || fused_rc=$?
echo "$fused" > tools/_ci/fused_smoke.json
timeout -k 10 120 env JAX_PLATFORMS=cpu python -c "
import json
from structured_light_for_3d_model_replication_tpu.ops import pallas_kernels as pk
print(json.dumps(pk.kernel_report()))
" > tools/_ci/kernel_probes.json 2>/dev/null || true
if [ $fused_rc -eq 0 ] \
   && echo "$fused" | grep -q '"merged_identical": true' \
   && echo "$fused" | grep -q '"stl_identical": true' \
   && echo "$fused" | grep -q '"cloud_bytes_ratio_ok": true'; then
  echo "FUSED_SMOKE=ok"
else
  echo "FUSED_SMOKE=FAIL (rc=$fused_rc; see tools/_ci/fused_smoke.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- packed-ingest smoke: the capture-rate ingest lane (packed bit-plane
# frames + streaming on-device decode, pipeline.packed_ingest) must produce
# merged PLY + STL byte-identical to the raw arm — discrete AND fused
# drains — while uploading >=6x fewer frame bytes (ISSUE 11) ----
packed_rc=0
packed=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py --packed-only --views=2 --compute-batch=2 2>/dev/null) || packed_rc=$?
echo "$packed" > tools/_ci/packed_smoke.json
if [ $packed_rc -eq 0 ] \
   && echo "$packed" | grep -q '"merged_identical": true' \
   && echo "$packed" | grep -q '"stl_identical": true' \
   && echo "$packed" | grep -q '"fused_identical": true' \
   && echo "$packed" | grep -q '"frame_bytes_ratio_ok": true'; then
  echo "PACKED_SMOKE=ok"
else
  echo "PACKED_SMOKE=FAIL (rc=$packed_rc; see tools/_ci/packed_smoke.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- streaming-merge smoke: the streamed register lane must produce
# byte-identical merged PLY + STL vs the barrier arm (ISSUE 5) ----
stream_rc=0
stream=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python bench.py --stream-only --views=4 2>/dev/null) || stream_rc=$?
echo "$stream" > tools/_ci/stream_smoke.json
if [ $stream_rc -eq 0 ] \
   && echo "$stream" | grep -q '"merged_identical": true' \
   && echo "$stream" | grep -q '"stl_identical": true' \
   && echo "$stream" | grep -q '"merge_mode_streamed": "streamed"'; then
  echo "STREAM_SMOKE=ok"
else
  echo "STREAM_SMOKE=FAIL (rc=$stream_rc; see tools/_ci/stream_smoke.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- trace smoke: traced 2-view pipeline -> schema-valid journal, report
# renders, Perfetto export shows >=4 lanes, journal lane walls reproduce
# OverlapStats within 1%, and the disabled-overhead bench arm (recorded in
# pipeline_smoke.json above) stays <= 1.02x vs pipeline_e2e (ISSUE 6) ----
trace_rc=0
tracesmoke=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/trace_smoke.py --overhead-json tools/_ci/pipeline_smoke.json 2>&1) || trace_rc=$?
echo "$tracesmoke" > tools/_ci/trace_smoke.log
if [ $trace_rc -eq 0 ] && echo "$tracesmoke" | grep -q 'TRACE_SMOKE=ok'; then
  echo "$tracesmoke" | grep 'TRACE_SMOKE=ok'
else
  echo "TRACE_SMOKE=FAIL (rc=$trace_rc; see tools/_ci/trace_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- chaos smoke: seeded fault plan (1 transient + 1 permanent over 5
# views) must retry, quarantine, and still ship the STL with exit 0;
# plus the ISSUE-7 stall case (a load hanging past its lane deadline
# must quarantine as DeadlineExceeded, never hang the run) ----
chaos_rc=0
chaos=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_smoke.py 2>&1) || chaos_rc=$?
echo "$chaos" > tools/_ci/chaos_smoke.log
if [ $chaos_rc -eq 0 ] && echo "$chaos" | grep -q 'CHAOS_SMOKE=ok'; then
  echo "$chaos" | grep 'CHAOS_SMOKE=ok'
else
  echo "CHAOS_SMOKE=FAIL (rc=$chaos_rc; see tools/_ci/chaos_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- soak smoke: 3 seeded runs over a randomized fault matrix
# (transient/permanent/crash/stall/slow mixes), 2 coordinated 2-worker
# runs from the host-scope kill matrix (the second one through the pod
# fabric with an extra wire-scope rule, ISSUE 15), and 1 serving
# kill->restart run from the serve-scope matrix — every run must
# TERMINATE within budget with a schema-valid trace journal (ISSUE 7), a
# replayable ledger (ISSUE 9), and every accepted serve request
# recovered (ISSUE 13); longer sweeps: python tools/soak.py --runs 20 ----
soak_rc=0
soak=$(timeout -k 10 700 env JAX_PLATFORMS=cpu python tools/soak.py --runs 3 --views 4 --budget-s 150 --multiproc-runs 2 --serve-runs 1 2>&1) || soak_rc=$?
echo "$soak" > tools/_ci/soak_smoke.log
if [ $soak_rc -eq 0 ] && echo "$soak" | grep -q 'SOAK=ok'; then
  echo "$soak" | grep 'SOAK=ok'
else
  echo "SOAK_SMOKE=FAIL (rc=$soak_rc; see tools/_ci/soak_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- multiproc smoke: one scan sharded across 2 worker processes with
# a seeded worker.kill (w0 dies on its first granted item) must exit 0,
# journal the steal, and produce PLY+STL byte-identical to the
# single-process run (ISSUE 9's acceptance anchor) ----
mproc_rc=0
mproc=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/multiproc_smoke.py 2>&1) || mproc_rc=$?
echo "$mproc" > tools/_ci/multiproc_smoke.log
if [ $mproc_rc -eq 0 ] && echo "$mproc" | grep -q 'MULTIPROC_SMOKE=ok'; then
  echo "$mproc" | grep 'MULTIPROC_SMOKE=ok'
else
  echo "MULTIPROC_SMOKE=FAIL (rc=$mproc_rc; see tools/_ci/multiproc_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- assembly smoke: a 2-worker pod with the incremental fold lane
# (merge.incremental) must ship PLY+STL byte-identical to the barrier
# pod AND the single-process run, fold the whole chain before the last
# item settles, and keep the assembly tail (last-item-settled ->
# artifacts) no slower than the barrier arm's (ISSUE 17) ----
asm_rc=0
asm=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/assembly_smoke.py 2>&1) || asm_rc=$?
echo "$asm" > tools/_ci/assembly_smoke.log
if [ $asm_rc -eq 0 ] && echo "$asm" | grep -q 'ASSEMBLY_SMOKE=ok'; then
  echo "$asm" | grep 'ASSEMBLY_SMOKE=ok'
else
  echo "ASSEMBLY_SMOKE=FAIL (rc=$asm_rc; see tools/_ci/assembly_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- fabric smoke: 2 workers joined over REAL TCP (coordinator.listen +
# shared secret, private per-worker L1 caches against the coordinator's
# blobstore L2) with a seeded worker.kill of w0 on its 3rd item plus a
# transient blob.fetch fault — the STL must ship, PLY+STL must be
# byte-identical to the single-process run, and the ledger must replay
# with >= 1 steal (ISSUE 15's acceptance anchor) ----
fabric_rc=0
fabric=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fabric_smoke.py 2>&1) || fabric_rc=$?
echo "$fabric" > tools/_ci/fabric_smoke.log
if [ $fabric_rc -eq 0 ] && echo "$fabric" | grep -q 'FABRIC_SMOKE=ok'; then
  echo "$fabric" | grep 'FABRIC_SMOKE=ok'
else
  echo "FABRIC_SMOKE=FAIL (rc=$fabric_rc; see tools/_ci/fabric_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- fabric bench: the pod-fabric cost/benefit ledger (bench_fabric) —
# arm A/B certify the FabricCache hook adds <= 2% to a fabric-less run,
# arm C certifies a cold 2-worker TCP pod stays byte-identical, arm D
# (a warm resume over pre-seeded caches) certifies every pair grant is a
# locality hit; wall-clock numbers land in tools/_ci/fabric_bench.json
# for trend-watching but only ratios/parity gate (1-CPU CI box) ----
fbench_rc=0
fbench=$(timeout -k 10 900 env JAX_PLATFORMS=cpu python bench.py --fabric-only 2>/dev/null) || fbench_rc=$?
echo "$fbench" > tools/_ci/fabric_bench.json
if [ $fbench_rc -eq 0 ] \
   && echo "$fbench" | grep -q '"parity_ply": true' \
   && echo "$fbench" | grep -q '"parity_stl": true' \
   && echo "$fbench" | grep -q '"parity_stl_resume": true' \
   && echo "$fbench" | grep -q '"locality_hit_rate": 1.0' \
   && echo "$fbench" | python -c "import json,sys; sys.exit(0 if json.load(sys.stdin).get('fabric_overhead', 9) <= 1.02 else 1)"; then
  echo "BENCH_FABRIC=ok"
else
  echo "BENCH_FABRIC=FAIL (rc=$fbench_rc; see tools/_ci/fabric_bench.json)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- serve smoke: one gateway, two concurrent tenants, a seeded
# permanent compute.view fault on ONE of the faulty tenant's views —
# the faulty request must complete DEGRADED, the clean tenant's
# downloaded PLY+STL must be byte-identical to a solo run_pipeline,
# and /metrics must scrape with per-tenant labels (ISSUE 12) ----
serve_rc=0
serve=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/serve_smoke.py 2>&1) || serve_rc=$?
echo "$serve" > tools/_ci/serve_smoke.log
if [ $serve_rc -eq 0 ] && echo "$serve" | grep -q 'SERVE_SMOKE=ok'; then
  echo "$serve" | grep 'SERVE_SMOKE=ok'
else
  echo "SERVE_SMOKE=FAIL (rc=$serve_rc; see tools/_ci/serve_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- serve chaos smoke: a REAL `sl3d serve` subprocess felled by an
# injected serve.crash (exit 137, ledger fd dangling), restarted over
# the same root — the resumed request must finish DONE with zero
# recompute and byte parity vs a solo run, the client's scan_id must
# stay idempotent across the crash, and SIGTERM must drain to exit 0
# (ISSUE 13) ----
schaos_rc=0
schaos=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/serve_chaos_smoke.py 2>&1) || schaos_rc=$?
echo "$schaos" > tools/_ci/serve_chaos_smoke.log
if [ $schaos_rc -eq 0 ] && echo "$schaos" | grep -q 'SERVE_CHAOS_SMOKE=ok'; then
  echo "$schaos" | grep 'SERVE_CHAOS_SMOKE=ok'
else
  echo "SERVE_CHAOS_SMOKE=FAIL (rc=$schaos_rc; see tools/_ci/serve_chaos_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- HA smoke: TWO real `sl3d serve` gateways over one shared root;
# the leader dies 137 mid-assembly (lease never released) — the standby
# must steal the expired lease within the lease bound (measured
# failover_s), rewrite serve.json with the bumped epoch, finish the
# orphaned request with zero recompute and byte parity vs a solo run,
# keep the client's scan_id idempotent across the takeover, and drain
# to exit 0 on SIGTERM; the follower must answer /submit with the
# machine-readable not-leader redirect while the leader lives (ISSUE 14) ----
ha_rc=0
ha=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/ha_smoke.py 2>&1) || ha_rc=$?
echo "$ha" > tools/_ci/ha_smoke.log
if [ $ha_rc -eq 0 ] && echo "$ha" | grep -q 'HA_SMOKE=ok'; then
  echo "$ha" | grep 'HA_SMOKE=ok'
else
  echo "HA_SMOKE=FAIL (rc=$ha_rc; see tools/_ci/ha_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi

# ---- fleet smoke: a real gateway with the elastic fleet supervisor on,
# driven over HTTP — scale-up 0->2 `sl3d worker` processes under load,
# byte parity vs solo runs, a SIGKILLed worker respawned at the same
# rank with a bumped generation (ledger + state + the worker's own
# hello), scale-in back to the floor on idle, and replay_fleet folding
# the decision ledger to the live supervisor's final state (ISSUE 18) ----
fleet_rc=0
fleet=$(timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fleet_smoke.py 2>&1) || fleet_rc=$?
echo "$fleet" > tools/_ci/fleet_smoke.log
if [ $fleet_rc -eq 0 ] && echo "$fleet" | grep -q 'FLEET_SMOKE=ok'; then
  echo "$fleet" | grep 'FLEET_SMOKE=ok'
else
  echo "FLEET_SMOKE=FAIL (rc=$fleet_rc; see tools/_ci/fleet_smoke.log)"
  [ $rc -eq 0 ] && rc=1
fi
exit $rc
