#!/usr/bin/env python
"""Multiprocess coordinator smoke for CI (run by tools/ci_tier1.sh).

Renders a 5-view synthetic turntable dataset and runs the same scan
twice: once single-process (the trusted baseline) and once sharded
across 2 worker processes with a seeded host fault —
``worker.item~w0:worker.kill`` SIGKILLs worker w0 on its first granted
item (ISSUE 9's acceptance anchor). Asserts the host-fault-domain
contract:

  - both runs exit 0 — a killed worker costs only its in-flight items
  - merged.ply and model.stl are BYTE-IDENTICAL across the two runs
    (workers are cache-warmers; assembly is the proven single-process
    pipeline, so parity is by construction — this asserts it held)
  - the coordinator journaled the kill: ledger.jsonl replays cleanly
    and contains >= 1 steal event (the dead worker's lease, stolen and
    regranted to the survivor)

Prints ``MULTIPROC_SMOKE=ok`` (exit 0) or ``MULTIPROC_SMOKE=FAIL (...)``
(exit 1).
"""
import json
import os
import shutil
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# kill worker w0 on its first granted item (fires once); spawned worker
# processes inherit the env, the coordinator process never fires
# worker.* sites, so exactly one worker dies exactly once
FAULT_SPEC = "worker.item~w0:worker.kill"

PIPE_FLAGS = [
    "--steps", "statistical",
    "--set", "parallel.backend=numpy",
    "--set", "decode.n_cols=128", "--set", "decode.n_rows=64",
    "--set", "decode.thresh_mode=manual",
    "--set", "merge.voxel_size=4.0",
    "--set", "merge.ransac_trials=512",
    "--set", "merge.icp_iters=10",
    "--set", "mesh.depth=5",
    "--set", "mesh.density_trim_quantile=0",
]


def fail(why: str) -> int:
    print(f"MULTIPROC_SMOKE=FAIL ({why})")
    return 1


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def main() -> int:
    # the baseline run must be fault-free even if the CI env is dirty
    os.environ.pop("SL3D_FAULTS", None)
    os.environ["SL3D_FAULTS_SEED"] = "0"
    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )
    from structured_light_for_3d_model_replication_tpu.parallel.coordinator import (  # noqa: E501
        Ledger,
    )

    tmp = tempfile.mkdtemp(prefix="slmproc_")
    try:
        root = os.path.join(tmp, "dataset")
        out_sp = os.path.join(tmp, "out_single")
        out_mp = os.path.join(tmp, "out_multi")
        rc = cli_main(["synth", root, "--views", "5",
                       "--cam", "160x120", "--proj", "128x64"])
        if rc != 0:
            return fail(f"synth rc={rc}")
        calib = ["--calib", os.path.join(root, "calib.mat")]

        rc = cli_main(["pipeline", root, "--out", out_sp]
                      + calib + PIPE_FLAGS)
        if rc != 0:
            return fail(f"single-process pipeline rc={rc}")

        # worker processes inherit this env; w0 dies on its first item
        os.environ["SL3D_FAULTS"] = FAULT_SPEC
        rc = cli_main(["pipeline", root, "--out", out_mp, "--workers", "2"]
                      + calib + PIPE_FLAGS)
        os.environ.pop("SL3D_FAULTS", None)
        if rc != 0:
            return fail(f"coordinated pipeline rc={rc} (a killed worker "
                        f"must cost only its in-flight items)")

        for name in ("merged.ply", "model.stl"):
            a, b = os.path.join(out_sp, name), os.path.join(out_mp, name)
            if not os.path.exists(b):
                return fail(f"{name} missing from coordinated run")
            if _read(a) != _read(b):
                return fail(f"{name} differs from single-process run "
                            f"({os.path.getsize(a)} vs "
                            f"{os.path.getsize(b)} bytes)")

        ledger_path = os.path.join(out_mp, "ledger.jsonl")
        if not os.path.exists(ledger_path):
            return fail("ledger.jsonl missing from coordinated run")
        replay = Ledger.replay(ledger_path)
        steals = 0
        with open(ledger_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("type") == "steal":
                    steals += 1
        if steals < 1:
            return fail("no steal event journaled for the killed worker")
        print(f"MULTIPROC_SMOKE=ok (2 workers, 1 killed; "
              f"{len(replay['completed'])} item(s) journaled complete, "
              f"{steals} steal(s); PLY+STL byte-identical to "
              f"single-process)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
