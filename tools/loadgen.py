#!/usr/bin/env python
"""Synthetic multi-tenant load generator for ``sl3d serve``.

Drives a RUNNING gateway over plain HTTP (stdlib urllib — same no-deps
discipline as the service itself): N tenants submit scans with Poisson
inter-arrival times (seeded, reproducible), every request is polled to a
terminal state, and the run is summarized the way a serving benchmark
needs — scans/hour, p50/p99 request latency, per-state counts, and the
gateway's launch-fill counters scraped from ``/metrics`` (mean
views/launch — the cross-tenant batching number).

Inputs come from a JSON manifest mapping tenants to (target, calib)
pairs, so every tenant can submit DISTINCT scan data (identical bytes
would dedup to zero engine work after the first tenant — correct for the
service, useless for a load test):

    {"tenants": {"ta": [{"target": "...", "calib": "..."}],
                 "tb": [{"target": "...", "calib": "...", "weight": 2}]}}

Each tenant cycles through its list for ``--scans`` submissions.

Usage:
    python tools/loadgen.py --root <serve root>   # reads serve.json
    python tools/loadgen.py --url http://127.0.0.1:8089 \
        --manifest inputs.json --scans 2 --rate 0.5 --seed 0 --out lg.json

``--root`` discovers the gateway via the ``serve.json`` the service
writes once listening (the ready handshake). Exit 0 when every request
reached done/degraded (degraded IS a completed request — the per-request
failure-domain contract), 1 otherwise.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request

_TERMINAL = ("done", "degraded", "failed", "aborted")


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _post_json(url: str, payload: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def discover(root: str, timeout_s: float = 30.0) -> str:
    """Wait for ``<root>/serve.json`` and return the gateway base URL."""
    path = os.path.join(root, "serve.json")
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    info = json.load(f)
                return f"http://{info['host']}:{info['port']}"
            except (json.JSONDecodeError, KeyError):
                pass        # torn read; the writer is not atomic
        time.sleep(0.1)
    raise TimeoutError(f"no serve.json under {root!r} after {timeout_s}s")


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _scrape_counter(text: str, name: str) -> float:
    """Sum a counter across its label sets in exposition text."""
    total, seen = 0.0, False
    for m in re.finditer(rf"^{re.escape(name)}(?:{{[^}}]*}})? (\S+)$",
                         text, re.M):
        total += float(m.group(1))
        seen = True
    return total if seen else 0.0


class TenantDriver(threading.Thread):
    """One tenant's arrival process: Poisson gaps, submit, poll to
    terminal. Results append to the shared list (lock-guarded)."""

    def __init__(self, base: str, tenant: str, inputs: list[dict],
                 scans: int, rate: float, rng: random.Random,
                 results: list, lock: threading.Lock,
                 poll_s: float = 0.25, request_timeout_s: float = 600.0,
                 budget_s: float = 0.0):
        super().__init__(name=f"loadgen-{tenant}", daemon=True)
        self.base = base
        self.tenant = tenant
        self.inputs = inputs
        self.scans = scans
        self.rate = rate
        self.rng = rng
        self.results = results
        self.lock = lock
        self.poll_s = poll_s
        self.request_timeout_s = request_timeout_s
        self.budget_s = budget_s

    def _one(self, i: int) -> dict:
        spec = self.inputs[i % len(self.inputs)]
        payload = {"tenant": self.tenant, "target": spec["target"],
                   "calib": spec["calib"]}
        if "weight" in spec:
            payload["weight"] = spec["weight"]
        if self.budget_s:
            payload["budget_s"] = self.budget_s
        t0 = time.monotonic()
        code, body = _post_json(self.base + "/submit", payload)
        if code != 200:
            return {"tenant": self.tenant, "state": "rejected",
                    "http": code, "error": body.get("error", ""),
                    "latency_s": time.monotonic() - t0}
        sid = body["scan_id"]
        while time.monotonic() - t0 < self.request_timeout_s:
            _, raw = _get(self.base + f"/status/{sid}")
            d = json.loads(raw)
            if d["state"] in _TERMINAL:
                return {"tenant": self.tenant, "scan_id": sid,
                        "state": d["state"],
                        "latency_s": time.monotonic() - t0}
            time.sleep(self.poll_s)
        return {"tenant": self.tenant, "scan_id": sid, "state": "timeout",
                "latency_s": time.monotonic() - t0}

    def run(self) -> None:
        for i in range(self.scans):
            if i > 0 and self.rate > 0:
                time.sleep(self.rng.expovariate(self.rate))
            res = self._one(i)
            with self.lock:
                self.results.append(res)


def run_load(base: str, manifest: dict, scans: int, rate: float,
             seed: int = 0, budget_s: float = 0.0,
             request_timeout_s: float = 600.0, log=print) -> dict:
    """Drive the gateway with every tenant in ``manifest`` and summarize.
    Importable — ``bench.py``'s serve arm calls this directly."""
    tenants = manifest["tenants"]
    results: list[dict] = []
    lock = threading.Lock()
    t_wall = time.monotonic()
    drivers = [
        TenantDriver(base, tenant, inputs, scans, rate,
                     random.Random(seed * 1000 + i), results, lock,
                     request_timeout_s=request_timeout_s,
                     budget_s=budget_s)
        for i, (tenant, inputs) in enumerate(sorted(tenants.items()))
    ]
    for d in drivers:
        d.start()
    for d in drivers:
        d.join()
    wall = time.monotonic() - t_wall

    states: dict[str, int] = {}
    for r in results:
        states[r["state"]] = states.get(r["state"], 0) + 1
    completed = [r for r in results if r["state"] in ("done", "degraded")]
    lats = sorted(r["latency_s"] for r in completed)
    out = {
        "tenants": len(drivers), "scans_per_tenant": scans,
        "arrival_rate_hz": rate, "seed": seed,
        "submitted": len(results), "states": states,
        "wall_s": round(wall, 3),
        "scans_per_hour": (round(len(completed) / wall * 3600.0, 1)
                           if wall > 0 else None),
        "p50_latency_s": (round(_percentile(lats, 0.50), 3)
                          if lats else None),
        "p99_latency_s": (round(_percentile(lats, 0.99), 3)
                          if lats else None),
        "results": results,
    }
    try:
        _, raw = _get(base + "/metrics")
        text = raw.decode()
        launches = _scrape_counter(text, "sl3d_serve_launches_total")
        views = _scrape_counter(text, "sl3d_serve_launch_views_total")
        out["launches"] = launches
        out["launch_views"] = views
        out["mean_views_per_launch"] = (round(views / launches, 3)
                                        if launches else None)
        out["cross_scan_launches"] = _scrape_counter(
            text, "sl3d_serve_cross_scan_launches_total")
        out["cross_tenant_launches"] = _scrape_counter(
            text, "sl3d_serve_cross_tenant_launches_total")
    except (OSError, ValueError) as e:
        log(f"[loadgen] metrics scrape failed: {e}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="gateway base URL (http://host:port)")
    ap.add_argument("--root", default=None,
                    help="service root; discovers the URL via serve.json")
    ap.add_argument("--manifest", required=True,
                    help="JSON manifest: {'tenants': {name: [{target, "
                         "calib[, weight]}...]}}")
    ap.add_argument("--scans", type=int, default=1,
                    help="submissions per tenant")
    ap.add_argument("--rate", type=float, default=1.0,
                    help="Poisson arrival rate per tenant (scans/sec; "
                         "0 = back-to-back)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="per-request SLO budget sent with every submit")
    ap.add_argument("--request-timeout-s", type=float, default=600.0)
    ap.add_argument("--out", default=None, help="write summary JSON here")
    args = ap.parse_args(argv)
    if not args.url and not args.root:
        ap.error("one of --url / --root is required")
    base = args.url or discover(args.root)
    with open(args.manifest) as f:
        manifest = json.load(f)
    out = run_load(base, manifest, args.scans, args.rate, seed=args.seed,
                   budget_s=args.budget_s,
                   request_timeout_s=args.request_timeout_s)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    ok = (out["submitted"] > 0
          and all(r["state"] in ("done", "degraded")
                  for r in out["results"]))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
