#!/usr/bin/env python
"""Synthetic multi-tenant load generator for ``sl3d serve``.

Drives a RUNNING gateway over plain HTTP (stdlib urllib — same no-deps
discipline as the service itself): N tenants submit scans with Poisson
inter-arrival times (seeded, reproducible), every request is polled to a
terminal state, and the run is summarized the way a serving benchmark
needs — scans/hour, p50/p99 request latency, per-state counts, and the
gateway's launch-fill counters scraped from ``/metrics`` (mean
views/launch — the cross-tenant batching number).

Inputs come from a JSON manifest mapping tenants to (target, calib)
pairs, so every tenant can submit DISTINCT scan data (identical bytes
would dedup to zero engine work after the first tenant — correct for the
service, useless for a load test):

    {"tenants": {"ta": [{"target": "...", "calib": "..."}],
                 "tb": [{"target": "...", "calib": "...", "weight": 2}]}}

Each tenant cycles through its list for ``--scans`` submissions.

Usage:
    python tools/loadgen.py --root <serve root>   # reads serve.json
    python tools/loadgen.py --url http://127.0.0.1:8089 \
        --manifest inputs.json --scans 2 --rate 0.5 --seed 0 --out lg.json

``--root`` discovers the gateway via the ``serve.json`` the service
writes once listening (the ready handshake). Exit 0 when every request
reached done/degraded (degraded IS a completed request — the per-request
failure-domain contract), 1 otherwise.

Kill→restart mode (the durable-serving measurement arm)::

    python tools/loadgen.py --root <root> --manifest m.json --scans 2 \
        --kill-after 5 --restart

SIGKILLs the serving process mid-load (pid from ``serve.json``),
relaunches it (``--restart-cmd`` or the argv recorded in serve.json),
and keeps driving: submissions carry stable client scan_ids so retries
through the outage are idempotent, pollers ride out connection-refused
until the new process answers, and every completed result is
sha256-hashed so the summary reports ``recovery_s`` (SIGKILL →
/healthz ok) and ``parity_ok`` (same input ⇒ same bytes, served before
or after the kill). Rejections always carry the gateway's
machine-readable ``reason`` (quota-reject vs overload-shed vs breaker).

HA mode (the ISSUE-14 measurement arm)::

    python tools/loadgen.py --root <root> --manifest m.json --scans 2 \
        --gateways 2 --serve-cmd "python -m <pkg>.cli serve <root> \
            --set serving.ha_enabled=true ..." --kill-leader-after 5

Launches N gateway processes over ONE shared root (each runs the
``--serve-cmd`` template verbatim; they elect a leader among
themselves), SIGKILLs the LEADER (pid from the epoch-stamped
``serve.json``) mid-load, and keeps driving WITHOUT a restart — the
surviving members hold the election. Drivers speak the HA protocol:
a 503 ``not-leader`` reply switches them to the leader address in the
redirect body, and any network error re-reads ``serve.json`` (the new
leader rewrites it atomically on takeover) and adopts whatever it says.
The summary reports ``failover_s`` — leader death → first accepted
submit (202) on the new leader — plus the healthz-level takeover time
and the same ``parity_ok`` sha256 check as the restart arm. Survivors
are SIGTERMed (drain) when the load completes; their exit codes land in
the summary.

Worker-sweep mode (the ISSUE-18 scaling-curve arm)::

    python tools/loadgen.py --worker-sweep 0,1,2 --out BENCH_FLEET_r01.json

Needs no gateway or manifest: it renders its own synthetic multi-tenant
inputs, starts an in-process gateway per point with the fleet PINNED at
N workers (``fleet_min_workers = fleet_max_workers = N``), drives the
same fixed load at every point, and emits ``BENCH_FLEET_r01.json`` —
scans/hour and p50/p99 vs worker count, stamped with host_cpus and
device_count so the regime is legible (on one CPU the fleet processes
timeshare one core and the curve is flat; the sweep measures the
MACHINERY, the shape is only meaningful multi-core). The record also
carries the standing differential A/B: the same load with the fleet
DISABLED vs enabled-but-idle (0 workers), asserting the disabled hot
path costs <= 1.02x (``fleet_disabled_overhead_x``).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import re
import shlex
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_TERMINAL = ("done", "degraded", "failed", "aborted", "shed")
_NETERR = (urllib.error.URLError, ConnectionError, OSError)


class Gateway:
    """Mutable gateway address: the kill→restart thread swaps ``base``
    under the drivers when the relaunched service comes up on a new
    ephemeral port, and HA drivers swap it themselves when a redirect
    or a dead socket tells them the leadership moved."""

    def __init__(self, base: str, root: str | None = None):
        self.base = base
        self.root = root
        self._lock = threading.Lock()

    def adopt(self, base: str) -> None:
        with self._lock:
            self.base = base

    def refresh(self) -> bool:
        """Re-read ``serve.json`` and adopt whatever address it carries
        (the leader rewrites it atomically on every takeover). Returns
        True when the address changed."""
        if not self.root:
            return False
        try:
            with open(os.path.join(self.root, "serve.json")) as f:
                info = json.load(f)
            base = f"http://{info['host']}:{info['port']}"
        except (OSError, json.JSONDecodeError, KeyError):
            return False
        with self._lock:
            changed = base != self.base
            self.base = base
        return changed


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _post_json(url: str, payload: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def discover(root: str, timeout_s: float = 30.0) -> str:
    """Wait for ``<root>/serve.json`` and return the gateway base URL."""
    path = os.path.join(root, "serve.json")
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    info = json.load(f)
                return f"http://{info['host']}:{info['port']}"
            except (json.JSONDecodeError, KeyError):
                pass        # torn read; the writer is not atomic
        time.sleep(0.1)
    raise TimeoutError(f"no serve.json under {root!r} after {timeout_s}s")


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _scrape_counter(text: str, name: str) -> float:
    """Sum a counter across its label sets in exposition text."""
    total, seen = 0.0, False
    for m in re.finditer(rf"^{re.escape(name)}(?:{{[^}}]*}})? (\S+)$",
                         text, re.M):
        total += float(m.group(1))
        seen = True
    return total if seen else 0.0


class TenantDriver(threading.Thread):
    """One tenant's arrival process: Poisson gaps, submit, poll to
    terminal. Results append to the shared list (lock-guarded). With
    ``client_ids`` every submission carries a stable scan_id, so a retry
    through a gateway outage is idempotent (the restarted service
    answers with the SAME request, never a duplicate scan)."""

    def __init__(self, gw: Gateway, tenant: str, inputs: list[dict],
                 scans: int, rate: float, rng: random.Random,
                 results: list, lock: threading.Lock,
                 poll_s: float = 0.25, request_timeout_s: float = 600.0,
                 budget_s: float = 0.0, client_ids: bool = False,
                 hash_results: bool = False):
        super().__init__(name=f"loadgen-{tenant}", daemon=True)
        self.gw = gw
        self.tenant = tenant
        self.inputs = inputs
        self.scans = scans
        self.rate = rate
        self.rng = rng
        self.results = results
        self.lock = lock
        self.poll_s = poll_s
        self.request_timeout_s = request_timeout_s
        self.budget_s = budget_s
        self.client_ids = client_ids
        self.hash_results = hash_results

    def _submit(self, payload: dict, t0: float):
        """Submit, riding out outages (connection refused during a kill →
        restart window) and 503s that carry a retry hint."""
        while time.monotonic() - t0 < self.request_timeout_s:
            try:
                code, body = _post_json(self.gw.base + "/submit", payload)
            except _NETERR:
                self.gw.refresh()     # leadership may have moved
                time.sleep(self.poll_s)
                continue
            if code == 503 and body.get("reason") == "not-leader":
                # HA follower redirect: adopt the leader address from
                # the body, else whatever serve.json now says
                leader = (body.get("leader") or {}).get("url")
                if leader:
                    self.gw.adopt(leader)
                else:
                    self.gw.refresh()
                time.sleep(min(float(body.get("retry_after_s", 0.2)),
                               1.0))
                continue
            if code == 503 and body.get("reason") in ("draining",
                                                      "transient"):
                time.sleep(min(float(body.get("retry_after_s", 1.0)),
                               2.0))
                continue
            return code, body
        return 0, {"error": "gateway unreachable", "reason": "timeout"}

    def _one(self, i: int) -> dict:
        spec = self.inputs[i % len(self.inputs)]
        payload = {"tenant": self.tenant, "target": spec["target"],
                   "calib": spec["calib"]}
        if self.client_ids:
            payload["scan_id"] = f"lg{i:03d}"
        if "weight" in spec:
            payload["weight"] = spec["weight"]
        if self.budget_s:
            payload["budget_s"] = self.budget_s
        t0 = time.monotonic()
        code, body = self._submit(payload, t0)
        if code != 200:
            return {"tenant": self.tenant, "state": "rejected",
                    "http": code, "error": body.get("error", ""),
                    "reason": body.get("reason", ""),
                    "target": spec["target"],
                    "latency_s": time.monotonic() - t0}
        sid = body["scan_id"]
        accepted_mono = time.monotonic()
        while time.monotonic() - t0 < self.request_timeout_s:
            try:
                _, raw = _get(self.gw.base + f"/status/{sid}")
                d = json.loads(raw)
            except _NETERR:
                self.gw.refresh()     # gateway down; leadership may move
                time.sleep(self.poll_s)
                continue
            if d["state"] in _TERMINAL:
                res = {"tenant": self.tenant, "scan_id": sid,
                       "state": d["state"], "target": spec["target"],
                       "accepted_mono": accepted_mono,
                       "latency_s": time.monotonic() - t0}
                if (self.hash_results
                        and d["state"] in ("done", "degraded")):
                    self._hash_into(res, sid)
                return res
            time.sleep(self.poll_s)
        return {"tenant": self.tenant, "scan_id": sid, "state": "timeout",
                "target": spec["target"],
                "latency_s": time.monotonic() - t0}

    def _hash_into(self, res: dict, sid: str) -> None:
        for art in ("ply", "stl"):
            try:
                _, raw = _get(self.gw.base
                              + f"/result/{sid}?artifact={art}",
                              timeout=60.0)
                res[f"sha_{art}"] = hashlib.sha256(raw).hexdigest()
            except (*_NETERR, urllib.error.HTTPError):
                res[f"sha_{art}"] = None

    def run(self) -> None:
        for i in range(self.scans):
            if i > 0 and self.rate > 0:
                time.sleep(self.rng.expovariate(self.rate))
            res = self._one(i)
            with self.lock:
                self.results.append(res)


def _kill_restart(gw: Gateway, kill_after_s: float, restart: bool,
                  restart_cmd: str | None, out: dict, log=print) -> None:
    """The chaos arm: SIGKILL the serving pid from serve.json after
    ``kill_after_s``, then (optionally) relaunch it and record
    ``recovery_s`` = SIGKILL → first /healthz ok. Runs on its own
    thread while the tenant drivers ride out the outage."""
    time.sleep(kill_after_s)
    sj = os.path.join(gw.root, "serve.json")
    try:
        with open(sj) as f:
            info = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        out["kill_error"] = f"serve.json unreadable: {e}"
        return
    pid = int(info["pid"])
    t_kill = time.monotonic()
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError as e:
        out["kill_error"] = f"SIGKILL pid {pid}: {e}"
        return
    out["killed_pid"] = pid
    log(f"[loadgen] SIGKILL pid {pid} after {kill_after_s:g}s of load")
    if not restart:
        return
    if restart_cmd:
        cmd = shlex.split(restart_cmd)
    else:
        argv = info.get("argv") or []
        if not argv:
            out["kill_error"] = ("no argv in serve.json and no "
                                 "--restart-cmd")
            return
        cmd = ([sys.executable] + argv if argv[0].endswith(".py")
               else list(argv))
    try:
        os.remove(sj)       # stale handshake: wait for the NEW process
    except OSError:
        pass
    with open(os.path.join(gw.root, "restart.log"), "ab") as rlog:
        proc = subprocess.Popen(cmd, stdout=rlog, stderr=rlog)
    out["restarted_pid"] = proc.pid
    log(f"[loadgen] restarting: {' '.join(cmd)} (pid {proc.pid})")
    try:
        base = discover(gw.root, timeout_s=120.0)
        t_end = time.monotonic() + 120.0
        while time.monotonic() < t_end:
            try:
                _, raw = _get(base + "/healthz", timeout=5.0)
                if json.loads(raw).get("ok"):
                    break
            except _NETERR:
                pass
            time.sleep(0.2)
        else:
            raise TimeoutError("restarted gateway never became healthy")
        gw.base = base      # drivers pick the new address up mid-poll
        out["recovery_s"] = round(time.monotonic() - t_kill, 3)
        log(f"[loadgen] gateway back at {base} "
            f"(recovery {out['recovery_s']}s)")
    except (TimeoutError, OSError) as e:
        out["kill_error"] = f"restart failed: {e}"


def _launch_gateways(serve_cmd: str, n: int, root: str,
                     log=print) -> list[subprocess.Popen]:
    """HA fleet launcher: run the ``--serve-cmd`` template N times over
    the shared root and wait until ONE member leads (serve.json appears
    and its /healthz answers ok). Ports must be ephemeral
    (serving.port=0) — members discover each other via the root, not via
    the command line."""
    procs: list[subprocess.Popen] = []
    logf = open(os.path.join(root, "gateways.log"), "ab") \
        if os.path.isdir(root) else None
    for i in range(n):
        proc = subprocess.Popen(shlex.split(serve_cmd),
                                stdout=logf or subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        procs.append(proc)
        log(f"[loadgen] gateway {i + 1}/{n} launched (pid {proc.pid})")
    base = discover(root, timeout_s=180.0)
    t_end = time.monotonic() + 180.0
    while time.monotonic() < t_end:
        dead = [p for p in procs if p.poll() is not None]
        if dead:
            raise RuntimeError(
                f"gateway pid {dead[0].pid} exited "
                f"{dead[0].returncode} before the group elected")
        try:
            _, raw = _get(base + "/healthz", timeout=5.0)
            h = json.loads(raw)
            if h.get("ok") and h.get("role") == "leader":
                log(f"[loadgen] leader at {base} (epoch {h.get('epoch')})")
                return procs
        except _NETERR:
            pass
        time.sleep(0.2)
    raise TimeoutError("HA group never elected a leader")


def _kill_leader(gw: Gateway, kill_after_s: float, out: dict,
                 log=print) -> None:
    """The HA chaos arm: SIGKILL the LEADER (pid + epoch from the
    epoch-stamped serve.json) mid-load and wait — with NO restart — for
    a surviving member to steal the expired lease, bump the epoch, and
    rewrite serve.json. Records ``t_kill_mono`` (the failover_s origin)
    and ``failover_healthz_s`` (death → new leader healthy)."""
    time.sleep(kill_after_s)
    sj = os.path.join(gw.root, "serve.json")
    try:
        with open(sj) as f:
            info = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        out["kill_error"] = f"serve.json unreadable: {e}"
        return
    pid, epoch = int(info["pid"]), int(info.get("epoch", 0))
    t_kill = time.monotonic()
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError as e:
        out["kill_error"] = f"SIGKILL leader pid {pid}: {e}"
        return
    out["killed_pid"] = pid
    out["killed_epoch"] = epoch
    out["t_kill_mono"] = t_kill
    log(f"[loadgen] SIGKILL LEADER pid {pid} (epoch {epoch}) after "
        f"{kill_after_s:g}s of load; awaiting takeover")
    t_end = time.monotonic() + 300.0
    while time.monotonic() < t_end:
        try:
            with open(sj) as f:
                info = json.load(f)
            if int(info.get("epoch", 0)) > epoch:
                base = f"http://{info['host']}:{info['port']}"
                _, raw = _get(base + "/healthz", timeout=5.0)
                h = json.loads(raw)
                if h.get("ok") and h.get("role") == "leader":
                    gw.adopt(base)
                    out["new_epoch"] = h.get("epoch")
                    out["failover_healthz_s"] = round(
                        time.monotonic() - t_kill, 3)
                    log(f"[loadgen] new leader at {base} (epoch "
                        f"{h.get('epoch')}, healthz after "
                        f"{out['failover_healthz_s']}s)")
                    return
        except (*_NETERR, json.JSONDecodeError, KeyError, ValueError):
            pass
        time.sleep(0.2)
    out["kill_error"] = "no takeover within 300s of the leader kill"


def run_load(base: str, manifest: dict, scans: int, rate: float,
             seed: int = 0, budget_s: float = 0.0,
             request_timeout_s: float = 600.0, root: str | None = None,
             kill_after_s: float = 0.0, restart: bool = False,
             restart_cmd: str | None = None, client_ids: bool = False,
             hash_results: bool = False, gateways: int = 0,
             serve_cmd: str | None = None,
             kill_leader_after_s: float = 0.0, log=print) -> dict:
    """Drive the gateway with every tenant in ``manifest`` and summarize.
    Importable — ``bench.py``'s serve arm calls this directly."""
    tenants = manifest["tenants"]
    results: list[dict] = []
    lock = threading.Lock()
    gw = Gateway(base, root=root)
    kill_info: dict = {}
    killer = None
    procs: list[subprocess.Popen] = []
    if gateways > 0:
        if not root or not serve_cmd:
            raise ValueError("--gateways needs --root and --serve-cmd")
        procs = _launch_gateways(serve_cmd, gateways, root, log=log)
        gw.refresh()                          # point at the leader
    if kill_leader_after_s > 0:
        if not root:
            raise ValueError("--kill-leader-after needs --root (pid + "
                             "epoch come from serve.json)")
        client_ids = hash_results = True      # idempotent retries + parity
        killer = threading.Thread(
            target=_kill_leader,
            args=(gw, kill_leader_after_s, kill_info, log),
            daemon=True)
    elif kill_after_s > 0:
        if not root:
            raise ValueError("--kill-after needs --root (pid + argv come "
                             "from serve.json)")
        client_ids = hash_results = True      # idempotent retries + parity
        killer = threading.Thread(
            target=_kill_restart,
            args=(gw, kill_after_s, restart, restart_cmd, kill_info, log),
            daemon=True)
    t_wall = time.monotonic()
    drivers = [
        TenantDriver(gw, tenant, inputs, scans, rate,
                     random.Random(seed * 1000 + i), results, lock,
                     request_timeout_s=request_timeout_s,
                     budget_s=budget_s, client_ids=client_ids,
                     hash_results=hash_results)
        for i, (tenant, inputs) in enumerate(sorted(tenants.items()))
    ]
    if killer is not None:
        killer.start()
    for d in drivers:
        d.start()
    for d in drivers:
        d.join()
    if killer is not None:
        killer.join(timeout=10.0)
    wall = time.monotonic() - t_wall

    states: dict[str, int] = {}
    reasons: dict[str, int] = {}
    for r in results:
        states[r["state"]] = states.get(r["state"], 0) + 1
        if r.get("reason"):
            reasons[r["reason"]] = reasons.get(r["reason"], 0) + 1
    completed = [r for r in results if r["state"] in ("done", "degraded")]
    lats = sorted(r["latency_s"] for r in completed)
    out = {
        "tenants": len(drivers), "scans_per_tenant": scans,
        "arrival_rate_hz": rate, "seed": seed,
        "submitted": len(results), "states": states,
        "wall_s": round(wall, 3),
        "scans_per_hour": (round(len(completed) / wall * 3600.0, 1)
                           if wall > 0 else None),
        "p50_latency_s": (round(_percentile(lats, 0.50), 3)
                          if lats else None),
        "p99_latency_s": (round(_percentile(lats, 0.99), 3)
                          if lats else None),
        "results": results,
    }
    if reasons:
        out["reject_reasons"] = reasons
    if kill_info:
        out["kill"] = kill_info
    if kill_leader_after_s > 0 and "t_kill_mono" in kill_info:
        # failover_s: leader death -> first accepted submit (202) that
        # landed on the NEW leader; healthz-level takeover as fallback
        t_kill = kill_info["t_kill_mono"]
        post = sorted(r["accepted_mono"] - t_kill for r in results
                      if r.get("accepted_mono", 0.0) > t_kill)
        out["failover_s"] = round(post[0], 3) if post \
            else kill_info.get("failover_healthz_s")
        out["failover_healthz_s"] = kill_info.get("failover_healthz_s")
        kill_info.pop("t_kill_mono", None)
    if hash_results:
        # post-restart parity: every completion of the SAME (tenant,
        # target) must serve the SAME bytes, killed gateway or not
        groups: dict[tuple, set] = {}
        for r in completed:
            if r.get("sha_ply"):
                groups.setdefault((r["tenant"], r["target"]), set()).add(
                    (r["sha_ply"], r.get("sha_stl")))
        out["parity_groups"] = len(groups)
        out["parity_ok"] = (all(len(v) == 1 for v in groups.values())
                            if groups else None)
    try:
        _, raw = _get(gw.base + "/metrics")
        text = raw.decode()
        launches = _scrape_counter(text, "sl3d_serve_launches_total")
        views = _scrape_counter(text, "sl3d_serve_launch_views_total")
        out["launches"] = launches
        out["launch_views"] = views
        out["mean_views_per_launch"] = (round(views / launches, 3)
                                        if launches else None)
        out["cross_scan_launches"] = _scrape_counter(
            text, "sl3d_serve_cross_scan_launches_total")
        out["cross_tenant_launches"] = _scrape_counter(
            text, "sl3d_serve_cross_tenant_launches_total")
    except (OSError, ValueError) as e:
        log(f"[loadgen] metrics scrape failed: {e}")
    if procs:
        # drain the fleet we launched: SIGTERM is the graceful path —
        # survivors must exit 0 (the killed leader reports -9/137)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=120))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(None)
        out["gateway_exit_codes"] = rcs
        killed = kill_info.get("killed_pid")
        out["survivors_clean"] = all(
            rc == 0 for p, rc in zip(procs, rcs) if p.pid != killed)
    return out


def worker_sweep(counts: list[int], scans: int = 2, tenants: int = 2,
                 views: int = 2, out_path: str = "BENCH_FLEET_r01.json",
                 log=print) -> int:
    """Scaling-curve arm: fixed synthetic load vs pinned fleet size, plus
    the fleet-disabled differential A/B. Heavy imports live HERE so the
    plain load-driving modes keep loadgen stdlib-only."""
    import shutil
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    sys.path.insert(0, os.path.join(here, ".."))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from serve_smoke import CAM, PROJ, make_cfg, render_scan
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        serving,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    tmp = tempfile.mkdtemp(prefix="sl3d_fleet_sweep_")
    try:
        calib = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(
            calib,
            syn.default_rig(cam_size=CAM, proj_size=PROJ).calibration())
        # every (tenant, submission) gets DISTINCT scan bytes: identical
        # inputs would dedup to one warm cache entry after the first
        # request and the curve would measure nothing but cache hits
        manifest: dict = {"tenants": {}}
        shift = 0.0
        for ti in range(tenants):
            inputs = []
            for si in range(scans):
                tgt = os.path.join(tmp, f"in_t{ti}_s{si}")
                os.makedirs(tgt)
                render_scan(tgt, views=views, shift=shift)
                shift += 7.0
                inputs.append({"target": tgt, "calib": calib})
            manifest["tenants"][f"t{ti}"] = inputs

        def arm(tag: str, fleet_enabled: bool, n: int) -> dict:
            cfg = make_cfg()
            cfg.serving.fleet_enabled = fleet_enabled
            cfg.serving.fleet_min_workers = n
            cfg.serving.fleet_max_workers = n
            cfg.serving.fleet_poll_s = 0.1
            root = os.path.join(tmp, f"svc_{tag}")
            httpd, svc = serving.start_gateway(root, cfg=cfg,
                                               log=lambda m: None)
            threading.Thread(target=httpd.serve_forever,
                             kwargs={"poll_interval": 0.1},
                             daemon=True).start()
            base = (f"http://{httpd.server_address[0]}:"
                    f"{httpd.server_address[1]}")
            log(f"[sweep] arm {tag}: gateway at {base} "
                f"(fleet {'on' if fleet_enabled else 'off'}, "
                f"pinned {n} worker(s))")
            try:
                s = run_load(base, manifest, scans, rate=0.0, seed=0,
                             log=lambda m: None)
            finally:
                httpd.shutdown()
                httpd.server_close()
                svc.close()
            s["all_completed"] = (s["submitted"] > 0 and all(
                r["state"] in ("done", "degraded") for r in s["results"]))
            s.pop("results", None)
            log(f"[sweep] arm {tag}: {s['scans_per_hour']} scans/h, "
                f"p99 {s['p99_latency_s']}s, wall {s['wall_s']}s, "
                f"completed={s['all_completed']}")
            return s

        # unmeasured warmup: the FIRST arm in this process pays one-time
        # costs (module imports, first-run pipeline caches) that would
        # otherwise be booked entirely against whichever measured arm
        # runs first and swamp the <=1.02x differential
        arm("warmup", False, 0)

        def paired_ab(r: int) -> tuple:
            """Run the disabled and enabled-idle gateways CONCURRENTLY
            under identical load. Sequential arms cannot certify a 2%
            bound on a box whose background load drifts more than that
            between arms; a concurrent pair shares every second of that
            drift symmetrically, so the wall ratio isolates the actual
            code differential."""
            results: dict = {}

            def side(tag: str, enabled: bool):
                results[tag] = arm(f"{tag}{r}", enabled, 0)

            ts = [threading.Thread(target=side, args=("off", False)),
                  threading.Thread(target=side, args=("idle", True))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return results["off"], results["idle"]

        reps = 2
        pairs = [paired_ab(r) for r in range(reps)]
        # the ratio is only meaningful WITHIN one pair (its two sides
        # shared the same seconds); keep the least-contended pair
        off, inert = min(pairs, key=lambda p: p[0]["wall_s"]
                         + p[1]["wall_s"])
        off["wall_s_runs"] = [p[0]["wall_s"] for p in pairs]
        off["all_completed"] = all(p[0]["all_completed"] for p in pairs)
        inert["wall_s_runs"] = [p[1]["wall_s"] for p in pairs]
        inert["all_completed"] = all(p[1]["all_completed"]
                                     for p in pairs)
        inert["workers"] = 0
        off["concurrent_pair"] = inert["concurrent_pair"] = True
        # the CURVE arms run solo — a pair-contended wall would
        # misstate the 0-worker throughput point
        sweep = []
        for n in sorted(set(counts)):
            rec = arm(f"w{n}", True, n)
            rec["workers"] = n
            sweep.append(rec)
        try:
            import jax
            device_count = jax.device_count()
        except Exception:
            device_count = None
        # the standing differential contract: the fleet code DISABLED
        # must not tax the serving hot path (and enabled-but-idle — the
        # supervisor thread + bridge socket with zero workers — must be
        # equally free; the two ratios are reciprocals, both stamped)
        ratio = (round(off["wall_s"] / inert["wall_s"], 3)
                 if inert["wall_s"] else None)
        out = {
            "schema": "sl3d-bench-fleet-v1",
            "backend": "numpy",
            "host_cpus": os.cpu_count(),
            "device_count": device_count,
            "load": {"tenants": tenants, "scans_per_tenant": scans,
                     "views_per_scan": views, "cam": list(CAM),
                     "proj": list(PROJ), "arrival": "back-to-back"},
            "sweep": sweep,
            "fleet_off": off,
            "fleet_disabled_overhead_x": ratio,
            "fleet_idle_overhead_x": (round(inert["wall_s"]
                                            / off["wall_s"], 3)
                                      if off["wall_s"] else None),
        }
        idle_x = out["fleet_idle_overhead_x"]
        out["overhead_ok"] = (ratio is not None and ratio <= 1.02
                              and idle_x is not None and idle_x <= 1.02)
        ok = (out["overhead_ok"] and off["all_completed"]
              and all(r["all_completed"] for r in sweep))
        line = json.dumps(out, indent=2, sort_keys=True)
        with open(out_path, "w") as f:
            f.write(line + "\n")
        print(line)
        log(f"[sweep] wrote {out_path} "
            f"(fleet_disabled_overhead_x={ratio}, ok={ok})")
        return 0 if ok else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="gateway base URL (http://host:port)")
    ap.add_argument("--root", default=None,
                    help="service root; discovers the URL via serve.json")
    ap.add_argument("--manifest", default=None,
                    help="JSON manifest: {'tenants': {name: [{target, "
                         "calib[, weight]}...]}} (not used by "
                         "--worker-sweep, required otherwise)")
    ap.add_argument("--worker-sweep", default=None, metavar="N,N,...",
                    help="scaling-curve mode: drive a fixed synthetic "
                         "load against in-process gateways pinned at "
                         "each worker count (plus a fleet-disabled A/B) "
                         "and emit a BENCH_FLEET record to --out")
    ap.add_argument("--sweep-tenants", type=int, default=2)
    ap.add_argument("--sweep-views", type=int, default=2,
                    help="views per synthetic sweep scan")
    ap.add_argument("--scans", type=int, default=1,
                    help="submissions per tenant")
    ap.add_argument("--rate", type=float, default=1.0,
                    help="Poisson arrival rate per tenant (scans/sec; "
                         "0 = back-to-back)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="per-request SLO budget sent with every submit")
    ap.add_argument("--request-timeout-s", type=float, default=600.0)
    ap.add_argument("--kill-after", type=float, default=0.0,
                    help="SIGKILL the serving process (pid from "
                         "serve.json) after this many seconds of load; "
                         "needs --root")
    ap.add_argument("--restart", action="store_true",
                    help="with --kill-after: relaunch the service and "
                         "report recovery time + post-restart parity")
    ap.add_argument("--restart-cmd", default=None,
                    help="shell command to relaunch the service "
                         "(default: the argv recorded in serve.json)")
    ap.add_argument("--gateways", type=int, default=0,
                    help="HA mode: launch this many gateway processes "
                         "(each runs --serve-cmd) over the shared "
                         "--root before driving load")
    ap.add_argument("--serve-cmd", default=None,
                    help="with --gateways: the serve command to run per "
                         "gateway (must use serving.ha_enabled=true and "
                         "serving.port=0 on the shared root)")
    ap.add_argument("--kill-leader-after", type=float, default=0.0,
                    help="HA mode: SIGKILL the LEADER (pid from the "
                         "epoch-stamped serve.json) after this many "
                         "seconds of load and let the survivors take "
                         "over (no restart); reports failover_s; "
                         "needs --root")
    ap.add_argument("--hash-results", action="store_true",
                    help="sha256 every completed PLY/STL and report "
                         "parity per (tenant, target)")
    ap.add_argument("--out", default=None, help="write summary JSON here")
    args = ap.parse_args(argv)
    if args.worker_sweep is not None:
        try:
            counts = [int(c) for c in args.worker_sweep.split(",")
                      if c.strip()]
        except ValueError:
            ap.error(f"--worker-sweep wants N,N,... "
                     f"(got {args.worker_sweep!r})")
        return worker_sweep(counts, scans=args.scans,
                            tenants=args.sweep_tenants,
                            views=args.sweep_views,
                            out_path=args.out or "BENCH_FLEET_r01.json")
    if not args.manifest:
        ap.error("--manifest is required (except with --worker-sweep)")
    if not args.url and not args.root:
        ap.error("one of --url / --root is required")
    if args.kill_after > 0 and not args.root:
        ap.error("--kill-after needs --root")
    if args.kill_leader_after > 0 and not args.root:
        ap.error("--kill-leader-after needs --root")
    if args.gateways > 0 and not (args.root and args.serve_cmd):
        ap.error("--gateways needs --root and --serve-cmd")
    if args.gateways > 0:
        os.makedirs(args.root, exist_ok=True)
        base = ""           # run_load discovers the leader post-launch
    else:
        base = args.url or discover(args.root)
    with open(args.manifest) as f:
        manifest = json.load(f)
    out = run_load(base, manifest, args.scans, args.rate, seed=args.seed,
                   budget_s=args.budget_s,
                   request_timeout_s=args.request_timeout_s,
                   root=args.root, kill_after_s=args.kill_after,
                   restart=args.restart, restart_cmd=args.restart_cmd,
                   hash_results=args.hash_results,
                   gateways=args.gateways, serve_cmd=args.serve_cmd,
                   kill_leader_after_s=args.kill_leader_after)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    ok = (out["submitted"] > 0
          and all(r["state"] in ("done", "degraded")
                  for r in out["results"]))
    if args.kill_after > 0:
        ok = (ok and "kill_error" not in out.get("kill", {})
              and out.get("parity_ok") is not False)
    if args.kill_leader_after > 0:
        ok = (ok and "kill_error" not in out.get("kill", {})
              and out.get("parity_ok") is not False
              and out.get("failover_s") is not None)
    if args.gateways > 0:
        ok = ok and out.get("survivors_clean", False)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
