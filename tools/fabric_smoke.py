#!/usr/bin/env python
"""Pod-fabric smoke for CI (run by tools/ci_tier1.sh).

Renders a 5-view synthetic turntable dataset and runs the same scan
twice: once single-process (the trusted baseline) and once as a 2-worker
pod over REAL TCP — ``coordinator.listen=127.0.0.1:0`` with a shared
secret, each worker warming a PRIVATE L1 stage cache against the
coordinator-hosted blobstore L2 — under seeded faults:
``worker.item~w0:worker.kill@3`` (SIGKILL w0 on its third granted item,
AFTER it pushed payloads the survivor must fetch over the fabric) plus
``blob.fetch:transient@1`` (the survivor's first fetch hiccups and must
absorb into one retry). Asserts ISSUE 15's acceptance anchor:

  - both runs exit 0 — a killed networked worker costs only its
    in-flight items, a transient blob fault costs one retry
  - merged.ply and model.stl are BYTE-IDENTICAL across the two runs
    (workers are cache-warmers publishing content-addressed payloads;
    assembly is the proven single-process pipeline over the blobstore's
    backing directory, so parity is by construction — this asserts it
    held over real sockets)
  - the ledger replays cleanly with >= 1 steal (the dead worker's lease)
  - the fabric moved real bytes (pushes > 0) and the locality counters
    are present in the coordinator summary

Prints ``FABRIC_SMOKE=ok`` (exit 0) or ``FABRIC_SMOKE=FAIL (...)``
(exit 1).
"""
import json
import os
import shutil
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# kill w0 on its THIRD granted item — by then it has pushed >= 2 payloads
# the survivor can only reach through the blobstore (private L1 roots) —
# and make the survivor's first blob fetch transiently fail. Spawned
# worker processes inherit the env; the coordinator process never fires
# worker.* or blob.* sites.
FAULT_SPEC = "worker.item~w0:worker.kill@3,blob.fetch:transient@1"

PIPE_OVERRIDES = (
    ("parallel.backend", "numpy"),
    ("decode.n_cols", 128), ("decode.n_rows", 64),
    ("decode.thresh_mode", "manual"),
    ("merge.voxel_size", 4.0),
    ("merge.ransac_trials", 512),
    ("merge.icp_iters", 10),
    ("mesh.depth", 5),
    ("mesh.density_trim_quantile", 0.0),
)


def fail(why: str) -> int:
    print(f"FABRIC_SMOKE=FAIL ({why})")
    return 1


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _cfg(workers: int = 0, listen: str = "", secret: str = ""):
    from structured_light_for_3d_model_replication_tpu.config import Config

    cfg = Config()
    for dotted, val in PIPE_OVERRIDES:
        section, key = dotted.split(".")
        setattr(getattr(cfg, section), key, val)
    cfg.coordinator.workers = workers
    cfg.coordinator.listen = listen
    cfg.coordinator.secret = secret
    return cfg


def main() -> int:
    # the baseline run must be fault-free even if the CI env is dirty
    os.environ.pop("SL3D_FAULTS", None)
    os.environ["SL3D_FAULTS_SEED"] = "0"
    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )
    from structured_light_for_3d_model_replication_tpu.parallel.coordinator import (  # noqa: E501
        Ledger,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    tmp = tempfile.mkdtemp(prefix="slfab_")
    try:
        root = os.path.join(tmp, "dataset")
        out_sp = os.path.join(tmp, "out_single")
        out_fab = os.path.join(tmp, "out_fabric")
        rc = cli_main(["synth", root, "--views", "5",
                       "--cam", "160x120", "--proj", "128x64"])
        if rc != 0:
            return fail(f"synth rc={rc}")
        calib = os.path.join(root, "calib.mat")
        steps = ("statistical",)

        rep = stages.run_pipeline(calib, root, out_sp, cfg=_cfg(),
                                  steps=steps, log=lambda m: None)
        if rep.failed or rep.degraded:
            return fail(f"single-process run degraded: {rep.failed}")

        # fabric run: real TCP listen + shared secret; workers inherit
        # the fault env (w0 killed on item 3, one transient blob fetch)
        os.environ["SL3D_FAULTS"] = FAULT_SPEC
        try:
            rep2 = stages.run_pipeline(
                calib, root, out_fab,
                cfg=_cfg(workers=2, listen="127.0.0.1:0",
                         secret="fabric-smoke"),
                steps=steps, log=lambda m: None)
        finally:
            os.environ.pop("SL3D_FAULTS", None)
        if rep2.failed or rep2.degraded:
            return fail("fabric run degraded (a killed networked worker "
                        "must cost only its in-flight items)")

        for name in ("merged.ply", "model.stl"):
            a, b = os.path.join(out_sp, name), os.path.join(out_fab, name)
            if not os.path.exists(b):
                return fail(f"{name} missing from fabric run")
            if _read(a) != _read(b):
                return fail(f"{name} differs from single-process run "
                            f"({os.path.getsize(a)} vs "
                            f"{os.path.getsize(b)} bytes)")

        info = rep2.coordinator or {}
        if not info.get("listen"):
            return fail("coordinator summary carries no listen endpoint")
        fb = info.get("fabric") or {}
        if not fb.get("pushes"):
            return fail(f"no blob pushes recorded ({fb}) — workers did "
                        f"not publish over the fabric")
        if "locality_hits" not in info or "locality_misses" not in info:
            return fail("locality counters missing from the summary")
        addrs = info.get("worker_addrs") or {}
        if not any(addrs.values()):
            return fail(f"no worker advertised an address ({addrs})")

        ledger_path = os.path.join(out_fab, "ledger.jsonl")
        if not os.path.exists(ledger_path):
            return fail("ledger.jsonl missing from fabric run")
        replay = Ledger.replay(ledger_path)
        steals = 0
        with open(ledger_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("type") == "steal":
                    steals += 1
        if steals < 1:
            return fail("no steal event journaled for the killed worker")
        print(f"FABRIC_SMOKE=ok (2 TCP workers on {info['listen']}, "
              f"1 killed; {len(replay['completed'])} item(s) complete, "
              f"{steals} steal(s); fabric {fb.get('bytes_pushed', 0)} B "
              f"pushed / {fb.get('bytes_fetched', 0)} B fetched, locality "
              f"{info['locality_hits']}h/{info['locality_misses']}m; "
              f"PLY+STL byte-identical to single-process)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
