#!/usr/bin/env python
"""TRACE_SMOKE: the flight-recorder CI gate (ISSUE 6).

Runs a tiny 2-view fused pipeline with tracing ON, then asserts the whole
observability chain end to end:

  1. the run emits trace.jsonl + metrics.json next to the STL
  2. the journal validates against the sl3d-trace-v1 schema
  3. ``sl3d report`` renders it (lane timeline + stage walls + cache table)
  4. ``sl3d report --chrome-trace`` exports a Perfetto-loadable trace.json
     showing >= 4 distinct lanes
  5. journal-derived lane walls reproduce the run's OverlapStats within 1%
  6. (``--overhead-json``) the bench record's disabled-overhead ratio
     (pipeline_trace.overhead_vs_e2e, measured by bench.py --pipeline-only)
     stays <= 1.02x — the fault layer's zero-overhead-by-default contract

Prints ``TRACE_SMOKE=ok`` and exits 0 on success; any assertion prints the
failure and exits 1. Run with JAX_PLATFORMS=cpu (ci_tier1.sh does) — the
pipeline runs the numpy decode backend and must not claim an accelerator.
"""
from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OVERHEAD_CEILING = 1.02


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--views", type=int, default=2)
    ap.add_argument("--overhead-json", default=None,
                    help="a bench --pipeline-only record "
                         "(tools/_ci/pipeline_smoke.json); asserts its "
                         "pipeline_trace.overhead_vs_e2e <= 1.02")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir (debugging)")
    args = ap.parse_args()

    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )
    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        report as replib,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages

    tmp = tempfile.mkdtemp(prefix="sl3d_trace_smoke_")
    ds = os.path.join(tmp, "ds")
    out = os.path.join(tmp, "out")
    print(f"[trace_smoke] scratch dir {tmp}", file=sys.stderr)

    # -- 1: traced 2-view pipeline ---------------------------------------
    rc = cli_main(["synth", ds, "--views", str(args.views),
                   "--cam", "160x120", "--proj", "128x64"])
    assert rc == 0, f"synth failed rc={rc}"
    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = 128, 64
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 512
    cfg.merge.icp_iters = 10
    cfg.mesh.depth = 5
    cfg.mesh.density_trim_quantile = 0.0
    cfg.observability.trace = True
    rep = stages.run_pipeline(os.path.join(ds, "calib.mat"), ds, out,
                              cfg=cfg, steps=("statistical",),
                              log=lambda m: None)
    assert rep.failed == [], f"pipeline failed: {rep.failed}"
    journal = os.path.join(out, "trace.jsonl")
    metrics = os.path.join(out, "metrics.json")
    assert os.path.exists(journal), "no trace.jsonl emitted"
    assert os.path.exists(metrics), "no metrics.json emitted"
    assert rep.run_id, "PipelineReport carries no run_id"

    # -- 2: schema validation --------------------------------------------
    errors = replib.validate_journal(journal)
    assert not errors, f"journal schema errors: {errors[:5]}"
    with open(metrics, encoding="utf-8") as f:
        m = json.load(f)
    assert m.get("run_id") == rep.run_id, "metrics run_id != report run_id"

    # -- 3: sl3d report renders ------------------------------------------
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(["report", out])
    text = buf.getvalue()
    assert rc == 0, f"sl3d report rc={rc}"
    for needle in ("lane timeline", "stage walls", "stage cache",
                   rep.run_id):
        assert needle in text, f"report output missing {needle!r}"

    # -- 4: chrome trace export ------------------------------------------
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(["report", out, "--chrome-trace", "--validate"])
    assert rc == 0, f"sl3d report --chrome-trace rc={rc}"
    chrome = os.path.join(out, "trace.json")
    with open(chrome, encoding="utf-8") as f:
        payload = json.load(f)
    names = {e["args"]["name"] for e in payload["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    lanes = {n.split(" [")[0] for n in names}
    exec_lanes = lanes & {"load", "transfer", "compute", "clean", "write",
                          "register", "stage"}
    assert len(exec_lanes) >= 4, \
        f"expected >= 4 distinct lanes in the chrome trace, got {lanes}"

    # -- 5: journal lane walls reproduce OverlapStats ---------------------
    a = replib.analyze_run(out)
    drift = 0.0
    checked = 0
    for lane, wall in a.lane_walls.items():
        stat = (rep.overlap or {}).get(f"{lane}_s")
        if stat:
            drift = max(drift, abs(wall - stat) / stat)
            checked += 1
    assert checked >= 2, f"too few lanes to cross-check ({checked})"
    assert drift <= 0.01, f"lane walls drifted {drift:.4f} from OverlapStats"

    # -- 6: disabled-overhead contract from the bench record --------------
    ratio = None
    if args.overhead_json:
        if os.path.exists(args.overhead_json):
            with open(args.overhead_json, encoding="utf-8") as f:
                try:
                    rec = json.load(f)
                except ValueError:
                    rec = {}
            ratio = (rec.get("pipeline_trace") or {}).get("overhead_vs_e2e")
            if ratio is not None:
                assert ratio <= OVERHEAD_CEILING, (
                    f"tracing-disabled overhead {ratio}x exceeds the "
                    f"{OVERHEAD_CEILING}x contract vs pipeline_e2e")
            else:
                print("[trace_smoke] WARNING: bench record carries no "
                      "pipeline_trace.overhead_vs_e2e (errored arm?) — "
                      "overhead not asserted here; PIPELINE_SMOKE flags "
                      "the underlying failure", file=sys.stderr)
        else:
            print(f"[trace_smoke] WARNING: {args.overhead_json} absent — "
                  f"overhead contract not asserted", file=sys.stderr)

    if not args.keep:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    print(f"TRACE_SMOKE=ok run_id={rep.run_id} events={a.events} "
          f"lanes={sorted(exec_lanes)} drift={drift:.4f}"
          + (f" overhead_vs_e2e={ratio}" if ratio is not None else ""))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"TRACE_SMOKE=FAIL {e}", file=sys.stderr)
        sys.exit(1)
