"""Stage-split profile of the meshing tail (bench phase D measured 202-274 s
on TPU in r5 — informational for the headline but far off the reference's
desktop Open3D Poisson, so find where it goes: normals / poisson CG /
surface-nets extraction / density trim).

The script self-terminates; do NOT wrap it in a kill timer near its
expected runtime — SIGTERM mid-TPU-claim wedges the device tunnel for
hours (see BENCH_NOTES.md).
"""
import argparse
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=0,
                    help="0 = MeshConfig default incl. density cap")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if not args.cpu:
        from structured_light_for_3d_model_replication_tpu.utils import (
            preflight,
            tpulock,
        )

        status, detail = preflight.accelerator_preflight(timeout=180)
        if status != "ok":
            print(f"preflight: {status} ({detail}) — aborting")
            sys.exit(1)
        lock = tpulock.acquire_tpu_lock(ROOT, timeout=60)  # noqa: F841
        if lock is None:  # held for process lifetime; fd close releases
            sys.exit("another TPU client holds .tpu_lock — not opening a "
                     "concurrent claim (the lock dies with its holder)")

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from structured_light_for_3d_model_replication_tpu.config import (
        MeshConfig,
    )
    from structured_light_for_3d_model_replication_tpu.models.meshing import (
        _poisson_dispatch,
    )
    from structured_light_for_3d_model_replication_tpu.ops import (
        meshproc,
        normals as nrmlib,
        surface_nets,
    )
    from structured_light_for_3d_model_replication_tpu.ops.poisson import (
        trilinear_sample,
    )
    from tools.tune_outlier import _bench_cloud

    cfg = MeshConfig()
    if args.depth:
        cfg.depth = args.depth
    pts_np = _bench_cloud(0.5)
    print(f"backend={jax.default_backend()} cloud={len(pts_np)} "
          f"depth_req={cfg.depth}", flush=True)
    pts = jnp.asarray(pts_np)
    v = jnp.ones(len(pts_np), bool)

    def timed(label, fn):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out) if hasattr(out, "block_until_ready") \
            else None
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out) if hasattr(out, "block_until_ready") \
            else None
        steady = time.perf_counter() - t0
        print(f"{label}: steady {steady:.2f}s (first {first:.2f}s)",
              flush=True)
        return out

    nr = timed("normals.estimate", lambda: nrmlib.estimate_normals(
        pts, v, k=cfg.normal_max_nn, radius=cfg.normal_radius or None))
    nr = timed("normals.orient", lambda: nrmlib.orient_normals(
        pts, nr, v, mode="radial"))

    res = timed("poisson", lambda: _poisson_dispatch(
        pts, nr, v, cfg.depth, lambda m: print("  " + m, flush=True),
        density_cap=cfg.density_cap))

    verts = faces = None

    def extract():
        nonlocal verts, faces
        verts, faces = surface_nets.extract_surface(
            res.chi, float(res.iso), origin=np.asarray(res.origin),
            cell=float(res.cell))
        return verts

    timed("surface_nets", extract)
    print(f"  mesh: {len(verts)} verts {len(faces)} faces", flush=True)

    def trim():
        coords = (jnp.asarray(verts) - res.origin) / res.cell
        dens = np.asarray(trilinear_sample(res.density, coords))
        thresh = np.quantile(dens, cfg.density_trim_quantile)
        return meshproc.filter_faces_by_vertex_mask(
            verts, faces, dens >= thresh)

    timed("density_trim", trim)


if __name__ == "__main__":
    main()
