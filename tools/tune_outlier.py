"""On-chip sweep of the slab-window outlier engine's knobs (tile, window,
selector) plus the feature-prep kNN arms, on a merged-cloud-scale input.

The r5 first on-chip line showed the outlier stage dominating the merge
(ring probe 26.3 s of 27.8 s); the slab rewrite landed at 1.69 s and this
script picks its fastest exact configuration. A synthetic quasi-voxelized
surface cloud at the bench's scale (~190k points, 0.5 mm cells, decimeter
scene offsets) reproduces the real stage's shape without paying a full
merge first.

The script self-terminates; do NOT wrap it in a kill timer near its
expected runtime — SIGTERM mid-TPU-claim wedges the device tunnel for
hours (see BENCH_NOTES.md).
"""
import argparse
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _make_cloud(n_target: int, cell: float, seed: int = 0):
    """Quasi-uniform voxelized surface cloud like the post-final-voxel merge
    output: sphere surface + backdrop plane, voxel-downsampled at ``cell``."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.ops import (
        pointcloud as pc,
    )

    rng = np.random.default_rng(seed)
    n_raw = int(n_target * 2.5)
    u = rng.normal(size=(n_raw * 2 // 3, 3))
    sphere = 70.0 * u / np.linalg.norm(u, axis=1, keepdims=True)
    sphere += np.array([0.0, 0.0, 420.0])
    plane = np.stack([rng.uniform(-150, 150, n_raw // 3),
                      rng.uniform(-110, 110, n_raw // 3),
                      np.full(n_raw // 3, 560.0)
                      + rng.normal(0, 0.2, n_raw // 3)], axis=1)
    cloud = np.concatenate([sphere, plane]).astype(np.float32)
    cols = np.zeros((len(cloud), 3), np.uint8)
    p, c, v = pc.voxel_downsample(jnp.asarray(cloud), jnp.asarray(cols),
                                  jnp.asarray(np.ones(len(cloud), bool)),
                                  cell)
    keep = np.asarray(v)
    pts = np.asarray(p)[keep]
    out = rng.uniform(250, 400, (60, 3)).astype(np.float32)
    return np.concatenate([pts, out]).astype(np.float32)


def _bench_cloud(cell: float):
    """The REAL outlier-stage input: the bench scene's registered merged
    cloud, final-voxeled — rebuilt exactly as profile_merge's A/B does."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.config import (
        MergeConfig,
    )
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as rec,
    )
    from structured_light_for_3d_model_replication_tpu.ops import (
        pointcloud as pc,
    )

    cache = os.path.join(ROOT, ".bench_cache.npz")
    if not os.path.exists(cache):
        sys.exit("no .bench_cache.npz — run `python bench.py` once first")
    z = np.load(cache)
    off = z["merge_off"]
    clouds = [(z["merge_pts"][off[i]:off[i + 1]],
               z["merge_cols"][off[i]:off[i + 1]])
              for i in range(len(off) - 1)]
    mcfg = MergeConfig(ransac_trials=512)  # mirror the bench's on-chip config
    pre = rec._preprocess_views(clouds, float(mcfg.voxel_size), 0)
    T_all, *_ = rec._register_chain_batched(pre, mcfg,
                                            float(mcfg.voxel_size),
                                            loop_closure=False)
    acc = np.eye(4, dtype=np.float32)
    parts = [np.asarray(clouds[0][0], np.float32)]
    for i in range(1, len(clouds)):
        acc = (acc @ T_all[i - 1]).astype(np.float32)
        parts.append(np.asarray(clouds[i][0], np.float32)
                     @ acc[:3, :3].T + acc[:3, 3])
    merged = np.concatenate(parts).astype(np.float32)
    cols = np.concatenate([c for _, c in clouds]).astype(np.uint8)
    p_v, _, v_v = pc.voxel_downsample(merged, cols,
                                      np.ones(len(merged), bool), cell)
    keep = np.asarray(v_v)
    return np.asarray(p_v)[keep]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=190_000)
    ap.add_argument("--cell", type=float, default=0.5)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--skip-feat", action="store_true")
    ap.add_argument("--bench-cloud", action="store_true",
                    help="sweep on the real bench merged cloud instead of "
                         "the synthetic (needs .bench_cache.npz)")
    args = ap.parse_args()

    from structured_light_for_3d_model_replication_tpu.utils import (
        preflight,
        tpulock,
    )

    status, detail = preflight.accelerator_preflight(timeout=180)
    if status != "ok":
        print(f"preflight: {status} ({detail}) — aborting")
        sys.exit(1)
    lock = tpulock.acquire_tpu_lock(ROOT, timeout=60)  # noqa: F841

    import jax
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.ops import (
        pointcloud as pc,
    )

    print(f"backend={jax.default_backend()}")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    cloud = (_bench_cloud(args.cell) if args.bench_cloud
             else _make_cloud(args.n, args.cell))
    n = len(cloud)
    valid = jnp.asarray(np.ones(n, bool))
    pts = jnp.asarray(cloud)
    print(f"cloud: {n} pts (cell {args.cell})")

    ref_md = None
    # narrow tiles need only narrow windows (window covers tile x-span +
    # 2r). Notes from the r5 sweeps: approx_min_k at recall 1.0 is 4x
    # SLOWER than top_k and not bit-identical on TPU; lax.map(batch_size=)
    # around the tile loop turns the dynamic_slice windows into gathers
    # and cost 4x — both dead ends are kept out of the engine
    # bisect rows: tile/window mean (tile, wblk) — the Pallas engine's
    # effective window is 2*wblk (two aligned half-blocks)
    for tile, window, sel in [(1024, 8192, "topk"),
                              (2048, 16384, "topk"),
                              (128, 8192, "bisect"),
                              (256, 8192, "bisect"),
                              (128, 4096, "bisect"),
                              (64, 8192, "bisect")]:
        try:
            t0 = time.perf_counter()
            md = np.array(pc._voxelized_knn_mean_dist(
                pts, valid, jnp.float32(args.cell), 20,
                tile=tile, window=window, selector=sel))
            first = time.perf_counter() - t0
            best = np.inf
            for _ in range(args.runs):
                t0 = time.perf_counter()
                md = np.array(pc._voxelized_knn_mean_dist(
                    pts, valid, jnp.float32(args.cell), 20,
                    tile=tile, window=window, selector=sel))
                best = min(best, time.perf_counter() - t0)
            cert = float(np.isfinite(md).mean())
            if ref_md is None:
                ref_md = md
                agree = 1.0
            else:
                both = np.isfinite(ref_md) & np.isfinite(md)
                agree = float(np.max(np.abs(ref_md[both] - md[both]))) \
                    if both.any() else -1.0
            print(f"slab tile={tile} window={window} sel={sel}: "
                  f"best {best:.3f}s (first {first:.1f}s) "
                  f"certified {cert:.4f} max|md-ref| {agree:.2e}")
        except Exception as e:
            print(f"slab tile={tile} window={window} sel={sel}: "
                  f"FAILED {type(e).__name__}: {e}"[:160])

    # full stage wall (engine + fallback + threshold) at the default knobs
    t0 = time.perf_counter()
    m = np.asarray(pc._stat_outlier_voxelized(pts, valid, 20, 2.0,
                                              args.cell))
    stage_first = time.perf_counter() - t0
    t0 = time.perf_counter()
    m = np.asarray(pc._stat_outlier_voxelized(pts, valid, 20, 2.0,
                                              args.cell))
    stage_steady = time.perf_counter() - t0
    print(f"stage[_stat_outlier_voxelized]: steady {stage_steady:.3f}s "
          f"(first {stage_first:.1f}s) kept {int(m.sum())}/{n}")

    t0 = time.perf_counter()
    m_np = pc.statistical_outlier_mask_np(cloud, np.ones(n, bool), 20, 2.0)
    print(f"stage[np twin cKDTree]: {time.perf_counter() - t0:.3f}s "
          f"agree {float((m == m_np).mean()):.6f}")

    if not args.skip_feat:
        from structured_light_for_3d_model_replication_tpu.ops import (
            knn as knnlib,
        )

        view = cloud[:: max(1, n // 16384)][:16384]
        vp = jnp.asarray(view)
        vv = jnp.asarray(np.ones(len(view), bool))
        for label, fn in (
                ("knn_brute k=48", lambda: knnlib.knn_brute(vp, vv, 48)),
                ("knn_dense_approx k=48",
                 lambda: knnlib.knn_dense_approx(vp, vv, 48)),
        ):
            idx, d2 = fn()
            jax.block_until_ready(d2)
            best = np.inf
            for _ in range(args.runs):
                t0 = time.perf_counter()
                idx, d2 = fn()
                jax.block_until_ready(d2)
                best = min(best, time.perf_counter() - t0)
            print(f"featknn[{label}] @{len(view)} pts: best {best:.3f}s")


if __name__ == "__main__":
    main()
