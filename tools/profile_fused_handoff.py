"""A/B the fused decode->merge handoff's pieces on the ambient backend:
the view-stack decode (jnp vs width-padded Mosaic) and the device
compaction (argsort-gather vs cumsum-scatter). Needs .bench_cache.npz
with merge_frames (run `python bench.py` once).

Self-terminating; do NOT wrap in a kill timer (see BENCH_NOTES.md).
"""
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    lock = tpulock.acquire_tpu_lock(ROOT, timeout=60)  # noqa: F841
    if lock is None:
        sys.exit("another TPU client holds .tpu_lock")

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import bench
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as rec,
    )
    from structured_light_for_3d_model_replication_tpu.models.scanner import (
        SLScanner,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    z = np.load(os.path.join(ROOT, ".bench_cache.npz"))
    if "merge_frames" not in z:
        sys.exit("cache lacks merge_frames — run `python bench.py` once")
    frames = z["merge_frames"]
    print(f"backend={jax.default_backend()} frames={frames.shape}")
    mrig = syn.default_rig(cam_size=bench.MERGE_CAM,
                           proj_size=bench.MERGE_PROJ)
    sc = SLScanner(mrig.calibration(), bench.MERGE_CAM, bench.MERGE_PROJ,
                   row_mode=1, plane_eval="quadratic")
    fr_dev = jax.block_until_ready(jnp.asarray(frames))

    def timed(label, fn, runs=3):
        out = fn()
        jax.block_until_ready(jax.tree.leaves(out))
        best = np.inf
        for _ in range(runs):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(jax.tree.leaves(out))
            best = min(best, time.perf_counter() - t0)
        print(f"[{label}] best {best:.4f}s", flush=True)
        return out

    out = timed("decode jnp 480w", lambda: sc.forward_views(
        fr_dev, thresh_mode="manual", shadow_val=40.0, contrast_val=10.0))

    # width-padded variant: zero columns decode invalid; the Mosaic fused
    # kernel needs w % 128 == 0
    wpad = (-frames.shape[-1]) % 128
    if wpad:
        fr_p = np.pad(frames, ((0, 0), (0, 0), (0, 0), (0, wpad)))
        cam_p = (bench.MERGE_CAM[0] + wpad, bench.MERGE_CAM[1])
        # the padded columns have no calibration; rays for them come from
        # the same rig evaluated at the padded width — only validity
        # matters (shadow threshold kills black columns)
        rig_p = syn.default_rig(cam_size=cam_p, proj_size=bench.MERGE_PROJ)
        sc_p = SLScanner(rig_p.calibration(), cam_p, bench.MERGE_PROJ,
                         row_mode=1, plane_eval="quadratic")
        fr_p_dev = jax.block_until_ready(jnp.asarray(fr_p))
        print(f"fuse_capable(padded)={sc_p._fuse_capable(fr_p_dev)}")
        out_p = timed("decode padded 512w", lambda: sc_p.forward_views(
            fr_p_dev, thresh_mode="manual", shadow_val=40.0,
            contrast_val=10.0))
        v0 = int(np.asarray(out.valid[0]).sum())
        v0p = int(np.asarray(out_p.valid[0]).sum())
        print(f"valid view0: 480w={v0} padded={v0p}")

    timed("compact argsort", lambda: rec._compact_views_jit(
        out.points, out.valid, out.colors))
    timed("compact+counts (full compact_views_device)",
          lambda: rec.compact_views_device(out.points, out.valid,
                                           out.colors).points)

    @jax.jit
    def compact_scatter(pts, valid, cols):
        S = pts.shape[1]
        pos = jnp.where(valid, jnp.cumsum(valid, axis=1) - 1, S - 1)
        vi = jnp.broadcast_to(
            jnp.arange(pts.shape[0], dtype=jnp.int32)[:, None], pos.shape)
        p = jnp.zeros_like(pts).at[vi, pos].set(pts)
        c = jnp.zeros_like(cols).at[vi, pos].set(cols)
        v = jnp.zeros_like(valid).at[vi, pos].set(valid)
        return p, v, c

    o2 = timed("compact cumsum-scatter", lambda: compact_scatter(
        out.points, out.valid, out.colors))
    # correctness: same survivor prefix content
    a = rec._compact_views_jit(out.points, out.valid, out.colors)
    n0 = int(np.asarray(a[1][0]).sum())
    same = np.array_equal(np.asarray(a[0][0, :n0]),
                          np.asarray(o2[0][0, :n0]))
    print(f"scatter prefix matches argsort: {same} ({n0} pts)")


if __name__ == "__main__":
    main()
