#!/usr/bin/env python
"""Seeded soak: N pipeline runs over a randomized fault matrix, asserting
that EVERY run terminates — the "the pipeline can never hang" acceptance.

Each run draws 1-3 rules from the full site x kind grid (transient /
permanent / crash / stall(T) / slow(T) over every wired injection site),
seeded from ``--seed`` so any failing run is reproducible by number, and
executes the fused pipeline on a small synthetic dataset with tight lane
deadlines + watchdog thresholds and the flight recorder armed. A run may
legitimately end four ways:

  completed        clean or DEGRADED (quarantines, identity fallbacks)
  aborted          below the min_views survivor floor (ValueError with an
                   aborted failure manifest) or past the run budget
                   (DeadlineExceeded with an aborted manifest)
  crashed          an injected ``crash`` rule (InjectedCrash is the
                   simulated kill -9; crash-safety is PR 3's contract)

What it may NEVER do is hang: each run must return within ``--budget-s``
wall seconds (a belt-and-braces SIGALRM dumps all thread stacks and fails
the soak if even that is violated), and each run's trace journal must
schema-validate so a stalled run is always diagnosable from artifacts.

``--multiproc-runs`` (ISSUE 9) appends a host-scope kill matrix: 2-worker
coordinated runs under seeded ``worker.kill`` / ``worker.preempt(T)`` /
``net.partition(T)`` rules. The same never-hang contract applies, plus
the work ledger must replay and every per-host journal must validate.
Every other multiproc run additionally goes through the pod fabric
(ISSUE 15) — real-TCP control plane + blobstore L2 — with one extra rule
drawn against the wire itself (``blob.fetch``/``blob.push`` transients,
``net.slowlink``); the fabric must degrade to retries and cache misses,
never to a hung or failed run. The non-fabric half routes through the
incremental assembly lane (ISSUE 17, ``merge.incremental``) — the fold
lane is a schedule knob, so the identical contract must hold while views
fold mid-pod under the drawn fault.

``--serve-runs`` (ISSUE 13) appends a serving kill->restart matrix:
each run drives a ScanService under a seeded serve-scope rule
(``serve.crash`` at the grant/complete/assembly boundaries, or
``ledger.append`` crash/transient), lets it crash or finish, then
RESTARTS a fault-free service over the same root. The recovery
contract: every accepted request must be terminal (done/degraded)
after the restart, within budget, and ``replay_serving`` must fold the
ledger without error.

``--fleet-runs`` (ISSUE 18) appends an elastic-fleet kill matrix: each
run starts an in-process gateway with the FleetSupervisor armed
(floor 1, cap 2 workers) under a randomly drawn chaos kind — SIGKILL
of one or two fleet workers mid-load, seeded ``worker.spawn``
transients (spawn attempts fail and retry under backoff),
``fleet.decide`` transients (skipped decision ticks), or an injected
``fleet.decide`` crash that fells the gateway itself (the service is
then restarted fault-free over the same root and must RESUME the
journaled fleet). Every run must end with all accepted requests
done/degraded, the fleet quiesced back to its floor, and —
the replayability contract — ``replay_fleet`` over the decision
ledger folding to exactly the live supervisor's final state.

Prints ``SOAK=ok runs=N ...`` (exit 0) or ``SOAK=FAIL (...)`` (exit 1).
CI runs a short arm (``tools/ci_tier1.sh`` SOAK_SMOKE); longer sweeps:

    python tools/soak.py --runs 20 --seed 7 --budget-s 120
"""
import argparse
import faulthandler
import json
import os
import random
import shutil
import signal
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# the full wired-site pool (utils/faults.py docstring); http.capture and
# serial.rotate never fire in a pipeline run but stay listed so a drawn
# rule exercises the no-op path too
SITES = ["frame.load", "frame.pack", "compute.view", "ply.write",
         "cache.get", "cache.put", "register.pair", "http.capture",
         "serial.rotate"]
KINDS = ["transient", "permanent", "crash", "stall(0.8)", "slow(0.3)"]

# host-scope kill matrix (ISSUE 9): every rule targets the per-item
# worker site, so a drawn rule SIGKILLs / preempts / partitions a worker
# process mid-scan; the coordinator must steal the orphaned leases and
# the run must still terminate with a replayable ledger
HOST_KINDS = ["worker.kill", "worker.preempt(0.3)", "net.partition(0.8)"]
HOST_MATCH = ["", "w0", "w1"]

# pod-fabric wire matrix (ISSUE 15): every other multiproc run listens on
# real TCP (127.0.0.1:0 + shared secret) and draws one extra rule against
# the fabric itself — a flaky blob fetch/push (must degrade to a retry or
# a cache miss, never a failed item) or a straggling socket
# (net.slowlink: frames arrive late but intact, throughput sags only)
FABRIC_RULES = ["blob.fetch:transient", "blob.push:transient",
                "worker.sock:net.slowlink(0.2)x20"]

# serving-scope kill matrix (ISSUE 13): crash the in-process service at
# each durability boundary (grant journaled / bytes cached / assembly
# started) or on the journal append itself; a transient on the append
# surfaces as a full-disk-style submit failure the service must survive
SERVE_RULES = ["serve.crash~grant:crash", "serve.crash~complete:crash",
               "serve.crash~assembly:crash", "ledger.append:crash",
               "ledger.append:transient"]

# gateway-HA matrix (ISSUE 14): two in-process HA members over one
# root; the leader either CRASHES at a durability boundary (lease never
# released — the standby must wait out the expiry) or is turned into a
# ZOMBIE (its renew stalls past the lease; a standby steals the epoch
# while the stale leader's engine is still appending — the fence must
# reject every late write). "zombie-renew" is formatted with the
# leader's run_id at draw time so only the leader's renew stalls.
HA_KINDS = ["crash-grant", "crash-assembly", "zombie-renew"]
HA_RULES = {"crash-grant": "serve.crash~grant:crash",
            "crash-assembly": "serve.crash~assembly:crash",
            "zombie-renew": "election.renew~{leader}:stall(3.0)"}


def fail(why: str) -> int:
    print(f"SOAK=FAIL ({why})")
    return 1


def _spec_for(rng: random.Random, view_names: list[str]) -> str:
    rules = []
    for _ in range(rng.randint(1, 3)):
        site = rng.choice(SITES)
        kind = rng.choice(KINDS)
        match = rng.choice(["", rng.choice(view_names)])
        rules.append(f"{site}{'~' + match if match else ''}:{kind}")
    return ",".join(rules)


def _host_spec_for(rng: random.Random, fabric: bool = False) -> str:
    rules = []
    for _ in range(rng.randint(1, 2)):
        kind = rng.choice(HOST_KINDS)
        match = rng.choice(HOST_MATCH)
        rules.append(f"worker.item{'~' + match if match else ''}:{kind}")
    if fabric:
        rules.append(rng.choice(FABRIC_RULES))
    return ",".join(rules)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--views", type=int, default=4)
    ap.add_argument("--budget-s", type=float, default=150.0,
                    help="per-run wall ceiling; a run past it fails the "
                         "soak (the never-hang assertion)")
    ap.add_argument("--multiproc-runs", type=int, default=3,
                    help="additional 2-worker coordinated runs drawn from "
                         "the host-scope kill matrix (worker.kill / "
                         "worker.preempt / net.partition); 0 disables")
    ap.add_argument("--serve-runs", type=int, default=2,
                    help="additional serving kill->restart runs drawn "
                         "from the serve-scope matrix (serve.crash / "
                         "ledger.append); 0 disables")
    ap.add_argument("--fleet-runs", type=int, default=0,
                    help="additional elastic-fleet runs drawn from the "
                         "fleet kill matrix (worker SIGKILLs, "
                         "worker.spawn/fleet.decide faults, supervisor "
                         "crash + fault-free resume); every run must "
                         "settle all accepted requests and its decision "
                         "ledger must replay to the live fleet state; "
                         "0 disables")
    ap.add_argument("--ha-runs", type=int, default=0,
                    help="additional two-member gateway-HA runs drawn "
                         "from the HA matrix (leader crash at a "
                         "durability boundary / stalled-renew zombie); "
                         "every accepted request must settle on the "
                         "surviving leader with a cleanly folding, "
                         "fence-consistent ledger; 0 disables")
    args = ap.parse_args()

    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )
    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        report as replib,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import faults

    from structured_light_for_3d_model_replication_tpu.parallel.coordinator import (  # noqa: E501
        Ledger,
    )

    # last line of defense: if the deadline layer itself wedges, dump every
    # thread's stack and die loudly instead of hanging CI
    alarm_s = int(args.budget_s * (args.runs + args.multiproc_runs
                                   + 2 * args.serve_runs
                                   + 2 * args.ha_runs
                                   + 3 * args.fleet_runs) + 120)

    def on_alarm(signum, frame):
        faulthandler.dump_traceback(all_threads=True)
        print(f"SOAK=FAIL (global {alarm_s}s alarm — a run hung past its "
              f"budget AND the in-run deadlines)")
        os._exit(1)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(alarm_s)

    tmp = tempfile.mkdtemp(prefix="slsoak_")
    try:
        root = os.path.join(tmp, "dataset")
        rc = cli_main(["synth", root, "--views", str(args.views),
                       "--cam", "160x120", "--proj", "128x64"])
        if rc != 0:
            return fail(f"synth rc={rc}")
        calib = os.path.join(root, "calib.mat")
        view_names = sorted(d for d in os.listdir(root)
                            if os.path.isdir(os.path.join(root, d)))
        # pack the last two views to the bit-plane container so the
        # frame.pack site fires (the unpack codec step runs inside the
        # loader for packed sources on every backend)
        from structured_light_for_3d_model_replication_tpu.io import (
            images as imio,
        )
        for name in view_names[-2:]:
            imio.pack_scan_folder(os.path.join(root, name), keep_raw=False)

        def cfg() -> Config:
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = 128, 64
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            # tight deadlines so injected stalls resolve in seconds
            c.deadlines.load_s = 2.0
            c.deadlines.compute_s = 60.0
            c.deadlines.write_s = 2.0
            c.deadlines.register_s = 5.0
            c.deadlines.drain_s = 5.0
            c.deadlines.soft_stall_s = 5.0
            c.deadlines.hard_stall_s = 15.0
            c.deadlines.watchdog_poll_s = 0.2
            c.pipeline.run_budget_s = args.budget_s
            c.observability.trace = True
            return c

        rng = random.Random(args.seed)
        outcomes: dict[str, int] = {}
        walls: list[float] = []
        for i in range(args.runs):
            spec = _spec_for(rng, view_names)
            out = os.path.join(tmp, f"out_{i:03d}")
            faults.configure(spec, seed=args.seed + i)
            t0 = time.monotonic()
            outcome = "completed"
            try:
                rep = stages.run_pipeline(calib, root, out, cfg=cfg(),
                                          steps=("statistical",),
                                          log=lambda m: None)
                if rep.degraded:
                    outcome = "degraded"
            except faults.InjectedCrash:
                outcome = "crashed"
            except Exception as e:
                # any controlled abort (below the survivor floor, run
                # budget, unwritable final artifact) must leave a failure
                # manifest — terminating is necessary but not sufficient
                outcome = "aborted"
                if not os.path.exists(os.path.join(out, "failures.json")):
                    return fail(f"run {i} [{spec}] aborted "
                                f"({type(e).__name__}: {e}) without a "
                                f"failure manifest")
            finally:
                faults.reset()
            wall = time.monotonic() - t0
            walls.append(round(wall, 1))
            if wall > args.budget_s:
                return fail(f"run {i} [{spec}] took {wall:.1f}s > "
                            f"{args.budget_s}s budget — a hang the "
                            f"deadline layer failed to bound")
            journal = os.path.join(out, "trace.jsonl")
            if not os.path.exists(journal):
                return fail(f"run {i} [{spec}] left no trace journal")
            errors = replib.validate_journal(journal)
            if errors:
                return fail(f"run {i} [{spec}] journal invalid: "
                            f"{errors[:3]}")
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
            print(f"[soak] run {i}: {outcome:<9} {wall:5.1f}s  [{spec}]")

        # ---- multiprocess kill matrix (ISSUE 9): coordinated 2-worker
        # runs under seeded host faults. Spawned workers arm from the
        # SL3D_FAULTS env (which wins over config), so the drawn rule
        # kills / preempts / partitions real OS processes; the
        # coordinator must steal orphaned leases and EVERY run must
        # terminate within budget with a replayable ledger, schema-valid
        # journals, and (on abort) a failure manifest.
        for i in range(args.multiproc_runs):
            # every other run is a pod-fabric run: real TCP control plane
            # + blobstore L2, with one extra rule drawn against the wire
            # itself (ISSUE 15) — the never-hang contract is identical
            fabric = bool(i % 2)
            spec = _host_spec_for(rng, fabric=fabric)
            out = os.path.join(tmp, f"out_mp_{i:03d}")
            mpcfg = cfg()
            mpcfg.coordinator.workers = 2
            # every other draw routes through the incremental assembly
            # lane (ISSUE 17): the fold lane is a schedule knob, so the
            # same never-hang / valid-ledger / valid-journal contract
            # must hold with views folding mid-pod under host faults
            mpcfg.merge.incremental = not fabric
            # short leases so an orphaned lease is stolen within seconds
            # (spurious expiry on a slow-but-alive item is safe: the late
            # complete is journaled and the cache entry stays warm)
            mpcfg.coordinator.lease_s = 6.0
            mpcfg.coordinator.heartbeat_s = 0.5
            if fabric:
                mpcfg.coordinator.listen = "127.0.0.1:0"
                mpcfg.coordinator.secret = "soak-pod"
            os.environ["SL3D_FAULTS"] = spec
            os.environ["SL3D_FAULTS_SEED"] = str(args.seed + 1000 + i)
            t0 = time.monotonic()
            outcome = "completed"
            try:
                rep = stages.run_pipeline(calib, root, out, cfg=mpcfg,
                                          steps=("statistical",),
                                          log=lambda m: None)
                if rep.degraded:
                    outcome = "degraded"
            except faults.InjectedCrash:
                outcome = "crashed"
            except Exception as e:
                outcome = "aborted"
                if not os.path.exists(os.path.join(out, "failures.json")):
                    return fail(f"mp run {i} [{spec}] aborted "
                                f"({type(e).__name__}: {e}) without a "
                                f"failure manifest")
            finally:
                os.environ.pop("SL3D_FAULTS", None)
                os.environ.pop("SL3D_FAULTS_SEED", None)
                faults.reset()
            wall = time.monotonic() - t0
            walls.append(round(wall, 1))
            if wall > args.budget_s:
                return fail(f"mp run {i} [{spec}] took {wall:.1f}s > "
                            f"{args.budget_s}s budget — a hang the "
                            f"coordinator failed to bound")
            ledger = os.path.join(out, "ledger.jsonl")
            if not os.path.exists(ledger):
                return fail(f"mp run {i} [{spec}] left no ledger")
            try:
                Ledger.replay(ledger)
            except ValueError as e:
                return fail(f"mp run {i} [{spec}] ledger invalid: {e}")
            # every per-host journal (coordinator assembly + each worker,
            # killed ones included) must schema-validate
            for journal in replib.host_journals(out, "trace.jsonl"):
                errors = replib.validate_journal(journal)
                if errors:
                    return fail(f"mp run {i} [{spec}] journal "
                                f"{os.path.basename(journal)} invalid: "
                                f"{errors[:3]}")
            outcomes[f"mp-{outcome}"] = outcomes.get(f"mp-{outcome}", 0) + 1
            tag = " +fabric" if fabric else " +incremental"
            print(f"[soak] mp run {i}: {outcome:<9} {wall:5.1f}s  "
                  f"[{spec}]{tag}")

        # ---- serving kill->restart matrix (ISSUE 13): an in-process
        # ScanService under a seeded serve-scope rule. Generation 1 runs
        # until it crashes (phase=crashed, no finish journaled) or every
        # request settles; generation 2 restarts FAULT-FREE over the same
        # root and must bring every accepted request to done/degraded
        # within budget, with a ledger replay_serving can fold.
        from structured_light_for_3d_model_replication_tpu.parallel.admission import (  # noqa: E501
            TERMINAL,
            replay_serving,
        )
        from structured_light_for_3d_model_replication_tpu.pipeline import (
            serving,
        )

        def serve_cfg() -> Config:
            c = cfg()
            # tiny synthetic clouds carry no dominant RANSAC plane: the
            # default clean chain would fail every view
            c.serving.clean_steps = "statistical"
            return c

        for i in range(args.serve_runs):
            spec = rng.choice(SERVE_RULES)
            sroot = os.path.join(tmp, f"serve_{i:03d}")
            t0 = time.monotonic()
            svc = serving.ScanService(sroot, cfg=serve_cfg(),
                                      log=lambda m: None)
            svc.start()
            faults.configure(spec, seed=args.seed + 2000 + i)
            crashed = False
            try:
                for tenant in ("ta", "tb"):
                    svc.submit({"tenant": tenant, "target": root,
                                "calib": calib})
            except faults.InjectedCrash:
                crashed = True       # died in the submit path itself
            t_end = t0 + args.budget_s
            while not crashed and time.monotonic() < t_end:
                if svc.phase == "crashed":
                    crashed = True
                    break
                with svc.adm.lock:
                    jobs = list(svc.adm.jobs.values())
                if jobs and all(j.state in TERMINAL for j in jobs):
                    break
                time.sleep(0.1)
            faults.reset()
            svc.close()
            outcome = "crashed" if crashed else "survived"

            svc2 = serving.ScanService(sroot, cfg=serve_cfg(),
                                       log=lambda m: None)
            svc2.start()
            t_end = time.monotonic() + args.budget_s
            settled = False
            while time.monotonic() < t_end:
                with svc2.adm.lock:
                    jobs = list(svc2.adm.jobs.values())
                if all(j.state in TERMINAL for j in jobs):
                    settled = True
                    break
                time.sleep(0.1)
            states = {j.scan_id: j.state for j in jobs}
            svc2.close()
            wall = time.monotonic() - t0
            walls.append(round(wall, 1))
            if not settled:
                return fail(f"serve run {i} [{spec}] not settled after "
                            f"restart: {states}")
            bad = {s: st for s, st in states.items()
                   if st not in ("done", "degraded")}
            if bad:
                return fail(f"serve run {i} [{spec}] accepted requests "
                            f"not recovered: {bad}")
            try:
                rs = replay_serving(os.path.join(sroot, "ledger.jsonl"))
            except ValueError as e:
                return fail(f"serve run {i} [{spec}] ledger invalid: {e}")
            outcomes[f"serve-{outcome}"] = \
                outcomes.get(f"serve-{outcome}", 0) + 1
            print(f"[soak] serve run {i}: {outcome:<9} {wall:5.1f}s  "
                  f"[{spec}] ({len(states)} scan(s), "
                  f"{len(rs['completed'])} credited item(s))")

        # ---- gateway-HA matrix (ISSUE 14): leader A + standby B over
        # one root; A is felled (crash or stalled-renew zombie) and B
        # must steal the epoch, resume, and settle every accepted
        # request — with the fold ignoring anything A raced in late.
        def ha_cfg() -> Config:
            c = serve_cfg()
            c.serving.ha_enabled = True
            c.serving.ha_lease_s = 1.5
            c.serving.ha_renew_s = 0.4
            c.serving.ha_poll_s = 0.3
            return c

        for i in range(args.ha_runs):
            kind = rng.choice(HA_KINDS)
            hroot = os.path.join(tmp, f"ha_{i:03d}")
            t0 = time.monotonic()
            a = serving.ScanService(hroot, cfg=ha_cfg(),
                                    log=lambda m: None)
            a.start()
            t_end = time.monotonic() + 30.0
            while a.role != "leader" and time.monotonic() < t_end:
                time.sleep(0.05)
            if a.role != "leader":
                a.close()
                return fail(f"ha run {i} [{kind}] member never led")
            b = serving.ScanService(hroot, cfg=ha_cfg(),
                                    log=lambda m: None)
            b.start()
            spec = HA_RULES[kind].format(leader=a.run_id)
            faults.configure(spec, seed=args.seed + 3000 + i)
            accepted = []
            try:
                for tenant in ("ta", "tb"):
                    ok, body = a.submit({"tenant": tenant, "target": root,
                                         "calib": calib})
                    if ok:
                        accepted.append(body["scan_id"])
            except faults.InjectedCrash:
                pass                 # died in the submit path itself
            if not accepted:
                faults.reset()
                b.close()
                a.close()
                return fail(f"ha run {i} [{kind}] leader accepted "
                            f"nothing")
            # settlement happens on the SURVIVING leader (B): it must
            # steal the epoch and bring every accepted request terminal
            t_end = t0 + 2 * args.budget_s
            states: dict = {}
            settled = False
            while time.monotonic() < t_end:
                ds = [b.status(s) for s in accepted]
                states = {s: (d["state"] if d else None)
                          for s, d in zip(accepted, ds)}
                if all(d is not None and d["state"] in TERMINAL
                       for d in ds):
                    settled = True
                    break
                time.sleep(0.1)
            faults.reset()
            b.close()
            a.close()
            wall = time.monotonic() - t0
            walls.append(round(wall, 1))
            if not settled:
                return fail(f"ha run {i} [{kind}] not settled on the "
                            f"standby: {states}")
            bad = {s: st for s, st in states.items()
                   if st not in ("done", "degraded")}
            if bad:
                return fail(f"ha run {i} [{kind}] accepted requests not "
                            f"recovered: {bad}")
            try:
                rs = replay_serving(os.path.join(hroot, "ledger.jsonl"))
            except ValueError as e:
                return fail(f"ha run {i} [{kind}] ledger invalid: {e}")
            if rs["max_epoch"] < 2:
                return fail(f"ha run {i} [{kind}] no takeover journaled "
                            f"(max_epoch {rs['max_epoch']})")
            stuck = {s: rs["scans"][s]["state"] for s in accepted
                     if s in rs["scans"]
                     and rs["scans"][s]["state"] not in ("done",
                                                         "degraded")}
            if stuck:
                return fail(f"ha run {i} [{kind}] fold disagrees (stale-"
                            f"epoch credit leaked?): {stuck}")
            outcomes[f"ha-{kind}"] = outcomes.get(f"ha-{kind}", 0) + 1
            print(f"[soak] ha run    {i}: {kind:<14} {wall:5.1f}s  "
                  f"epoch {rs['max_epoch']}, "
                  f"{rs['stale_ignored']} stale record(s) fenced out of "
                  f"the fold ({len(accepted)} scan(s))")

        # ---- elastic-fleet kill matrix (ISSUE 18): an in-process
        # gateway with the FleetSupervisor armed, under a drawn chaos
        # kind — real SIGKILLs of spawned fleet workers, spawn/decide
        # faults, or a supervisor crash followed by a fault-free resume
        # over the same root. Contract per run: all accepted requests
        # done/degraded, the fleet quiesced back to target, and the
        # journaled decisions replaying to the live supervisor's state.
        from structured_light_for_3d_model_replication_tpu.parallel.fleet import (  # noqa: E501
            replay_fleet,
        )

        def fleet_cfg() -> Config:
            c = serve_cfg()
            c.serving.fleet_enabled = True
            c.serving.fleet_min_workers = 1   # the floor keeps a worker
            c.serving.fleet_max_workers = 2   # up so every kind can kill
            c.serving.fleet_poll_s = 0.1
            c.serving.fleet_scale_up_queue = 2
            c.serving.fleet_scale_in_idle_s = 2.0
            c.serving.fleet_backoff_s = 0.2
            c.serving.fleet_backoff_max_s = 2.0
            return c

        FLEET_KINDS = ["kill-worker", "kill-worker-x2", "spawn-flap",
                       "decide-skip", "supervisor-crash"]
        FLEET_RULES = {"spawn-flap": "worker.spawn:transientx2",
                       "decide-skip": "fleet.decide:transient@2x3",
                       "supervisor-crash": "fleet.decide:crash@6"}

        for i in range(args.fleet_runs):
            kind = rng.choice(FLEET_KINDS)
            froot = os.path.join(tmp, f"fleet_{i:03d}")
            t0 = time.monotonic()
            spec = FLEET_RULES.get(kind, "")
            if spec:
                faults.configure(spec, seed=args.seed + 4000 + i)
            svc = serving.ScanService(froot, cfg=fleet_cfg(),
                                      log=lambda m: None)
            svc.start()
            accepted = []
            try:
                for tenant in ("ta", "tb"):
                    ok, body = svc.submit({"tenant": tenant,
                                           "target": root,
                                           "calib": calib})
                    if ok:
                        accepted.append(body["scan_id"])
            except faults.InjectedCrash:
                pass                 # died in the submit path itself
            kills = {"kill-worker": 1, "kill-worker-x2": 2}.get(kind, 0)
            killed: list[int] = []
            crashed = False
            t_end = t0 + args.budget_s
            while time.monotonic() < t_end:
                if svc.phase == "crashed":
                    crashed = True
                    break
                if kills and svc.fleet is not None:
                    # SIGKILL a fleet worker we have not killed yet (a
                    # respawned incarnation is a NEW pid, so x2 kills the
                    # healed worker too)
                    fresh = [p for p in svc.fleet.state()["pids"].values()
                             if p not in killed]
                    if fresh:
                        try:
                            os.kill(fresh[0], signal.SIGKILL)
                            killed.append(fresh[0])
                            kills -= 1
                        except OSError:
                            pass     # lost the race with its own death
                with svc.adm.lock:
                    jobs = list(svc.adm.jobs.values())
                if accepted and jobs and all(j.state in TERMINAL
                                             for j in jobs):
                    break
                time.sleep(0.1)
            faults.reset()
            if not accepted and not crashed:
                svc.close()
                return fail(f"fleet run {i} [{kind}] accepted nothing")
            if crashed:
                # the supervisor (or its host service) died: restart
                # FAULT-FREE over the same root — replay_fleet hands the
                # new supervisor the journaled fleet to resume
                svc.close()
                svc = serving.ScanService(froot, cfg=fleet_cfg(),
                                          log=lambda m: None)
                svc.start()
            t_end = time.monotonic() + args.budget_s
            settled = False
            jobs = []
            while time.monotonic() < t_end:
                with svc.adm.lock:
                    jobs = list(svc.adm.jobs.values())
                if jobs and all(j.state in TERMINAL for j in jobs):
                    settled = True
                    break
                time.sleep(0.1)
            states = {j.scan_id: j.state for j in jobs}
            # quiesce + replay parity: the fleet drains to its floor and
            # the decision ledger folds to exactly the live state
            ledger_path = os.path.join(froot, "ledger.jsonl")
            parity = False
            rs_fleet: dict = {}
            st: dict = {}
            t_end = time.monotonic() + 90.0
            while settled and svc.fleet is not None \
                    and time.monotonic() < t_end:
                st = svc.fleet.state()
                rs_fleet = replay_fleet(ledger_path)
                if (not st["retiring"] and not st["respawning"]
                        and len(st["live"]) == st["target"]
                        and rs_fleet["live"] == st["live"]
                        and rs_fleet["target"] == st["target"]
                        and all(rs_fleet["generations"].get(r)
                                == st["generations"][r]
                                for r in st["live"])):
                    parity = True
                    break
                time.sleep(0.2)
            svc.close()
            wall = time.monotonic() - t0
            walls.append(round(wall, 1))
            if not settled:
                return fail(f"fleet run {i} [{kind}] not settled: "
                            f"{states}")
            bad = {s: stt for s, stt in states.items()
                   if stt not in ("done", "degraded")}
            if bad:
                return fail(f"fleet run {i} [{kind}] accepted requests "
                            f"not recovered: {bad}")
            if not parity:
                return fail(f"fleet run {i} [{kind}] decision ledger "
                            f"does not replay to the live fleet state: "
                            f"replay={rs_fleet} live={st}")
            if not rs_fleet.get("events"):
                return fail(f"fleet run {i} [{kind}] journaled no fleet "
                            f"decisions")
            try:
                replay_serving(os.path.join(froot, "ledger.jsonl"))
            except ValueError as e:
                return fail(f"fleet run {i} [{kind}] ledger invalid: {e}")
            outcomes[f"fleet-{kind}"] = \
                outcomes.get(f"fleet-{kind}", 0) + 1
            print(f"[soak] fleet run {i}: {kind:<16} {wall:5.1f}s  "
                  f"({len(states)} scan(s), {rs_fleet['events']} fleet "
                  f"event(s), {len(killed)} worker(s) SIGKILLed, "
                  f"final fleet {st['live']})")

        summary = json.dumps(outcomes, sort_keys=True)
        print(f"SOAK=ok runs={args.runs} seed={args.seed} "
              f"multiproc={args.multiproc_runs} serve={args.serve_runs} "
              f"ha={args.ha_runs} fleet={args.fleet_runs} "
              f"outcomes={summary} max_wall={max(walls)}s")
        return 0
    finally:
        signal.alarm(0)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
