#!/usr/bin/env python
"""CI smoke for ``sl3d serve`` (ISSUE 12): a real gateway, two concurrent
tenants, one of them carrying a seeded permanent ``compute.view`` fault on
one view.

Asserts, end to end over HTTP (no thresholds — completion + identity):
  * the clean tenant's request completes DONE and its downloaded
    /result PLY + STL are byte-identical to a solo ``run_pipeline`` of
    the same input (the PR-8 parity construction carried to serving);
  * the faulty tenant completes DEGRADED (its faulted view quarantined,
    survivors >= the min_views floor) — the per-request failure domain:
    one tenant's fault never touches the other's request;
  * /metrics scrapes as Prometheus exposition with per-tenant labels on
    the request counters.

Prints ``SERVE_SMOKE=ok`` and exits 0 on success. Numpy backend: this is
the policy/parity smoke; the batched device lane has its own bench arm
(``bench.py --serve-only``).
"""
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.pipeline import serving
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

CAM, PROJ = (160, 120), (128, 64)
STEPS = ("statistical",)
TERMINAL = ("done", "degraded", "failed", "aborted")


def render_scan(tgt: str, views: int, shift: float) -> None:
    """Per-tenant satellite offset: EVERY view's bytes distinct across
    tenants (identical bytes would dedup to one tenant's cache entry and
    the faulted view would be served from the other tenant's warm work)."""
    rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
    scene = syn.sphere_on_background()
    obj, background = scene.objects
    satellite = syn.Sphere(np.array([48.0 + shift, -92.0, 430.0]), 16.0)
    step = 360.0 / views
    pivot = np.array([0.0, 0.0, 420.0])
    for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
        frames, _ = syn.render_scene(
            rig, syn.Scene([obj.transformed(R, t),
                            satellite.transformed(R, t), background]))
        imio.save_stack(
            os.path.join(tgt, f"scan_{int(round(i * step)):03d}deg_scan"),
            frames)


def make_cfg() -> Config:
    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = PROJ
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 512
    cfg.merge.icp_iters = 10
    cfg.mesh.depth = 5
    cfg.mesh.density_trim_quantile = 0.0
    cfg.serving.clean_steps = "statistical"
    cfg.serving.host = "127.0.0.1"
    cfg.serving.port = 0
    return cfg


def post_json(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200, r.status
        return json.loads(r.read())


def get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=30) as r:
        assert r.status == 200, (url, r.status)
        return r.read()


def wait_terminal(base: str, sid: str, timeout_s: float = 300.0) -> dict:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        d = json.loads(get(f"{base}/status/{sid}"))
        if d["state"] in TERMINAL:
            return d
        time.sleep(0.25)
    raise TimeoutError(f"{sid} still {d['state']} after {timeout_s}s")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="sl3d_serve_smoke_")
    try:
        rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
        calib = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib, rig.calibration())
        # clean tenant: 2 views; faulty tenant: 3 views, so ONE faulted
        # view still leaves it at the min_views floor -> DEGRADED, not
        # the below-floor abort
        tgt_clean = os.path.join(tmp, "in_tclean")
        tgt_fault = os.path.join(tmp, "in_tfault")
        os.makedirs(tgt_clean)
        os.makedirs(tgt_fault)
        render_scan(tgt_clean, views=2, shift=0.0)
        render_scan(tgt_fault, views=3, shift=9.0)

        # solo reference for the clean tenant (no faults armed)
        solo = os.path.join(tmp, "solo")
        rep = stages.run_pipeline(calib, tgt_clean, solo, cfg=make_cfg(),
                                  steps=STEPS, log=lambda m: None)
        assert rep.failed == [], rep.failed
        print("[serve_smoke] solo reference done "
              f"({rep.merged_points:,} points)")

        cfg = make_cfg()
        cfg.faults.spec = "compute.view~in_tfault/scan_000:permanent"
        faults.configure_from(cfg.faults)
        httpd, svc = serving.start_gateway(os.path.join(tmp, "svc"),
                                           cfg=cfg, log=lambda m: None)
        th = threading.Thread(target=httpd.serve_forever,
                              kwargs={"poll_interval": 0.1}, daemon=True)
        th.start()
        base = (f"http://{httpd.server_address[0]}:"
                f"{httpd.server_address[1]}")
        print(f"[serve_smoke] gateway up at {base}")
        try:
            sid_c = post_json(f"{base}/submit",
                              {"tenant": "tclean", "target": tgt_clean,
                               "calib": calib})["scan_id"]
            sid_f = post_json(f"{base}/submit",
                              {"tenant": "tfault", "target": tgt_fault,
                               "calib": calib})["scan_id"]
            st_c = wait_terminal(base, sid_c)
            st_f = wait_terminal(base, sid_f)
            print(f"[serve_smoke] {sid_c}: {st_c['state']}; "
                  f"{sid_f}: {st_f['state']}")
            assert st_c["state"] == "done", st_c
            assert st_f["state"] == "degraded", st_f

            ply = get(f"{base}/result/{sid_c}?artifact=ply")
            stl = get(f"{base}/result/{sid_c}?artifact=stl")
            with open(os.path.join(solo, "merged.ply"), "rb") as f:
                ply_ok = f.read() == ply
            with open(os.path.join(solo, "model.stl"), "rb") as f:
                stl_ok = f.read() == stl
            print(f"[serve_smoke] clean-tenant parity: ply={ply_ok} "
                  f"stl={stl_ok}")
            assert ply_ok and stl_ok, "clean tenant diverged from solo run"
            # the degraded tenant still ships a (reduced) result
            assert get(f"{base}/result/{sid_f}?artifact=ply")

            text = get(f"{base}/metrics").decode()
            for needle in (
                    'sl3d_serve_requests_total{state="done",'
                    'tenant="tclean"} 1',
                    'sl3d_serve_requests_total{state="degraded",'
                    'tenant="tfault"} 1',
                    'sl3d_serve_views_warmed_total{tenant="tclean"}',
                    'sl3d_serve_view_failures_total{tenant="tfault"}',
            ):
                assert needle in text, f"metrics missing: {needle}"
            print("[serve_smoke] /metrics exposes per-tenant counters")
        finally:
            httpd.shutdown()
            httpd.server_close()
            svc.close()
            faults.reset()
        print("SERVE_SMOKE=ok")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
