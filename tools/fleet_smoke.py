#!/usr/bin/env python
"""CI smoke for the elastic fleet (ISSUE 18): a real gateway with the
fleet supervisor on, driven over HTTP, with a real SIGKILL in the middle.

Asserts, end to end (no thresholds — completion + identity + ledger):
  * under load the supervisor scales 0 -> 2 ``sl3d worker`` processes
    (spawn decisions journaled with their signal snapshots);
  * both tenants' requests complete DONE with /result PLY + STL
    byte-identical to solo ``run_pipeline`` runs of the same inputs —
    fleet workers only warm the shared content-addressed cache, so
    parity is the PR-8 construction whoever computed each view;
  * SIGKILLing a worker respawns its RANK with a bumped generation
    (visible in the ledger, the supervisor state, AND the respawned
    worker's own hello — the spec->hello->trace generation thread);
  * a fully idle fleet scales back in to the floor (0), draining via
    clean shutdown grants;
  * ``replay_fleet`` over the ledger reproduces the live supervisor's
    final state — the scaling history is replayable.

Prints ``FLEET_SMOKE=ok`` and exits 0 on success. Numpy backend.
"""
import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.parallel.fleet import (
    replay_fleet,
)
from structured_light_for_3d_model_replication_tpu.pipeline import serving
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

from serve_smoke import CAM, PROJ, STEPS, get, post_json, render_scan, \
    wait_terminal  # noqa: E402  (same dir; the shared smoke idiom)


def make_cfg() -> Config:
    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = PROJ
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 512
    cfg.merge.icp_iters = 10
    cfg.mesh.depth = 5
    cfg.mesh.density_trim_quantile = 0.0
    cfg.serving.clean_steps = "statistical"
    cfg.serving.host = "127.0.0.1"
    cfg.serving.port = 0
    cfg.serving.fleet_enabled = True
    cfg.serving.fleet_min_workers = 0
    cfg.serving.fleet_max_workers = 2
    cfg.serving.fleet_poll_s = 0.1
    cfg.serving.fleet_scale_up_queue = 2
    cfg.serving.fleet_scale_in_idle_s = 10.0
    cfg.serving.fleet_backoff_s = 0.2
    return cfg


def wait_for(pred, what: str, timeout_s: float = 90.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        v = pred()
        if v:
            return v
    raise TimeoutError(f"timed out waiting for {what} after {timeout_s}s")


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="sl3d_fleet_smoke_")
    try:
        calib = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(
            calib, syn.default_rig(cam_size=CAM,
                                   proj_size=PROJ).calibration())
        targets, solos = [], []
        for i, shift in enumerate((0.0, 9.0)):
            tgt = os.path.join(tmp, f"in_t{i}")
            os.makedirs(tgt)
            render_scan(tgt, views=3, shift=shift)
            solo = os.path.join(tmp, f"solo{i}")
            rep = stages.run_pipeline(calib, tgt, solo, cfg=make_cfg(),
                                      steps=STEPS, log=lambda m: None)
            assert rep.failed == [], rep.failed
            targets.append(tgt)
            solos.append(solo)
        print("[fleet_smoke] solo references done")

        root = os.path.join(tmp, "svc")
        httpd, svc = serving.start_gateway(root, cfg=make_cfg(),
                                           log=lambda m: None)
        threading.Thread(target=httpd.serve_forever,
                         kwargs={"poll_interval": 0.1},
                         daemon=True).start()
        base = (f"http://{httpd.server_address[0]}:"
                f"{httpd.server_address[1]}")
        assert svc.fleet is not None, "fleet supervisor did not start"
        print(f"[fleet_smoke] gateway up at {base} "
              f"(fleet bridge {svc.fleet.server.endpoint})")
        try:
            sids = [post_json(f"{base}/submit",
                              {"tenant": f"t{i}", "target": tgt,
                               "calib": calib})["scan_id"]
                    for i, tgt in enumerate(targets)]

            # scale-up: 6 pending items / scale_up_queue=2, capped at 2
            wait_for(lambda: len(svc.fleet.state()["live"]) >= 2,
                     "scale-up to 2 workers")
            print(f"[fleet_smoke] scaled 0 -> 2: "
                  f"{svc.fleet.state()['live']}")

            for sid, solo in zip(sids, solos):
                st = wait_terminal(base, sid)
                assert st["state"] == "done", st
                for art, name in (("ply", "merged.ply"),
                                  ("stl", "model.stl")):
                    got = get(f"{base}/result/{sid}?artifact={art}")
                    with open(os.path.join(solo, name), "rb") as f:
                        assert f.read() == got, \
                            f"{sid} {name} diverged from solo"
            print("[fleet_smoke] both tenants done, PLY/STL byte-parity "
                  "vs solo holds")

            # the kill: wait for a worker to be fully up (hello'd), then
            # SIGKILL it and watch the SAME RANK come back at gen+1
            wait_for(lambda: svc.fleet.state()["hellos"],
                     "first worker hello")
            st = svc.fleet.state()
            rank = st["live"][0]
            pid = st["pids"][rank]
            os.kill(pid, signal.SIGKILL)
            print(f"[fleet_smoke] SIGKILLed fw{rank} (pid {pid})")
            wait_for(lambda: (svc.fleet.state()["generations"]
                              .get(rank, 0) >= 1),
                     f"fw{rank} respawn with bumped generation")
            gen = svc.fleet.state()["generations"][rank]
            print(f"[fleet_smoke] fw{rank} healed as generation {gen}")
            # the respawned incarnation's own hello carries the stamp
            wait_for(lambda: (svc.fleet.state()["hellos"]
                              .get(f"fw{rank}", {})
                              .get("generation", 0) >= 1),
                     "respawned worker hello with generation")

            # scale-in: idle past fleet_scale_in_idle_s drains to floor 0
            wait_for(lambda: not svc.fleet.state()["live"]
                     and not svc.fleet.state()["respawning"],
                     "scale-in to 0 on idle")
            print("[fleet_smoke] scaled in to 0 on idle")

            # the ledger replays to the live final state
            rs = replay_fleet(svc._ledger_path)
            live_st = svc.fleet.state()
            assert rs["live"] == live_st["live"] == [], \
                (rs["live"], live_st["live"])
            assert rs["target"] == live_st["target"] == 0, \
                (rs["target"], live_st["target"])
            assert rs["generations"].get(rank, 0) >= 1, rs["generations"]
            actions: dict = {}
            with open(svc._ledger_path, encoding="utf-8") as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("type") == "fleet":
                        a = ev.get("action", "?")
                        actions[a] = actions.get(a, 0) + 1
            for need in ("scale-up", "spawn", "worker-exit", "respawn",
                         "scale-in", "retired"):
                assert actions.get(need), \
                    f"ledger missing fleet action {need!r}: {actions}"
            print(f"[fleet_smoke] decision ledger replays "
                  f"({rs['events']} fleet events: {actions})")

            text = get(f"{base}/metrics").decode()
            assert "sl3d_fleet_spawns_total" in text, "fleet metrics"
        finally:
            httpd.shutdown()
            httpd.server_close()
            svc.close()
        print("FLEET_SMOKE=ok")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
