#!/usr/bin/env python
"""CI smoke for gateway HA (ISSUE 14): TWO real ``sl3d serve`` processes
over one shared root, the leader felled by an injected ``serve.crash``
at the assembly boundary (exit 137, lease never released — a kill -9
twin), the standby left to steal the expired lease and finish the work.

Asserts, end to end over HTTP against the real CLI entry:
  * exactly one member leads (healthz ``role``/``epoch``); the follower
    answers /submit with the machine-readable ``not-leader`` redirect
    pointing at the live leader, and serve.json carries the leader's
    address + epoch;
  * after the leader dies 137 mid-assembly the standby PROMOTES within
    the lease bound (measured ``failover_s``: leader death -> standby
    reports role=leader with the bumped epoch) and atomically rewrites
    serve.json so stale clients re-discover;
  * the orphaned request finishes DONE on the new leader with ZERO
    recompute (``views_computed == 0`` — every epoch-1-credited view is
    a cache hit) and /result PLY + STL byte-identical to a solo
    ``run_pipeline``: the PR-8 parity construction carried across the
    takeover;
  * the client's durable scan_id is idempotent across the failover —
    the same re-POST lands on the existing (done) request;
  * SIGTERM on the survivor drains and exits 0.

Prints ``HA_SMOKE=ok`` and exits 0 on success.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    TERMINAL,
    replay_serving,
)
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn
from serve_smoke import STEPS, make_cfg, post_json, get, render_scan

CAM, PROJ = (160, 120), (128, 64)
LEASE_S = 3.0

_SETS = [
    "parallel.backend=numpy",
    f"decode.n_cols={PROJ[0]}", f"decode.n_rows={PROJ[1]}",
    "decode.thresh_mode=manual",
    "merge.voxel_size=4.0", "merge.ransac_trials=512",
    "merge.icp_iters=10",
    "mesh.depth=5", "mesh.density_trim_quantile=0.0",
    "serving.clean_steps=statistical",
    "serving.host=127.0.0.1", "serving.port=0",
    "serving.ha_enabled=true",
    f"serving.ha_lease_s={LEASE_S}",
    "serving.ha_poll_s=0.3",
]


def launch(root: str, ready: str, log_path: str,
           extra_sets=()) -> subprocess.Popen:
    cmd = [sys.executable, "-m",
           "structured_light_for_3d_model_replication_tpu.cli", "serve",
           root, "--ready-file", ready]
    for s in list(_SETS) + list(extra_sets):
        cmd += ["--set", s]
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   x for x in (repo, os.environ.get("PYTHONPATH")) if x))
    logf = open(log_path, "a")
    return subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                            env=env)


def wait_ready(ready: str, proc: subprocess.Popen,
               timeout_s: float = 120.0) -> str:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve exited {proc.returncode} before ready")
        if os.path.exists(ready):
            try:
                with open(ready) as f:
                    info = json.load(f)
                base = f"http://{info['host']}:{info['port']}"
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=5) as r:
                    if json.loads(r.read()).get("ok"):
                        return base
            except (ValueError, OSError, urllib.error.URLError):
                pass
        time.sleep(0.1)
    raise TimeoutError(f"serve not ready after {timeout_s}s")


def healthz(base: str) -> dict:
    return json.loads(get(f"{base}/healthz"))


def wait_role(base: str, role: str, timeout_s: float = 60.0) -> dict:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            h = healthz(base)
            if h["role"] == role:
                return h
        except (OSError, urllib.error.URLError):
            pass
        time.sleep(0.1)
    raise TimeoutError(f"{base} never became {role!r}")


def post_raw(url: str, payload: dict) -> tuple[int, dict]:
    """POST that hands back non-2xx bodies instead of raising — the
    follower redirect is a 503 we WANT to inspect."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="sl3d_ha_smoke_")
    try:
        rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
        calib = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib, rig.calibration())
        tgt = os.path.join(tmp, "in_tha")
        os.makedirs(tgt)
        render_scan(tgt, views=2, shift=0.0)

        solo = os.path.join(tmp, "solo")
        rep = stages.run_pipeline(calib, tgt, solo, cfg=make_cfg(),
                                  steps=STEPS, log=lambda m: None)
        assert rep.failed == [], rep.failed
        print(f"[ha] solo reference done ({rep.merged_points:,} points)")

        root = os.path.join(tmp, "svc")
        log_path = os.path.join(tmp, "serve.log")
        payload = {"tenant": "tha", "target": tgt, "calib": calib,
                   "scan_id": "h1"}

        # ---- gen 1: leader, armed to crash at the assembly boundary --
        ready1 = os.path.join(tmp, "ready1.json")
        p1 = launch(root, ready1, log_path,
                    extra_sets=["faults.spec=serve.crash~assembly"
                                ":crash"])
        base1 = wait_ready(ready1, p1)
        h1 = wait_role(base1, "leader")
        assert h1["epoch"] == 1, h1
        print(f"[ha] gen-1 LEADER at {base1} (pid {p1.pid}, epoch 1, "
              f"crash armed)")

        # ---- gen 2: joins as follower --------------------------------
        ready2 = os.path.join(tmp, "ready2.json")
        p2 = launch(root, ready2, log_path)
        base2 = wait_ready(ready2, p2)
        h2 = healthz(base2)
        assert h2["role"] == "follower" and h2["epoch"] == 0, h2
        print(f"[ha] gen-2 follower at {base2} (pid {p2.pid})")

        # serve.json is the leader's discovery record
        with open(os.path.join(root, "serve.json")) as f:
            sj = json.load(f)
        assert sj["epoch"] == 1 and sj["pid"] == p1.pid, sj

        # follower /submit: machine-readable redirect at the live leader
        code, body = post_raw(f"{base2}/submit", payload)
        assert code == 503, (code, body)
        assert body["reason"] == "not-leader", body
        assert body["leader"]["url"] == base1, body
        assert body["retry_after_s"] > 0, body
        print(f"[ha] follower redirected /submit to {body['leader']['url']}"
              f" (503 not-leader, epoch {body['epoch']})")

        # ---- submit to the leader; it dies mid-assembly --------------
        body = post_json(f"{base1}/submit", payload)
        sid = body["scan_id"]
        print(f"[ha] leader accepted {sid}; waiting for the crash")
        rc = p1.wait(timeout=300)
        t_death = time.monotonic()
        assert rc == 137, f"expected exit 137 (injected crash), got {rc}"
        rs = replay_serving(os.path.join(root, "ledger.jsonl"))
        assert rs["scans"][sid]["state"] not in TERMINAL, rs["scans"][sid]
        print(f"[ha] leader died 137 mid-flight; {sid} is "
              f"{rs['scans'][sid]['state']!r}, "
              f"{len(rs['completed'])} view(s) credited under epoch 1")

        # ---- failover: the standby steals the expired lease ----------
        h2 = wait_role(base2, "leader",
                       timeout_s=LEASE_S + 30.0)
        failover_s = time.monotonic() - t_death
        assert h2["epoch"] == 2, h2
        # lease bound: expiry (<= LEASE_S after the leader's last renew,
        # which was at most a renew tick before death) + poll + resume
        assert failover_s <= LEASE_S + 10.0, failover_s
        with open(os.path.join(root, "serve.json")) as f:
            sj = json.load(f)
        assert sj["epoch"] == 2 and sj["pid"] == p2.pid, sj
        print(f"[ha] standby promoted in failover_s={failover_s:.2f} "
              f"(lease {LEASE_S}s, epoch 2, serve.json rewritten)")

        try:
            t0 = time.monotonic()
            while True:
                d = json.loads(get(f"{base2}/status/{sid}"))
                if d["state"] in TERMINAL:
                    break
                assert time.monotonic() - t0 < 300.0, d
                time.sleep(0.25)
            assert d["state"] == "done", d
            report = d.get("report") or {}
            assert report.get("views_computed") == 0, report
            print(f"[ha] new leader finished {sid} with zero recompute "
                  f"({report.get('views_cached')} cached view(s))")

            ply = get(f"{base2}/result/{sid}?artifact=ply")
            stl = get(f"{base2}/result/{sid}?artifact=stl")
            with open(os.path.join(solo, "merged.ply"), "rb") as f:
                assert f.read() == ply, "PLY diverged across failover"
            with open(os.path.join(solo, "model.stl"), "rb") as f:
                assert f.read() == stl, "STL diverged across failover"
            print("[ha] byte parity with solo run holds across the "
                  "takeover")

            body = post_json(f"{base2}/submit", payload)
            assert body.get("duplicate") is True, body
            assert body["scan_id"] == sid and body["state"] == "done"
            print("[ha] re-POST of the original submit is idempotent "
                  "across the failover")
        finally:
            if p2.poll() is None:
                p2.send_signal(signal.SIGTERM)
        rc = p2.wait(timeout=120)
        assert rc == 0, f"SIGTERM drain should exit 0, got {rc}"
        with open(log_path) as f:
            assert "stopped cleanly" in f.read()
        print("[ha] SIGTERM drained the survivor, exit 0")
        print(f"HA_SMOKE=ok failover_s={failover_s:.2f}")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
