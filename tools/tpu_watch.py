"""Tunnel-recovery watcher: probe the device tunnel on a gentle cadence and
start the one-shot validation session (tools/tpu_session.py) the moment a
probe passes.

The wedge pattern (BENCH_NOTES.md) is hours-long outages that recover on
the remote side at an unpredictable time; recovery windows can be short, so
an unattended watcher converts "tunnel healed at 3am" into "measurements
captured at 3am". The probe is utils.preflight.accelerator_preflight —
init + ONE device op, 180 s bound — so the init-ok/exec-stalled signature
(round-4 incident) cannot trigger a doomed session.

Run detached:  setsid nohup python tools/tpu_watch.py > tpu_watch.log 2>&1 &
Stop:          kill the printed pid. Safe at any moment: it never holds a
               claim while sleeping, and a running tpu_session child owns
               its OWN .tpu_lock (it keeps excluding other TPU clients
               even orphaned — let it finish or kill its whole process
               group too).
"""
import argparse
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg: str) -> None:
    print(f"[tpu-watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gap", type=float, default=1200.0,
                    help="seconds between probe STARTS (the ~20 min "
                         "cadence from BENCH_NOTES)")
    ap.add_argument("--round", type=int, default=4)
    ap.add_argument("--max-hours", type=float, default=24.0,
                    help="give up after this long")
    ap.add_argument("--probe-only", action="store_true",
                    help="log probe verdicts without starting the session")
    ap.add_argument("--max-sessions", type=int, default=3,
                    help="give up after this many failed session attempts "
                         "(a deterministic session bug with a healthy "
                         "tunnel would otherwise re-run the multi-hour "
                         "chain back-to-back all watch long)")
    args = ap.parse_args()

    sys.path.insert(0, ROOT)
    from structured_light_for_3d_model_replication_tpu.utils.preflight import (
        accelerator_preflight,
    )
    from structured_light_for_3d_model_replication_tpu.utils.tpulock import (
        acquire_tpu_lock,
    )

    log(f"pid {os.getpid()} — probing every {args.gap:.0f}s for up to "
        f"{args.max_hours:.1f}h")
    # monotonic, never wall-clock: deadline arithmetic must not move with
    # NTP steps or suspend/resume (repo-wide convention, utils/deadline.py)
    t_end = time.monotonic() + args.max_hours * 3600
    n = 0
    same_failure = 0
    sessions = 0
    last_detail = None
    while time.monotonic() < t_end:
        n += 1
        t0 = time.time()
        # hold the repo-wide claim lock across probe AND session: a probe
        # is itself a brief TPU client, and racing one against the
        # driver's round-end bench.py is the concurrent-client wedge
        lock = acquire_tpu_lock(ROOT, timeout=0)
        if lock is None:
            log(f"probe #{n}: skipped — .tpu_lock held elsewhere (another "
                f"TPU client is active; it dies with its holder)")
            time.sleep(args.gap)
            continue
        status, detail = accelerator_preflight(cwd=ROOT)
        log(f"probe #{n}: {status} ({detail}) [{time.time() - t0:.0f}s]")
        # 'hung' is the recoverable wedge we are here to outlast; 'failed'
        # with an identical stderr tail, or a cpu-only backend, fails
        # deterministically every time (same early-exit bench.py's
        # _wait_for_accelerator applies) — probing for hours won't help
        if (status, detail) == last_detail:
            same_failure += 1
        else:
            same_failure = 0
        last_detail = (status, detail)
        if status == "failed" and same_failure >= 2:
            log("3 identical deterministic failures — giving up")
            return
        if status == "ok" and detail == "cpu":
            # NOT a reason to stop on this rig: the ambient backend is the
            # accelerator whenever the tunnel is healthy, so a cpu verdict
            # means the plugin failed FAST this instant — a wedge variant
            # observed alternating with the hung signature (r4). Keep
            # watching; the 24 h cap bounds us.
            log("plugin failed fast — jax fell back to cpu (wedge "
                "variant); still watching")
            if lock is not None:
                lock.close()
            time.sleep(args.gap)
            continue
        if status == "ok":
            if args.probe_only:
                log("tunnel healthy (probe-only mode; not starting session)")
            else:
                log("tunnel healthy — starting tpu_session")
                sessions += 1
                # hand the claim over rather than down: the session takes
                # its OWN lock so the claim lives exactly as long as the
                # session process — if this watcher is killed mid-session,
                # the orphaned session keeps excluding other clients
                # (inheriting our lock via HOLD_ENV would instead release
                # it with us while the session runs on). The handoff gap is
                # milliseconds; the session waits up to 120 s for the lock.
                lock.close()
                lock = None
                rc = subprocess.call(
                    [sys.executable, "tools/tpu_session.py",
                     "--round", str(args.round), "--skip-preflight"],
                    cwd=ROOT)
                log(f"tpu_session exited rc={rc}")
                if rc == 0:
                    log("session complete — watcher done")
                    return
                if sessions >= args.max_sessions:
                    log(f"{sessions} failed session attempts — giving up")
                    return
                log("session did not complete cleanly — resuming watch")
        if lock is not None:
            lock.close()  # release between cycles; never sleep holding a claim
        dt = time.time() - t0
        if dt < args.gap:
            time.sleep(args.gap - dt)
    log("max watch time reached without a completed session")


if __name__ == "__main__":
    main()
