#!/usr/bin/env python
"""Seeded chaos smoke for CI (run by tools/ci_tier1.sh).

Renders a 5-view synthetic turntable dataset, arms the deterministic
fault-injection plan from ISSUE 3's acceptance criteria — one transient
``frame.load`` fault (must be absorbed by a backoff retry) plus one
permanent ``compute.view`` fault (must quarantine that view) — and runs
``sl3d pipeline`` end to end, asserting the resilience contract:

  - exit code 0: a degraded-but-completed run is a success
  - exactly 1 FailureRecord in the failure manifest, >= 1 retry recorded
  - the merged STL exists (4 of 5 views merged)
  - the quarantine folder holds the failed view's record

A second run (ISSUE 7) injects a ``stall`` — a load that simply never
returns for longer than its lane deadline — into a FRESH out dir and
asserts the deadline layer's contract: the run still exits 0, the stalled
view is quarantined with a ``DeadlineExceeded`` record, and the STL
ships.

Prints ``CHAOS_SMOKE=ok`` (exit 0) or ``CHAOS_SMOKE=FAIL (...)`` (exit 1).
"""
import json
import os
import shutil
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# arm via env (wins over any config file) BEFORE the CLI reads config;
# 5 views at 72 deg/step -> folders 000/072/144/216/288
FAULT_SPEC = "frame.load~000deg:transient,compute.view~216deg:permanent"


def fail(why: str) -> int:
    print(f"CHAOS_SMOKE=FAIL ({why})")
    return 1


def main() -> int:
    os.environ["SL3D_FAULTS"] = FAULT_SPEC
    os.environ["SL3D_FAULTS_SEED"] = "0"
    from structured_light_for_3d_model_replication_tpu.cli import (
        main as cli_main,
    )

    tmp = tempfile.mkdtemp(prefix="slchaos_")
    try:
        root = os.path.join(tmp, "dataset")
        out = os.path.join(tmp, "out")
        rc = cli_main(["synth", root, "--views", "5",
                       "--cam", "160x120", "--proj", "128x64"])
        if rc != 0:
            return fail(f"synth rc={rc}")
        rc = cli_main([
            "pipeline", root, "--out", out,
            "--calib", os.path.join(root, "calib.mat"),
            "--steps", "statistical",
            "--set", "parallel.backend=numpy",
            "--set", "decode.n_cols=128", "--set", "decode.n_rows=64",
            "--set", "decode.thresh_mode=manual",
            "--set", "merge.voxel_size=4.0",
            "--set", "merge.ransac_trials=512",
            "--set", "merge.icp_iters=10",
            "--set", "mesh.depth=5",
            "--set", "mesh.density_trim_quantile=0",
        ])
        if rc != 0:
            return fail(f"pipeline rc={rc} (must exit 0 when degraded)")
        stl = os.path.join(out, "model.stl")
        if not os.path.exists(stl) or os.path.getsize(stl) == 0:
            return fail("merged STL missing after degraded run")
        manifest_path = os.path.join(out, "failures.json")
        if not os.path.exists(manifest_path):
            return fail("failure manifest missing")
        with open(manifest_path) as f:
            manifest = json.load(f)
        if len(manifest["failures"]) != 1:
            return fail(f"expected exactly 1 quarantined view, got "
                        f"{len(manifest['failures'])}")
        rec = manifest["failures"][0]
        if "216deg" not in rec["view"]:
            return fail(f"wrong view quarantined: {rec['view']}")
        if rec["transient"]:
            return fail("permanent fault misclassified as transient")
        if manifest["retries"] < 1:
            return fail("transient frame.load fault was not retried")
        if manifest["injected_faults"].get("frame.load") != 1:
            return fail(f"unexpected injection counts: "
                        f"{manifest['injected_faults']}")
        qrec = os.path.join(out, "quarantine", f"{rec['view']}.json")
        if not os.path.exists(qrec):
            return fail(f"quarantine record missing: {qrec}")

        # ---- stall case (ISSUE 7): a load that hangs past its lane
        # deadline must be quarantined like a permanent failure, never
        # hang the run. Fresh out dir: run 1's view cache would otherwise
        # satisfy every load and the site would never fire.
        os.environ["SL3D_FAULTS"] = "frame.load~072deg:stall(1.5)"
        out2 = os.path.join(tmp, "out_stall")
        rc = cli_main([
            "pipeline", root, "--out", out2,
            "--calib", os.path.join(root, "calib.mat"),
            "--steps", "statistical",
            "--set", "parallel.backend=numpy",
            "--set", "decode.n_cols=128", "--set", "decode.n_rows=64",
            "--set", "decode.thresh_mode=manual",
            "--set", "merge.voxel_size=4.0",
            "--set", "merge.ransac_trials=512",
            "--set", "merge.icp_iters=10",
            "--set", "mesh.depth=5",
            "--set", "mesh.density_trim_quantile=0",
            "--set", "deadlines.load_s=0.4",
        ])
        if rc != 0:
            return fail(f"stall pipeline rc={rc} (a stalled load must "
                        f"degrade, not hang or abort)")
        stl2 = os.path.join(out2, "model.stl")
        if not os.path.exists(stl2) or os.path.getsize(stl2) == 0:
            return fail("merged STL missing after stalled run")
        with open(os.path.join(out2, "failures.json")) as f:
            manifest2 = json.load(f)
        recs = manifest2.get("failures", [])
        if len(recs) != 1 or "072deg" not in recs[0]["view"]:
            return fail(f"stall case: expected 1 quarantined 072deg view, "
                        f"got {[(r['view'], r['error_type']) for r in recs]}")
        if recs[0]["error_type"] != "DeadlineExceeded":
            return fail(f"stall case: expected DeadlineExceeded, got "
                        f"{recs[0]['error_type']}")

        # ---- worker-kill case (ISSUE 9): kill EVERY worker on its first
        # granted item (the site has no ~substr filter and each worker
        # process arms its own fault plan, so the once-only counter fires
        # once per worker). Total worker loss must never hang or abort:
        # the coordinator journals the unfinished items as lost and the
        # single-process assembly pass recomputes them, so the run still
        # exits 0 and ships the STL.
        os.environ["SL3D_FAULTS"] = "worker.item:worker.kill"
        out3 = os.path.join(tmp, "out_wkill")
        rc = cli_main([
            "pipeline", root, "--out", out3, "--workers", "2",
            "--calib", os.path.join(root, "calib.mat"),
            "--steps", "statistical",
            "--set", "parallel.backend=numpy",
            "--set", "decode.n_cols=128", "--set", "decode.n_rows=64",
            "--set", "decode.thresh_mode=manual",
            "--set", "merge.voxel_size=4.0",
            "--set", "merge.ransac_trials=512",
            "--set", "merge.icp_iters=10",
            "--set", "mesh.depth=5",
            "--set", "mesh.density_trim_quantile=0",
        ])
        os.environ.pop("SL3D_FAULTS", None)
        if rc != 0:
            return fail(f"worker-kill pipeline rc={rc} (losing every "
                        f"worker must degrade to single-process assembly, "
                        f"not hang or abort)")
        stl3 = os.path.join(out3, "model.stl")
        if not os.path.exists(stl3) or os.path.getsize(stl3) == 0:
            return fail("merged STL missing after all-workers-killed run")
        lost = 0
        with open(os.path.join(out3, "ledger.jsonl")) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("type") == "lost":
                    lost += 1
        if lost < 1:
            return fail("all-workers-killed run journaled no lost items")

        # ---- fabric case (ISSUE 15): a 2-worker pod over REAL TCP with a
        # degraded wire — every worker's first blob fetch transiently
        # fails (absorbed by the client's single retry) and the first 10
        # control frames on each worker's sockets straggle 50 ms
        # (net.slowlink; nothing raises, throughput just sags). The run
        # must still exit 0 and ship the STL: the fabric is an
        # optimization, never a failure source.
        os.environ["SL3D_FAULTS"] = \
            "blob.fetch:transient@1,worker.sock:net.slowlink(0.05)x10"
        out4 = os.path.join(tmp, "out_fabric")
        rc = cli_main([
            "pipeline", root, "--out", out4, "--workers", "2",
            "--calib", os.path.join(root, "calib.mat"),
            "--steps", "statistical",
            "--set", "coordinator.listen=127.0.0.1:0",
            "--set", "coordinator.secret=chaos-pod",
            "--set", "parallel.backend=numpy",
            "--set", "decode.n_cols=128", "--set", "decode.n_rows=64",
            "--set", "decode.thresh_mode=manual",
            "--set", "merge.voxel_size=4.0",
            "--set", "merge.ransac_trials=512",
            "--set", "merge.icp_iters=10",
            "--set", "mesh.depth=5",
            "--set", "mesh.density_trim_quantile=0",
        ])
        os.environ.pop("SL3D_FAULTS", None)
        if rc != 0:
            return fail(f"fabric pipeline rc={rc} (a transient blob fault "
                        f"+ slow wire must degrade to retries/misses, "
                        f"never fail the run)")
        stl4 = os.path.join(out4, "model.stl")
        if not os.path.exists(stl4) or os.path.getsize(stl4) == 0:
            return fail("merged STL missing after degraded-wire fabric run")

        print(f"CHAOS_SMOKE=ok (1 view quarantined, "
              f"{manifest['retries']} retry(ies), STL "
              f"{os.path.getsize(stl)} bytes from 4/5 views; stall case: "
              f"1 DeadlineExceeded quarantine, STL shipped; worker-kill "
              f"case: 2/2 workers killed, {lost} item(s) lost, STL "
              f"shipped; fabric case: slow wire + transient blob fetch, "
              f"STL shipped)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
