"""Profile the 360-degree merge stages on the ambient backend.

Uses the bench scene cache (.bench_cache.npz at the repo root — run
`python bench.py` once to build it). Prints per-stage wall seconds for a
compile run and two steady runs, plus per-pair registration timings across
trial/iteration knobs with --register.

The script self-terminates; do NOT wrap it in a kill timer near its
expected runtime — SIGTERM mid-TPU-claim wedges the device tunnel for
hours (see BENCH_NOTES.md).
"""
import argparse
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--register", action="store_true",
                    help="also sweep register_pairs trial/ICP knobs")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--trials", type=int, default=2048,
                    help="ransac_trials for the merge runs (bench uses 2048; "
                         "the library default is 4096)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from structured_light_for_3d_model_replication_tpu.config import MergeConfig
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as rec,
    )
    from structured_light_for_3d_model_replication_tpu.ops import (
        registration as reg,
    )

    cache = os.path.join(ROOT, ".bench_cache.npz")
    if not os.path.exists(cache):
        sys.exit("no .bench_cache.npz — run `python bench.py` once first")
    z = np.load(cache)
    off = z["merge_off"]
    clouds = [(z["merge_pts"][off[i]:off[i + 1]],
               z["merge_cols"][off[i]:off[i + 1]])
              for i in range(len(off) - 1)]
    print(f"backend={jax.default_backend()} views={len(clouds)}")

    mcfg = MergeConfig(ransac_trials=args.trials)
    for it in range(args.runs):
        tm: dict = {}
        t0 = time.perf_counter()
        p, c, T = rec.merge_360(clouds, cfg=mcfg, log=lambda m: None,
                                timings=tm)
        print(f"run{it}: {time.perf_counter() - t0:.3f}s stages={tm} "
              f"pts={len(p)}")

    if not args.register:
        return
    cfg = MergeConfig()
    voxel = float(cfg.voxel_size)
    preps = rec._preprocess_views(clouds, voxel, cfg.sample_before)
    srcs, dsts = preps[1:], preps[:-1]
    stacked = (jnp.stack([x.points for x in srcs]),
               jnp.stack([x.valid for x in srcs]),
               jnp.stack([x.features for x in srcs]),
               jnp.stack([x.points for x in dsts]),
               jnp.stack([x.valid for x in dsts]),
               jnp.stack([x.features for x in dsts]),
               jnp.stack([x.normals for x in dsts]))
    for trials, icp_iters in ((4096, 30), (2048, 30), (1024, 30), (2048, 10)):
        t = np.inf
        for _ in range(2):
            t0 = time.perf_counter()
            T, gfit, ifit, _ = reg.register_pairs(
                *stacked, max_dist=voxel * 1.5,
                icp_max_dist=voxel * float(cfg.icp_dist_ratio),
                trials=trials, icp_iters=icp_iters)
            jax.block_until_ready(T)
            t = min(t, time.perf_counter() - t0)
        print(f"register trials={trials} icp_iters={icp_iters} "
              f"steady={t:.3f}s gfit={float(np.mean(np.asarray(gfit))):.3f} "
              f"ifit={float(np.mean(np.asarray(ifit))):.3f}")


if __name__ == "__main__":
    main()
