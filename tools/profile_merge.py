"""Profile the 360-degree merge stages on the ambient backend.

Uses the bench scene cache (.bench_cache.npz at the repo root — run
`python bench.py` once to build it). Prints per-stage wall seconds for a
compile run and two steady runs, plus per-pair registration timings across
trial/iteration knobs with --register.

The script self-terminates; do NOT wrap it in a kill timer near its
expected runtime — SIGTERM mid-TPU-claim wedges the device tunnel for
hours (see BENCH_NOTES.md).
"""
import argparse
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--register", action="store_true",
                    help="also sweep register_pairs trial/ICP knobs")
    ap.add_argument("--postprocess-ab", action="store_true",
                    help="A/B the postprocess compaction strategies on the "
                         "merged cloud: device-resident prefix slice vs "
                         "host compact-between-stages (the round-4 "
                         "transfer-trim hypothesis)")
    ap.add_argument("--outlier-ab", action="store_true",
                    help="A/B statistical outlier paths on the merged "
                         "cloud: exact voxelized ring probe (hinted), "
                         "unhinted exact, and the opt-in approx_min_k "
                         "route — times + mask agreement, so the exactness "
                         "default's cost is a number, not a guess")
    ap.add_argument("--features-ab", action="store_true",
                    help="attribute the per-view feature-prep device time "
                         "(kNN vs normals vs FPFH) and sweep the kNN "
                         "query-block size — feature prep is ~0.57 s of "
                         "the r5 on-chip register_s wait")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--trials", type=int, default=2048,
                    help="ransac_trials for the merge runs (bench: 512 "
                         "on-chip / 2048 CPU; library default 4096)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the cpu platform (smoke/debug; the env var "
                         "alone loses to this box's sitecustomize)")
    args = ap.parse_args()

    if not args.cpu:
        from structured_light_for_3d_model_replication_tpu.utils import (
            tpulock,
        )

        lock = tpulock.acquire_tpu_lock(ROOT, timeout=60)  # noqa: F841
        if lock is None:  # held for process lifetime; fd close releases
            sys.exit("another TPU client holds .tpu_lock — not opening a "
                     "concurrent claim (the lock dies with its holder)")

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from structured_light_for_3d_model_replication_tpu.config import MergeConfig
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as rec,
    )
    from structured_light_for_3d_model_replication_tpu.ops import (
        registration as reg,
    )

    cache = os.path.join(ROOT, ".bench_cache.npz")
    if not os.path.exists(cache):
        sys.exit("no .bench_cache.npz — run `python bench.py` once first")
    z = np.load(cache)
    off = z["merge_off"]
    clouds = [(z["merge_pts"][off[i]:off[i + 1]],
               z["merge_cols"][off[i]:off[i + 1]])
              for i in range(len(off) - 1)]
    print(f"backend={jax.default_backend()} views={len(clouds)}")

    mcfg = MergeConfig(ransac_trials=args.trials)
    merged_raw = None
    for it in range(args.runs):
        tm: dict = {}
        t0 = time.perf_counter()
        p, c, T = rec.merge_360(clouds, cfg=mcfg, log=lambda m: None,
                                timings=tm)
        print(f"run{it}: {time.perf_counter() - t0:.3f}s stages={tm} "
              f"pts={len(p)}", flush=True)

    def build_merged_raw():
        # rebuild the pre-postprocess merged cloud once, so the A/Bs time
        # their strategies on the identical real input
        pre = rec._preprocess_views(clouds, float(mcfg.voxel_size), 0)
        T_all, *_ = rec._register_chain_batched(pre, mcfg,
                                                float(mcfg.voxel_size),
                                                loop_closure=False)
        acc = np.eye(4, dtype=np.float32)
        parts = [np.asarray(clouds[0][0], np.float32)]
        for i in range(1, len(clouds)):
            acc = (acc @ T_all[i - 1]).astype(np.float32)
            parts.append(np.asarray(clouds[i][0], np.float32)
                         @ acc[:3, :3].T + acc[:3, 3])
        return (np.concatenate(parts).astype(np.float32),
                np.concatenate([c for _, c in clouds]).astype(np.uint8))

    if args.postprocess_ab:
        merged_raw, cols_raw = build_merged_raw()
        # isolate ONLY the compaction strategy: patching the fusion gate
        # keeps the outlier op on its real accelerator dispatch (faking the
        # backend name instead would reroute it onto the host-only grid
        # engine — which raises on accelerators for crash-safety)
        real_gate = rec._full_postprocess
        for label, gate in (("device-resident", real_gate),
                            ("host-compact", lambda cfg: False)):
            rec._full_postprocess = gate
            try:
                best = np.inf
                for _ in range(max(args.runs, 2)):
                    tm2: dict = {}
                    t0 = time.perf_counter()
                    pp, _ = rec._postprocess_merged(merged_raw.copy(),
                                                    cols_raw.copy(), mcfg,
                                                    tm2)
                    best = min(best, time.perf_counter() - t0)
                print(f"postprocess[{label}]: best {best:.3f}s stages={tm2} "
                      f"pts={len(pp)}", flush=True)
            finally:
                rec._full_postprocess = real_gate

    if args.outlier_ab:
        from structured_light_for_3d_model_replication_tpu.ops import (
            pointcloud as pc,
        )

        if merged_raw is None:
            merged_raw, cols_raw = build_merged_raw()
        # mirror the outlier stage's real input: final-voxel the merged
        # cloud and compact (host strategy keeps the A/B backend-neutral)
        vox = float(mcfg.final_voxel or mcfg.voxel_size)
        p_v, _, v_v = pc.voxel_downsample(merged_raw, cols_raw,
                                          np.ones(len(merged_raw), bool),
                                          vox)
        keep = np.asarray(v_v)
        pts = np.asarray(p_v)[keep]
        val = np.ones(len(pts), bool)
        print(f"outlier A/B input: {len(pts)} voxeled merged points "
              f"(cell {vox})", flush=True)
        masks = {}
        for label, kw in (("hinted-exact", {"voxelized_cell": vox}),
                          ("unhinted-exact", {}),
                          ("approx", {"approximate": True})):
            best = np.inf
            for _ in range(max(args.runs, 2)):
                t0 = time.perf_counter()
                m = np.asarray(pc.statistical_outlier_mask(
                    jnp.asarray(pts), jnp.asarray(val), mcfg.outlier_nb,
                    mcfg.outlier_std, **kw))
                best = min(best, time.perf_counter() - t0)
            masks[label] = m
            agree = float((m == masks["hinted-exact"]).mean())
            print(f"outlier[{label}]: best {best:.3f}s kept {int(m.sum())}"
                  f"/{len(m)} agree_vs_hinted={agree:.4f}", flush=True)

    if args.features_ab:
        from structured_light_for_3d_model_replication_tpu.ops import (
            knn as knnlib,
            normals as nrmlib,
        )

        voxel = float(mcfg.voxel_size)
        t0 = time.perf_counter()
        p_stack, v_stack, _ = rec._voxel_pack_views(clouds, voxel, 0)
        jax.block_until_ready(v_stack)
        print(f"features: voxel+pack {time.perf_counter() - t0:.3f}s "
              f"stack={tuple(p_stack.shape)}", flush=True)
        fr = jnp.float32(rec.FEAT_RADIUS_SCALE * voxel)
        feat_k, nrm_k = rec.FEAT_K, rec.NORMALS_K

        def timed(label, fn):
            out, best = None, np.inf
            for _ in range(max(args.runs, 2)):
                t0 = time.perf_counter()
                out = fn()
                jax.block_until_ready(out)
                best = min(best, time.perf_counter() - t0)
            print(f"features[{label}]: best {best:.3f}s", flush=True)
            return out

        def chunked(fn):
            # same 8-view batching as _features_views_jit, so per-arm times
            # sum to the full stage's launch structure
            n_views = p_stack.shape[0]
            ch = min(rec.FEATURE_CHUNK, n_views)
            return [fn(s, s + ch) for s in range(0, n_views, ch)]

        def reg_quality(label, nr_s, ft_s):
            # the approx selector's only acceptance gate: registration
            # quality from these features must match the exact arm
            T, gfit, ifit, _ = reg.register_pairs(
                p_stack[1:], v_stack[1:], ft_s[1:],
                p_stack[:-1], v_stack[:-1], ft_s[:-1], nr_s[:-1],
                max_dist=voxel * 1.5,
                icp_max_dist=voxel * float(mcfg.icp_dist_ratio),
                trials=1024, icp_iters=30)
            jax.block_until_ready(T)
            print(f"features[{label}] -> register@1024: "
                  f"gfit={float(np.mean(np.asarray(gfit))):.3f} "
                  f"ifit={float(np.mean(np.asarray(ifit))):.3f}", flush=True)

        full_out = timed("full(knn+normals+fpfh)",
                         lambda: rec._features_views_jit(p_stack, v_stack,
                                                         fr))
        # the full stage runs the PRODUCTION selector (approx on TPU) —
        # the exact-baseline quality line is built from the bq=512 exact
        # idx_d2 after the arms loop, so the gate stays approx-vs-exact
        reg_quality("production(selector=auto)", *full_out)
        nrm_fn = jax.jit(jax.vmap(
            lambda p, v, i, dd: nrmlib.estimate_normals(
                p, v, k=nrm_k, idx_d2=(i, dd))))
        fpfh_fn = jax.jit(jax.vmap(
            lambda p, nr, v, i, dd: reg.fpfh_features(
                p, nr, v, radius=float(fr), k=feat_k, idx_d2=(i, dd))))
        idx_d2 = None
        for arm in ("bq=512", "bq=1024", "bq=2048",
                    "approx:0.99", "approx:0.95"):
            if arm.startswith("bq="):
                kw = dict(block_q=int(arm[3:]))
            else:
                kw = dict(selector=arm)
            knn_fn = jax.jit(jax.vmap(
                lambda p, v: knnlib.knn_brute(p, v, feat_k, **kw)))
            out = timed(f"knn {arm}",
                        lambda: chunked(
                            lambda s, e: knn_fn(p_stack[s:e], v_stack[s:e])))
            cat = (jnp.concatenate([o[0] for o in out]),
                   jnp.concatenate([o[1] for o in out]))
            if arm == "bq=512":
                idx_d2 = cat
            elif arm.startswith("approx"):
                nr_a = jnp.concatenate(chunked(
                    lambda s, e: nrm_fn(p_stack[s:e], v_stack[s:e],
                                        cat[0][s:e], cat[1][s:e])))
                ft_a = jnp.concatenate(chunked(
                    lambda s, e: fpfh_fn(p_stack[s:e], nr_a[s:e],
                                         v_stack[s:e], cat[0][s:e],
                                         cat[1][s:e])))
                reg_quality(arm, nr_a, ft_a)
        idx_all, d2_all = idx_d2
        nr_out = timed("normals(given knn)",
                       lambda: chunked(
                           lambda s, e: nrm_fn(p_stack[s:e], v_stack[s:e],
                                               idx_all[s:e], d2_all[s:e])))
        nr_all = jnp.concatenate(nr_out)
        ft_out = timed("fpfh(given knn+normals)",
                       lambda: chunked(
                           lambda s, e: fpfh_fn(p_stack[s:e], nr_all[s:e],
                                                v_stack[s:e], idx_all[s:e],
                                                d2_all[s:e])))
        # exact-selection baseline for the quality gate (bq=512 exact idx)
        reg_quality("exact-topk", nr_all, jnp.concatenate(ft_out))

        # smaller FPFH neighborhood arm: the reference uses max_nn=100,
        # the production value is 48 (perf departure, fitness-gated) —
        # measure whether 32 holds quality for another ~0.1 s
        for kk in (32,):
            knn_k = jax.jit(jax.vmap(
                lambda p, v: knnlib.knn_brute(p, v, kk,
                                              selector="approx:0.95")))
            fpfh_k = jax.jit(jax.vmap(
                lambda p, nr, v, i, dd: reg.fpfh_features(
                    p, nr, v, radius=float(fr), k=kk, idx_d2=(i, dd))))
            out = timed(f"knn k={kk} approx:0.95",
                        lambda: chunked(
                            lambda s, e: knn_k(p_stack[s:e], v_stack[s:e])))
            i_k = jnp.concatenate([o[0] for o in out])
            d_k = jnp.concatenate([o[1] for o in out])
            nr_k = jnp.concatenate(chunked(
                lambda s, e: nrm_fn(p_stack[s:e], v_stack[s:e],
                                    i_k[s:e], d_k[s:e])))
            ft_k = jnp.concatenate(timed(
                f"fpfh k={kk}",
                lambda: chunked(
                    lambda s, e: fpfh_k(p_stack[s:e], nr_k[s:e],
                                        v_stack[s:e], i_k[s:e],
                                        d_k[s:e]))))
            reg_quality(f"k={kk}-approx:0.95", nr_k, ft_k)

    if not args.register:
        return
    cfg = MergeConfig()
    voxel = float(cfg.voxel_size)
    preps = rec._preprocess_views(clouds, voxel, cfg.sample_before)
    srcs, dsts = preps[1:], preps[:-1]
    stacked = (jnp.stack([x.points for x in srcs]),
               jnp.stack([x.valid for x in srcs]),
               jnp.stack([x.features for x in srcs]),
               jnp.stack([x.points for x in dsts]),
               jnp.stack([x.valid for x in dsts]),
               jnp.stack([x.features for x in dsts]),
               jnp.stack([x.normals for x in dsts]))
    # fb16=None resolves to f32 (the r5 on-chip sweep measured bf16
    # features saving nothing while dropping gfit 0.818 -> 0.608, see
    # _resolve_feat_bf16); the explicit True arm keeps the bf16 path
    # measurable in case a later FPFH change revives it
    for trials, icp_iters, fb16 in ((2048, 30, None), (1024, 30, None),
                                    (768, 30, None), (512, 30, None)):
        t = np.inf
        for _ in range(2):
            t0 = time.perf_counter()
            T, gfit, ifit, _ = reg.register_pairs(
                *stacked, max_dist=voxel * 1.5,
                icp_max_dist=voxel * float(cfg.icp_dist_ratio),
                trials=trials, icp_iters=icp_iters, feat_bf16=fb16)
            jax.block_until_ready(T)
            t = min(t, time.perf_counter() - t0)
        print(f"register trials={trials} icp_iters={icp_iters} "
              f"feat_bf16={fb16} "
              f"steady={t:.3f}s gfit={float(np.mean(np.asarray(gfit))):.3f} "
              f"ifit={float(np.mean(np.asarray(ifit))):.3f}")


if __name__ == "__main__":
    main()
