"""Benchmark: the full 360-degree scan compute of the north star
(BASELINE.json): decode+triangulate of 24 views x 46 frames @1080p, the
360-degree merge, and the Chamfer distance vs the bit-exact NumPy CPU path.

Prints exactly ONE JSON line on stdout:

  {"metric": "full_360_decode_triangulate_merge_wall", "value": <s>,
   "unit": "s",
   "vs_baseline": <numpy_baseline_s / decode_triangulate_s — the speedup on
                   the phase the NumPy reference path actually runs (the
                   reference has no merge twin to time); null whenever the
                   ambient child degraded to a fallback>,
   "decode_triangulate_s", "decode_compile_s", "mpix_per_s",
   "merge_s" (steady), "merge_compile_s", "chamfer_mm",
   "decode_backend"/"merge_backend"/"chamfer_backend" (per-phase provenance;
   top-level "backend" is their join, e.g. "cpu+tpu" for a fallback-filled
   run), "pallas", "views_measured", "error"}

Robustness contract (round-1 verdict item 1):
  - the synthetic 1080p scene + 24 turntable merge clouds are rendered ONCE
    and cached in .bench_cache.npz; subsequent runs skip the ~60 s render
  - every jax phase runs in a CHILD process under a hard timeout; a hung
    TPU backend (axon init flakiness, observed >240 s in round 1) is killed
    and the run retried with a forced-CPU child — a JSON line is ALWAYS
    printed, carrying partial results plus an "error" note when degraded
  - the child persists partial results after each phase so a mid-run hang
    still yields the completed phases
  - all progress goes to stderr; stdout carries only the final JSON line
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

N_VIEWS = 24
CAM = (1920, 1080)
PROJ = (1920, 1080)
MERGE_CAM = (480, 360)      # merge-phase views: camera res of the turntable rig
MERGE_PROJ = (512, 256)
CPU_FALLBACK_VIEWS = 4      # forced-CPU child measures 4 views, extrapolates
ROOT = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(ROOT, ".bench_cache.npz")
CHILD_TIMEOUT_TPU = 2700    # killing a TPU client near its expected runtime
                            # is what wedges the pool tunnel (observed twice
                            # in round 3, once in round 4: a fully-cold
                            # round-4 merge spent >15 min in tunnel-side
                            # compiles and the old 1200 s limit killed it
                            # mid-claim). The real mitigation is the warm
                            # step tools/tpu_session.py now runs first — the
                            # bench child on a warm cache finishes in
                            # minutes, nowhere near this limit; the 45 min
                            # covers a cold run (merge ~15 min + mesh phase)
                            # when bench runs standalone on changed code.
CHILD_TIMEOUT_CPU = 480
# a wedged tunnel recovers on a server-side lease timescale: probe it for a
# bounded window before degrading (round-3 verdict #2 — the record artifact
# fell back to CPU twice because one probe at one instant decided the run)
TPU_RETRY_WINDOW = 1200     # keep probing up to 20 min
TPU_PROBE_GAP = 60          # pause between probes that fail FAST (a hung
                            # probe already burns its 180 s timeout)
LOCK_WAIT = 2400            # queue behind another TPU client (a validation
                            # session mid-chain) rather than racing it; its
                            # warm cache makes our own run fast afterwards.
                            # Waiting holds NO claim, so even an external
                            # kill during the wait cannot wedge anything —
                            # waiting long is strictly safer than degrading
PARENT_DEADLINE = 7200      # absolute last resort: emit an error line and
                            # exit (must cover lock wait + probe window +
                            # TPU child + CPU fallback)


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# scene cache (parent, numpy only — must never touch the jax backend)
# ---------------------------------------------------------------------------

def _merge_scene():
    """An asymmetric rigid object (3 spheres) — a single sphere is rotation-
    invariant about the turntable axis and would make registration ill-posed."""
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    return syn.Scene([
        syn.Sphere(np.array([0.0, 0.0, 420.0]), 70.0),
        syn.Sphere(np.array([55.0, -40.0, 360.0]), 28.0),
        syn.Sphere(np.array([-48.0, 35.0, 370.0]), 22.0),
    ])


def build_cache() -> dict:
    from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
    from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    t0 = time.perf_counter()
    log("cache miss: rendering 1080p flagship scene...")
    rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
    frames, _ = syn.render_scene(rig, syn.sphere_on_background())

    log(f"rendering {N_VIEWS} turntable views at {MERGE_CAM} + NumPy decode...")
    mrig = syn.default_rig(cam_size=MERGE_CAM, proj_size=MERGE_PROJ)
    mcalib = mrig.calibration()
    scene = _merge_scene()
    poses = syn.turntable_poses(N_VIEWS, 360.0 / N_VIEWS,
                                pivot=np.array([0.0, 0.0, 400.0]))
    pts_list, col_list, frames_list = [], [], []
    for R, t in poses:
        vf, _ = syn.render_scene(mrig, scene.transformed(R, t))
        frames_list.append(np.asarray(vf, np.uint8))
        dec = gc.decode_stack_np(vf, n_cols=MERGE_PROJ[0], n_rows=MERGE_PROJ[1],
                                 thresh_mode="manual")
        cloud = tri.triangulate_np(dec.col_map, dec.row_map, dec.mask,
                                   dec.texture, mcalib, row_mode=1)
        p, c = tri.compact_cloud(cloud)
        pts_list.append(p.astype(np.float32))
        col_list.append(c.astype(np.uint8))

    log("NumPy-backend 1080p reference cloud (Chamfer baseline)...")
    dec = gc.decode_stack_np(frames, thresh_mode="manual")
    cloud = tri.triangulate_np(dec.col_map, dec.row_map, dec.mask, dec.texture,
                               rig.calibration(), row_mode=1)
    np_pts, _ = tri.compact_cloud(cloud)

    off = np.cumsum([0] + [len(p) for p in pts_list]).astype(np.int64)
    data = dict(frames=frames,
                np_pts=np_pts.astype(np.float32),
                merge_pts=np.concatenate(pts_list),
                merge_cols=np.concatenate(col_list),
                merge_off=off,
                # raw view stacks [V, F, H, W] u8 for the fused
                # decode->merge phase (device-resident DeviceClouds path)
                merge_frames=np.stack(frames_list))
    np.savez(CACHE, **data)
    log(f"cache built in {time.perf_counter() - t0:.1f}s -> {CACHE}")
    return data


def load_cache() -> dict:
    if os.path.exists(CACHE):
        try:
            with np.load(CACHE) as z:
                data = {k: z[k] for k in z.files}
            if (data["frames"].shape[1:] == (CAM[1], CAM[0])
                    and "merge_frames" in data):
                log(f"cache hit: {CACHE}")
                return data
            log("cache shape/key mismatch; rebuilding")
        except Exception as e:  # corrupt cache: rebuild
            log(f"cache unreadable ({e}); rebuilding")
    return build_cache()


# ---------------------------------------------------------------------------
# reconstruct-pipeline A/B (parent, numpy backend only — never touches jax):
# serial loop vs the pipelined batch executor on a real on-disk dataset, the
# one phase where the lever is host I/O overlap rather than kernel speed
# ---------------------------------------------------------------------------

PIPE_VIEWS = 6
PIPE_CAM = (320, 240)
PIPE_PROJ = (256, 128)
PIPE_COLD_IO_S = 0.04   # injected per-view load latency for the cold-IO arm
                        # (~a 46-frame 1080p stack over NFS/object storage)


def _device_count_or_none():
    """Device count WITHOUT forcing accelerator init: host-only arms must
    stay accelerator-free, so only report when jax is already live."""
    mod = sys.modules.get("jax")
    try:
        return mod.device_count() if mod is not None else None
    except Exception:
        return None


def bench_reconstruct_pipeline(views: int = PIPE_VIEWS, reps: int = 2,
                               inject_io_latency_s: float = 0.0) -> dict:
    """Batch reconstruct from disk, serial (io_workers=1) vs pipelined
    (prefetch + overlapped writeback), byte-comparing the PLYs. Returns the
    overlap accounting (load_s/compute_s/write_s/critical_path_s) of the
    pipelined arm alongside both wall times. ``reps`` runs of each arm, best
    taken, arms interleaved so the OS page cache warms both equally.

    ``inject_io_latency_s`` > 0 adds that much sleep to EVERY stack load in
    BOTH arms — the network-storage / cold-disk scenario this host cannot
    produce natively (its page cache is warm and fadvise is a no-op on the
    overlay fs). The sleep blocks without CPU, exactly like a remote read:
    the serial loop pays it per view, the pipelined executor hides it behind
    compute. On a single-CPU host the un-injected arms measure scheduling
    overhead only (two CPU-bound stages cannot overlap on one core) — the
    injected arm is what exercises the latency-hiding the executor exists
    for; ``host_cpus`` is recorded so readers can tell which regime a line
    came from."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    io_workers, prefetch = 4, 3
    out: dict = {"views": views, "io_workers": io_workers,
                 "prefetch_depth": prefetch, "backend": "numpy",
                 "host_cpus": os.cpu_count(),
                 "io_latency_injected_s": inject_io_latency_s}
    tmp = tempfile.mkdtemp(prefix="slbench_pipe_")
    real_load = imio.load_stack
    if inject_io_latency_s > 0:
        def _latent_load(source, expected=None, io_workers=None):
            res = real_load(source, expected=expected, io_workers=io_workers)
            time.sleep(inject_io_latency_s)
            return res

        imio.load_stack = _latent_load
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def run(workers: int, outdir: str):
            cfg = Config()
            cfg.parallel.backend = "numpy"
            cfg.parallel.io_workers = workers
            cfg.parallel.prefetch_depth = prefetch
            cfg.decode.n_cols, cfg.decode.n_rows = PIPE_PROJ
            cfg.decode.thresh_mode = "manual"
            t0 = time.perf_counter()
            rep = stages.reconstruct(calib_path, root, mode="batch",
                                     output=outdir, cfg=cfg,
                                     log=lambda m: None)
            wall = time.perf_counter() - t0
            assert not rep.failed, f"pipeline bench item failed: {rep.failed}"
            return wall, rep

        serial_dir = os.path.join(tmp, "serial")
        pipe_dir = os.path.join(tmp, "pipe")
        serial_best = pipe_best = np.inf
        rep_pipe = None
        for _ in range(max(1, reps)):
            s, _rep = run(1, serial_dir)
            serial_best = min(serial_best, s)
            p, rep_pipe = run(io_workers, pipe_dir)
            pipe_best = min(pipe_best, p)

        identical = True
        for f in sorted(os.listdir(serial_dir)):
            with open(os.path.join(serial_dir, f), "rb") as fa, \
                    open(os.path.join(pipe_dir, f), "rb") as fb:
                if fa.read() != fb.read():
                    identical = False
                    break
        out["serial_s"] = round(serial_best, 4)
        out["pipelined_s"] = round(pipe_best, 4)
        out["speedup"] = round(serial_best / pipe_best, 3)
        out["outputs_identical"] = identical
        out.update(rep_pipe.overlap or {})
    finally:
        imio.load_stack = real_load
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_pipeline_e2e(views: int = PIPE_VIEWS) -> dict:
    """Fused ``slscan pipeline`` vs the discrete reconstruct -> clean ->
    merge-360 -> mesh command chain on the synthetic turntable rig (numpy
    decode backend — parent-process safe, no accelerator lock).

    Measures wall time for both arms, counts intermediate PLY parses
    (``ply.read_ply`` calls during each arm — the discrete chain's only
    stage handoff; the fused path must show ZERO), and asserts the final
    merged cloud is the same point multiset within float tolerance. A
    second fused run must hit every stage cache and produce byte-identical
    artifacts."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.io import ply as plyio
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy",
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_e2e_")
    parse_counter = {"n": 0}
    real_read = plyio.read_ply

    def counting_read(path):
        parse_counter["n"] += 1
        return real_read(path)

    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg():
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            return c

        steps = ("statistical",)
        # ---- discrete arm: four commands through PLY files ----
        plyio.read_ply = counting_read
        stages.ply.read_ply = counting_read
        t0 = time.perf_counter()
        vdir = os.path.join(tmp, "views")
        rep = stages.reconstruct(calib_path, root, mode="batch", output=vdir,
                                 cfg=cfg(), log=lambda m: None)
        assert not rep.failed, rep.failed
        cdir = os.path.join(tmp, "cleaned")
        os.makedirs(cdir)
        for f in sorted(os.listdir(vdir)):
            stages.clean_cloud(os.path.join(vdir, f), os.path.join(cdir, f),
                               cfg=cfg(), steps=steps, log=lambda m: None)
        merged_d = os.path.join(tmp, "merged_discrete.ply")
        stages.merge_views(cdir, merged_d, cfg=cfg(), log=lambda m: None)
        stl_d = os.path.join(tmp, "model_discrete.stl")
        stages.mesh_cloud(merged_d, stl_d, cfg=cfg(), log=lambda m: None)
        out["discrete_s"] = round(time.perf_counter() - t0, 4)
        out["discrete_ply_parses"] = parse_counter["n"]

        # ---- fused arm (cold cache) ----
        parse_counter["n"] = 0
        fdir = os.path.join(tmp, "fused")
        t0 = time.perf_counter()
        frep = stages.run_pipeline(calib_path, root, fdir, cfg=cfg(),
                                   steps=steps, log=lambda m: None)
        out["fused_s"] = round(time.perf_counter() - t0, 4)
        out["fused_ply_parses"] = parse_counter["n"]
        assert not frep.failed, frep.failed

        # ---- fused rerun (warm cache: zero stage compute) ----
        t0 = time.perf_counter()
        frep2 = stages.run_pipeline(calib_path, root, fdir, cfg=cfg(),
                                    steps=steps, log=lambda m: None)
        out["fused_cached_s"] = round(time.perf_counter() - t0, 4)
        out["cache_hits_second_run"] = frep2.cache["hits"]
        out["cache_misses_second_run"] = frep2.cache["misses"]
        plyio.read_ply = real_read
        stages.ply.read_ply = real_read

        # ---- equivalence: same point multiset within float tolerance ----
        pd = real_read(merged_d)["points"]
        pf = real_read(frep.merged_ply)["points"]
        out["merged_points"] = int(len(pf))
        if pd.shape == pf.shape:
            sd = pd[np.lexsort(pd.T)]
            sf = pf[np.lexsort(pf.T)]
            out["merged_max_abs_diff"] = float(np.abs(sd - sf).max())
            out["equivalent"] = bool(out["merged_max_abs_diff"] <= 1e-4)
        else:
            out["equivalent"] = False
        with open(stl_d, "rb") as fa, open(frep.stl_path, "rb") as fb:
            out["stl_identical"] = fa.read() == fb.read()
        out["speedup_vs_discrete"] = round(
            out["discrete_s"] / out["fused_s"], 3)
    finally:
        plyio.read_ply = real_read
        stages.ply.read_ply = real_read
        shutil.rmtree(tmp, ignore_errors=True)
    return out


BATCHED_VIEWS = 8
BATCHED_COMPUTE = 4


def bench_reconstruct_batched(views: int = BATCHED_VIEWS,
                              compute_batch: int = BATCHED_COMPUTE,
                              reps: int = 2) -> dict:
    """Per-view device dispatch vs the view-batched executor (the ISSUE-4
    compute lane), byte-comparing the PLYs. REQUIRES jax (the jax backend is
    the whole point — the per-view loop's one-launch-per-view schedule vs
    bucket-padded ``forward_views`` launches); callers that must not claim
    an accelerator run it via ``--batched-only`` in a JAX_PLATFORMS=cpu
    subprocess (``_run_batched_child``). Records the launch accounting
    (launches / views per launch / bucket compile proxy / transfer wall)
    plus ``host_cpus`` and ``device_count`` so the regime is legible: on one
    CPU device the win is launch amortization only; with >1 device the view
    axis shards across chips (parallel.shard_views)."""
    import shutil
    import tempfile

    import jax

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "compute_batch": compute_batch,
                 "backend": f"jax-{jax.default_backend()}",
                 "host_cpus": os.cpu_count(),
                 "device_count": jax.device_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_batched_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def run(batch: int, outdir: str):
            cfg = Config()
            cfg.parallel.backend = "jax"
            cfg.parallel.io_workers = 4
            cfg.parallel.compute_batch = batch
            cfg.decode.n_cols, cfg.decode.n_rows = PIPE_PROJ
            cfg.decode.thresh_mode = "manual"
            t0 = time.perf_counter()
            rep = stages.reconstruct(calib_path, root, mode="batch",
                                     output=outdir, cfg=cfg,
                                     log=lambda m: None)
            wall = time.perf_counter() - t0
            assert not rep.failed, f"batched bench item failed: {rep.failed}"
            return wall, rep

        pv_dir = os.path.join(tmp, "perview")
        bt_dir = os.path.join(tmp, "batched")
        pv_best = bt_best = np.inf
        rep_bt = None
        for _ in range(max(1, reps)):
            s, _rep = run(1, pv_dir)           # compute_batch<=1: per-view arm
            pv_best = min(pv_best, s)
            b, rep_bt = run(compute_batch, bt_dir)
            bt_best = min(bt_best, b)

        identical = True
        for f in sorted(os.listdir(pv_dir)):
            with open(os.path.join(pv_dir, f), "rb") as fa, \
                    open(os.path.join(bt_dir, f), "rb") as fb:
                if fa.read() != fb.read():
                    identical = False
                    break
        out["per_view_s"] = round(pv_best, 4)
        out["batched_s"] = round(bt_best, 4)
        out["speedup"] = round(pv_best / bt_best, 3)
        out["outputs_identical"] = identical
        o = rep_bt.overlap or {}
        for k in ("launches", "views_dispatched", "mean_views_per_launch",
                  "min_views_per_launch", "max_views_per_launch",
                  "bucket_first_dispatch_s", "transfer_s", "compute_s",
                  "compute_per_item_s", "transfer_per_item_s",
                  "shard_devices", "critical_path_s"):
            if k in o:
                out[k] = o[k]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _run_batched_child(views: int = BATCHED_VIEWS,
                       compute_batch: int = BATCHED_COMPUTE,
                       timeout: int = 900) -> dict:
    """Run ``bench_reconstruct_batched`` in a JAX_PLATFORMS=cpu subprocess:
    the parent process (bench main / --pipeline-only) must never initialize
    a jax backend itself — on an accelerator box that would open a second
    device claim against the measured child (the concurrent-client wedge).
    The A/B measures launch-schedule overlap, which the CPU backend
    exhibits the same way; on-chip regimes come from the operator running
    ``--batched-only`` directly on the accelerator."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--batched-only",
             f"--views={views}", f"--compute-batch={compute_batch}"],
            capture_output=True, text=True, timeout=timeout, env=env)
        for line in reversed(p.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"error": f"no JSON line (rc={p.returncode}, "
                         f"stderr: {p.stderr.strip()[-200:]})"}
    except subprocess.TimeoutExpired:
        return {"error": f"batched child timed out after {timeout}s"}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def bench_fused_resident(views: int = PIPE_VIEWS,
                         compute_batch: int = 3, reps: int = 2) -> dict:
    """HBM-resident view fastpath A/B: the batched pipeline with the
    discrete drain (decode slots synced to host, host masking, clean
    re-uploaded) vs ``pipeline.fused_clean`` (compact + clean +
    final-compact on device, ONE bulk device_get at the collect boundary,
    cleaned device buffers handed to the registrar's
    ``prep_view_device``). REQUIRES jax; callers that must not claim an
    accelerator run it via ``--fused-only`` in a JAX_PLATFORMS=cpu
    subprocess (``_run_fused_child``).

    Byte-compares merged PLY + STL across arms (the fused path is
    parity-by-construction, not by tolerance) and reads the new
    ``transfer_bytes_*`` counters: the headline number is the CLOUD-path
    bytes per view — (h2d - frame uploads) + d2h, i.e. everything except
    the irreducible stripe upload — where the fused arm must move >=3x
    less. The wall ratio is stamped with host_cpus/device_count: on one
    CPU device the device->host copy is a memcpy, so the byte win shows
    as schedule headroom, not wall; on a real accelerator the saved PCIe
    round-trips are the point."""
    import shutil
    import tempfile

    import jax

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "compute_batch": compute_batch,
                 "backend": f"jax-{jax.default_backend()}",
                 "host_cpus": os.cpu_count(),
                 "device_count": jax.device_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_fused_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg(fused: bool):
            c = Config()
            c.parallel.backend = "jax"
            c.parallel.io_workers = 4
            c.parallel.compute_batch = compute_batch
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.pipeline.fused_clean = fused
            return c

        steps = ("statistical",)

        def run(fused: bool, outdir: str):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path, root,
                                      os.path.join(tmp, outdir),
                                      cfg=cfg(fused), steps=steps,
                                      log=lambda m: None)
            wall = time.perf_counter() - t0
            assert not rep.failed, rep.failed
            return wall, rep

        def cloud_bytes(o: dict) -> int:
            # everything the cloud path moved over the device<->host edge:
            # total h2d minus the irreducible frame uploads, plus d2h
            return (int(o.get("transfer_bytes_h2d", 0))
                    - int(o.get("transfer_bytes_frames", 0))
                    + int(o.get("transfer_bytes_d2h", 0)))

        # interleaved reps, best-of (PR-1 idiom) with FRESH out dirs: the
        # stage cache would otherwise turn rep 2 into a no-compute hit
        fused_s = disc_s = np.inf
        rep_f = rep_d = None
        for r in range(max(1, reps)):
            f, rep_f = run(True, f"fused{r}")
            fused_s = min(fused_s, f)
            d, rep_d = run(False, f"discrete{r}")
            disc_s = min(disc_s, d)
        out["discrete_s"] = round(disc_s, 4)
        out["fused_s"] = round(fused_s, 4)
        out["speedup"] = round(disc_s / fused_s, 3)
        with open(rep_d.merged_ply, "rb") as fa, \
                open(rep_f.merged_ply, "rb") as fb:
            out["merged_identical"] = fa.read() == fb.read()
        with open(rep_d.stl_path, "rb") as fa, open(rep_f.stl_path, "rb") as fb:
            out["stl_identical"] = fa.read() == fb.read()
        of, od = rep_f.overlap or {}, rep_d.overlap or {}
        for arm, o in (("fused", of), ("discrete", od)):
            out[f"{arm}_h2d_bytes"] = o.get("transfer_bytes_h2d", 0)
            out[f"{arm}_d2h_bytes"] = o.get("transfer_bytes_d2h", 0)
            out[f"{arm}_frame_bytes"] = o.get("transfer_bytes_frames", 0)
        cb_f, cb_d = cloud_bytes(of), cloud_bytes(od)
        out["cloud_bytes_per_view_fused"] = cb_f // max(views, 1)
        out["cloud_bytes_per_view_discrete"] = cb_d // max(views, 1)
        out["cloud_bytes_ratio"] = (round(cb_d / cb_f, 3) if cb_f else None)
        out["cloud_bytes_ratio_ok"] = bool(cb_f) and cb_d / cb_f >= 3.0
        if of.get("kernels"):
            out["kernels"] = of["kernels"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _run_fused_child(views: int = PIPE_VIEWS, compute_batch: int = 3,
                     timeout: int = 1200) -> dict:
    """Run ``bench_fused_resident`` in a JAX_PLATFORMS=cpu subprocess —
    same containment as ``_run_batched_child``: the parent must never
    initialize a jax backend (second-device-claim wedge). The byte ratio
    the arm certifies is backend-independent; wall regimes on real chips
    come from the operator running ``--fused-only`` there directly."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--fused-only",
             f"--views={views}", f"--compute-batch={compute_batch}"],
            capture_output=True, text=True, timeout=timeout, env=env)
        for line in reversed(p.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"error": f"no JSON line (rc={p.returncode}, "
                         f"stderr: {p.stderr.strip()[-200:]})"}
    except subprocess.TimeoutExpired:
        return {"error": f"fused child timed out after {timeout}s"}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def bench_packed_ingest(views: int = PIPE_VIEWS,
                        compute_batch: int = 3, reps: int = 2) -> dict:
    """Capture-rate ingest A/B: the batched pipeline with raw frame-stack
    uploads vs ``pipeline.packed_ingest`` (views land as 1-bit bit-plane
    containers, the loader streams the ~8x-smaller planes to the device
    as they arrive, and decode runs from bits on device). REQUIRES jax;
    callers that must not claim an accelerator run it via
    ``--packed-only`` in a JAX_PLATFORMS=cpu subprocess
    (``_run_packed_child``).

    The packed arm reads pre-packed ``frames.slbp`` datasets (the
    capture-side product of ``acquire.pack_frames``); the raw arm reads
    the identical scans as PNG stacks. Byte-compares merged PLY + STL
    across arms — the stored bits ARE the decoder's pat>inv comparisons,
    so parity is by construction, not tolerance — for BOTH the discrete
    and the fused drain, and certifies the headline contract from the
    ``transfer_bytes_frames`` counters: the packed arm must upload >=6x
    fewer frame bytes. Wall is stamped with host_cpus/device_count: on
    one CPU device the upload is a memcpy, so the byte win shows as
    schedule headroom; on a real accelerator the saved PCIe wire time
    while the turntable rotates is the point."""
    import shutil
    import tempfile

    import jax

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "compute_batch": compute_batch,
                 "backend": f"jax-{jax.default_backend()}",
                 "host_cpus": os.cpu_count(),
                 "device_count": jax.device_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_packed_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        packed_root = os.path.join(tmp, "scans_packed")
        os.makedirs(root)
        os.makedirs(packed_root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            name = f"scan_{int(round(i * step)):03d}deg_scan"
            imio.save_stack(os.path.join(root, name), frames)
            imio.save_packed_stack(os.path.join(packed_root, name),
                                   imio.pack_stack(frames))

        def cfg(packed: bool, fused: bool = False):
            c = Config()
            c.parallel.backend = "jax"
            c.parallel.io_workers = 4
            c.parallel.compute_batch = compute_batch
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.pipeline.packed_ingest = packed
            c.pipeline.fused_clean = fused
            return c

        steps = ("statistical",)

        def run(packed: bool, outdir: str, fused: bool = False):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path,
                                      packed_root if packed else root,
                                      os.path.join(tmp, outdir),
                                      cfg=cfg(packed, fused), steps=steps,
                                      log=lambda m: None)
            wall = time.perf_counter() - t0
            assert not rep.failed, rep.failed
            return wall, rep

        # interleaved reps, best-of (PR-1 idiom) with FRESH out dirs: the
        # stage cache would otherwise turn rep 2 into a no-compute hit
        packed_s = raw_s = np.inf
        rep_p = rep_r = None
        for r in range(max(1, reps)):
            p, rep_p = run(True, f"packed{r}")
            packed_s = min(packed_s, p)
            w, rep_r = run(False, f"raw{r}")
            raw_s = min(raw_s, w)
        out["raw_s"] = round(raw_s, 4)
        out["packed_s"] = round(packed_s, 4)
        out["speedup"] = round(raw_s / packed_s, 3)
        with open(rep_r.merged_ply, "rb") as fa, \
                open(rep_p.merged_ply, "rb") as fb:
            out["merged_identical"] = fa.read() == fb.read()
        with open(rep_r.stl_path, "rb") as fa, open(rep_p.stl_path, "rb") as fb:
            out["stl_identical"] = fa.read() == fb.read()
        # the fused drain over packed ingest: same artifacts again
        _, rep_pf = run(True, "packed_fused", fused=True)
        with open(rep_r.merged_ply, "rb") as fa, \
                open(rep_pf.merged_ply, "rb") as fb:
            fused_merged = fa.read() == fb.read()
        with open(rep_r.stl_path, "rb") as fa, \
                open(rep_pf.stl_path, "rb") as fb:
            out["fused_identical"] = fused_merged and fa.read() == fb.read()
        op, orr = rep_p.overlap or {}, rep_r.overlap or {}
        for arm, o in (("packed", op), ("raw", orr)):
            out[f"{arm}_frame_bytes"] = o.get("transfer_bytes_frames", 0)
            out[f"{arm}_frame_bytes_raw"] = o.get("transfer_bytes_frames_raw",
                                                  0)
        ratio = op.get("frame_bytes_ratio")
        out["frame_bytes_ratio"] = ratio
        out["frame_bytes_ratio_ok"] = bool(ratio) and ratio >= 6.0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _run_packed_child(views: int = PIPE_VIEWS, compute_batch: int = 3,
                      timeout: int = 1200) -> dict:
    """Run ``bench_packed_ingest`` in a JAX_PLATFORMS=cpu subprocess —
    same containment as ``_run_fused_child``: the parent must never
    initialize a jax backend (second-device-claim wedge). The >=6x frame
    byte ratio the arm certifies is backend-independent; wall regimes on
    real chips come from the operator running ``--packed-only`` there."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--packed-only",
             f"--views={views}", f"--compute-batch={compute_batch}"],
            capture_output=True, text=True, timeout=timeout, env=env)
        for line in reversed(p.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"error": f"no JSON line (rc={p.returncode}, "
                         f"stderr: {p.stderr.strip()[-200:]})"}
    except subprocess.TimeoutExpired:
        return {"error": f"packed child timed out after {timeout}s"}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def bench_merge_stream(views: int = PIPE_VIEWS) -> dict:
    """Streaming 360 merge A/B (ISSUE 5): the fused pipeline with the
    monolithic barrier merge (``merge.stream=false``) vs the streamed
    register lane (pair (i, i+1) registered the moment both views are
    cleaned, overlapped with reconstruction of later views).

    Byte-compares the merged PLY and the STL across arms (the streamed
    schedule must be the barrier computation re-ordered, bit for bit) and
    records the register-lane overlap accounting: pairs dispatched,
    pairs/launch, ``register_s`` vs ``critical_path_s``. ``host_cpus`` and
    ``device_count`` are stamped so the regime is legible — on one CPU the
    lane and the executor contend for the same core, so the wall win is
    bounded by scheduling, exactly like the PR-1 warm-page-cache arm; the
    lever this A/B certifies is the SCHEDULE (registration off the
    critical path), which pays on any host with a spare core or an
    accelerator doing the reconstruction."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy",
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_stream_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg(stream: bool):
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.merge.stream = stream
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            return c

        steps = ("statistical",)

        def run(stream: bool, outdir: str):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path, root,
                                      os.path.join(tmp, outdir),
                                      cfg=cfg(stream), steps=steps,
                                      log=lambda m: None)
            wall = time.perf_counter() - t0
            assert not rep.failed, rep.failed
            return wall, rep

        # interleaved reps, best-of (the PR-1 bench idiom): both arms run
        # the same canonical register programs, so a single cold pass would
        # charge the whole jit-compile bill to whichever arm went first.
        # Each rep uses a FRESH out dir — the stage cache would otherwise
        # turn rep 2 into a no-compute cache hit.
        stream_s = barrier_s = np.inf
        rep_s = rep_b = None
        for r in range(2):
            s, rep_s = run(True, f"stream{r}")
            stream_s = min(stream_s, s)
            b, rep_b = run(False, f"barrier{r}")
            barrier_s = min(barrier_s, b)
        out["barrier_s"] = round(barrier_s, 4)
        out["streamed_s"] = round(stream_s, 4)
        out["speedup"] = round(barrier_s / stream_s, 3)
        out["merge_mode_barrier"] = rep_b.merge_mode
        out["merge_mode_streamed"] = rep_s.merge_mode
        with open(rep_b.merged_ply, "rb") as fa, \
                open(rep_s.merged_ply, "rb") as fb:
            out["merged_identical"] = fa.read() == fb.read()
        with open(rep_b.stl_path, "rb") as fa, open(rep_s.stl_path, "rb") as fb:
            out["stl_identical"] = fa.read() == fb.read()
        o = rep_s.overlap or {}
        for k in ("pair_launches", "pairs_dispatched",
                  "mean_pairs_per_launch", "register_s", "critical_path_s",
                  "serial_sum_s", "overlap_ratio", "compute_s", "clean_s"):
            if k in o:
                out[k] = o[k]
        try:  # merge arms run jax regardless of the decode backend
            from jax._src import xla_bridge as _xb

            if _xb._backends:
                import jax

                out["device_count"] = jax.device_count()
        except Exception:
            pass
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_pipeline_faults(views: int = PIPE_VIEWS) -> dict:
    """Resilience-layer cost on the fused pipeline (ISSUE 3 acceptance).

    Arm A (``disabled_s``): the fault layer wired through every site but
    with NO plan armed — each ``fire()`` is a single None check. This must
    sit within run-to-run noise of the ``pipeline_e2e`` fused arm (the
    zero-overhead-by-default contract; the --pipeline-only record carries
    the ratio).

    Arm B (``faulted_s``): the seeded chaos plan — one transient
    ``frame.load`` fault (absorbed by a backoff retry) plus one permanent
    ``compute.view`` fault (view quarantined) — must complete DEGRADED with
    exactly one failure, still emitting the merged STL. The delta over arm
    A is the recovery bill (backoff sleeps + the wasted attempt)."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import faults
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy",
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_faults_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        view_names = []
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            name = f"scan_{int(round(i * step)):03d}deg_scan"
            view_names.append(name)
            imio.save_stack(os.path.join(root, name), frames)

        def cfg():
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            return c

        steps = ("statistical",)
        # ---- arm A: fault layer present, disarmed ----
        faults.reset()
        t0 = time.perf_counter()
        rep = stages.run_pipeline(calib_path, root,
                                  os.path.join(tmp, "clean"), cfg=cfg(),
                                  steps=steps, log=lambda m: None)
        out["disabled_s"] = round(time.perf_counter() - t0, 4)
        assert not rep.failed, rep.failed

        # ---- arm B: seeded chaos (1 transient load + 1 permanent view) ----
        transient_view = view_names[0]
        permanent_view = view_names[views // 2]
        spec = (f"frame.load~{transient_view}:transient,"
                f"compute.view~{permanent_view}:permanent")
        faults.configure(spec, seed=0)
        t0 = time.perf_counter()
        rep2 = stages.run_pipeline(calib_path, root,
                                   os.path.join(tmp, "chaos"), cfg=cfg(),
                                   steps=steps, log=lambda m: None)
        out["faulted_s"] = round(time.perf_counter() - t0, 4)
        out["fault_spec"] = spec
        out["failures"] = len(rep2.failed)
        out["retries"] = rep2.retries
        out["degraded"] = rep2.degraded
        out["recovered_ok"] = bool(
            rep2.stl_path and os.path.exists(rep2.stl_path)
            and len(rep2.failed) == 1 and rep2.retries >= 1)
        out["recovery_overhead_s"] = round(
            out["faulted_s"] - out["disabled_s"], 4)
    finally:
        faults.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_pipeline_trace(views: int = PIPE_VIEWS) -> dict:
    """Flight-recorder cost on the fused pipeline (ISSUE 6 acceptance).

    Arm A (``disabled_s``): telemetry wired through every lane/cache/fault
    site but NO tracer active — each instrumentation point is a single
    module-global None check. Must sit within run-to-run noise of the
    ``pipeline_e2e`` fused arm (the <= 1.02x disabled-overhead contract;
    the --pipeline-only record carries the ratio as ``overhead_vs_e2e``).

    Arm B (``traced_s``): the same run with ``observability.trace`` on —
    records the journal size, validates it against the schema, derives the
    per-lane walls from the journal and cross-checks them against the
    run's ``OverlapStats`` (<= 1% drift), and exports the Chrome trace."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        report as replib,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )
    from structured_light_for_3d_model_replication_tpu.utils import telemetry

    out: dict = {"views": views, "backend": "numpy",
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_trace_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg(trace: bool):
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.observability.trace = trace
            return c

        steps = ("statistical",)
        # ---- arm A: telemetry wired, tracer off (the default) ----
        t0 = time.perf_counter()
        rep = stages.run_pipeline(calib_path, root,
                                  os.path.join(tmp, "off"), cfg=cfg(False),
                                  steps=steps, log=lambda m: None)
        out["disabled_s"] = round(time.perf_counter() - t0, 4)
        assert not rep.failed, rep.failed
        assert not os.path.exists(os.path.join(tmp, "off", "trace.jsonl"))

        # ---- arm B: flight recorder on ----
        tdir = os.path.join(tmp, "on")
        t0 = time.perf_counter()
        rep2 = stages.run_pipeline(calib_path, root, tdir, cfg=cfg(True),
                                   steps=steps, log=lambda m: None)
        out["traced_s"] = round(time.perf_counter() - t0, 4)
        out["run_id"] = rep2.run_id
        journal = os.path.join(tdir, "trace.jsonl")
        errors = replib.validate_journal(journal)
        out["journal_valid"] = not errors
        if errors:
            out["journal_errors"] = errors[:5]
        a = replib.analyze_run(tdir)
        out["journal_events"] = a.events
        out["lanes"] = sorted(a.lane_walls)
        # cross-check: journal-derived lane walls vs the executor's own
        # OverlapStats, within 1% (they come from the same calls)
        drift = 0.0
        for lane, wall in a.lane_walls.items():
            stat = rep2.overlap.get(f"{lane}_s") if rep2.overlap else None
            if stat:
                drift = max(drift, abs(wall - stat) / stat)
        out["lane_wall_max_drift"] = round(drift, 5)
        out["lane_walls_match"] = drift <= 0.01
        chrome = telemetry.export_chrome_trace(
            journal, os.path.join(tdir, "trace.json"))
        out["chrome_lanes"] = chrome["lanes"]
        out["metrics_json"] = os.path.exists(
            os.path.join(tdir, "metrics.json"))
        out["trace_overhead_s"] = round(out["traced_s"] - out["disabled_s"],
                                        4)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_pipeline_deadline(views: int = PIPE_VIEWS) -> dict:
    """Deadline-layer cost on the fused pipeline (ISSUE 7 acceptance).

    Arm A (``disabled_s``): bounded waits + watchdog wired through every
    lane but ``deadlines.enabled=false`` — each wait point is a single
    flag/None check and falls through to the bare blocking call. Must sit
    within run-to-run noise of the ``pipeline_e2e`` fused arm (the
    <= 1.02x disabled-overhead contract the faults and telemetry layers
    hold; the --pipeline-only record carries the ratio).

    Arm B (``enabled_s``): the default-on layer — bounded waits with
    production budgets plus the heartbeat watchdog thread. The delta over
    arm A is the full price of never hanging.

    Arm C (``stalled_s``): a seeded ``frame.load`` stall longer than a
    deliberately tight lane deadline — the run must complete DEGRADED
    with exactly one ``DeadlineExceeded`` quarantine and still ship the
    STL; the wall records what a bounded stall costs end to end."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import faults
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy",
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_deadline_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        view_names = []
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            name = f"scan_{int(round(i * step)):03d}deg_scan"
            view_names.append(name)
            imio.save_stack(os.path.join(root, name), frames)

        def cfg(enabled: bool, stall: bool = False):
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.deadlines.enabled = enabled
            if stall:
                # tight lane budget so the injected stall (arm C) trips
                # it instead of waiting out the full block
                c.deadlines.load_s = 0.4
            return c

        steps = ("statistical",)
        # ---- arms A/B interleaved, best-of-2 (the merge_stream
        # discipline): single-shot walls on a 1-CPU box carry several
        # percent of scheduler noise, which is larger than the layer
        # cost being measured ----
        faults.reset()
        disabled_walls, enabled_walls = [], []
        for rep_i in range(2):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path, root,
                                      os.path.join(tmp, f"off{rep_i}"),
                                      cfg=cfg(False), steps=steps,
                                      log=lambda m: None)
            disabled_walls.append(time.perf_counter() - t0)
            assert not rep.failed, rep.failed
            t0 = time.perf_counter()
            rep2 = stages.run_pipeline(calib_path, root,
                                       os.path.join(tmp, f"on{rep_i}"),
                                       cfg=cfg(True), steps=steps,
                                       log=lambda m: None)
            enabled_walls.append(time.perf_counter() - t0)
            assert not rep2.failed, rep2.failed
        out["disabled_s"] = round(min(disabled_walls), 4)
        out["enabled_s"] = round(min(enabled_walls), 4)
        out["disabled_walls"] = [round(w, 4) for w in disabled_walls]
        out["enabled_walls"] = [round(w, 4) for w in enabled_walls]
        # the contract ratio (<= 1.02x): disabled vs the pipeline_e2e
        # configuration. Arm B IS that configuration (deadlines are on
        # by default in pipeline_e2e's Config) on an IDENTICAL dataset,
        # interleaved — cross-arm single-shot walls on a 1-CPU box carry
        # ±5-7% dataset/page-cache bias, larger than the layer cost
        # (the --pipeline-only record stores that cross-arm ratio too,
        # as fused_ref_ratio)
        out["overhead_vs_e2e"] = (
            round(out["disabled_s"] / out["enabled_s"], 3)
            if out["enabled_s"] else None)
        out["enabled_overhead"] = (
            round(out["enabled_s"] / out["disabled_s"], 3)
            if out["disabled_s"] else None)

        # ---- arm C: seeded stall past a tight lane deadline ----
        stall_view = view_names[0]
        spec = f"frame.load~{stall_view}:stall(1.5)"
        faults.configure(spec, seed=0)
        t0 = time.perf_counter()
        rep3 = stages.run_pipeline(calib_path, root,
                                   os.path.join(tmp, "stall"),
                                   cfg=cfg(True, stall=True), steps=steps,
                                   log=lambda m: None)
        out["stalled_s"] = round(time.perf_counter() - t0, 4)
        out["stall_spec"] = spec
        out["stall_failures"] = [
            {"stage": r.stage, "view": r.view, "error": r.error_type}
            for r in rep3.failures]
        out["stall_recovered_ok"] = bool(
            rep3.stl_path and os.path.exists(rep3.stl_path)
            and rep3.degraded and len(rep3.failures) == 1
            and rep3.failures[0].error_type == "DeadlineExceeded")
    finally:
        faults.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_multiproc(views: int = PIPE_VIEWS) -> dict:
    """Multiprocess-coordinator cost on the fused pipeline (ISSUE 9).

    Arms A/B (``single_s`` vs ``hooked_s``, interleaved best-of-2): the
    coordinator's only recurring hot-path cost on a single-process run is
    the heartbeat-hook check in ``OverlapStats.add`` (the can't-drift
    lease-renewal seam; the workers=0 dispatch head is one branch per
    run). Arm A runs with the hook disarmed — the stock fused pipeline —
    and arm B with a no-op hook armed, upper-bounding what lease renewal
    costs every stage transition. ``coordinator_overhead`` = B/A is the
    <= 1.02x contract number.

    Arm C (``coordinated_s``): the real thing — the same scan sharded
    across 2 worker processes (spawn + lease protocol + ledger + assembly
    over the warmed cache), with ``parity_ply`` / ``parity_stl`` byte
    comparisons against arm A's artifacts. Its wall is a regime record,
    not a contract: on a 1-CPU box process spawn + the assembly pass
    dominate; with idle cores the workers overlap and it approaches (or
    beats) the single-process wall."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        profiling as prof,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy", "workers": 2,
                 "host_cpus": os.cpu_count()}
    tmp = tempfile.mkdtemp(prefix="slbench_mproc_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg(workers: int = 0) -> Config:
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.coordinator.workers = workers
            return c

        steps = ("statistical",)
        single_walls, hooked_walls = [], []
        for rep_i in range(2):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path, root,
                                      os.path.join(tmp, f"sp{rep_i}"),
                                      cfg=cfg(), steps=steps,
                                      log=lambda m: None)
            single_walls.append(time.perf_counter() - t0)
            assert not rep.failed, rep.failed
            prev = prof.set_heartbeat_hook(lambda stage: None)
            try:
                t0 = time.perf_counter()
                rep2 = stages.run_pipeline(calib_path, root,
                                           os.path.join(tmp, f"hk{rep_i}"),
                                           cfg=cfg(), steps=steps,
                                           log=lambda m: None)
                hooked_walls.append(time.perf_counter() - t0)
            finally:
                prof.set_heartbeat_hook(prev)
            assert not rep2.failed, rep2.failed
        out["single_s"] = round(min(single_walls), 4)
        out["hooked_s"] = round(min(hooked_walls), 4)
        out["single_walls"] = [round(w, 4) for w in single_walls]
        out["hooked_walls"] = [round(w, 4) for w in hooked_walls]
        out["coordinator_overhead"] = (
            round(out["hooked_s"] / out["single_s"], 3)
            if out["single_s"] else None)

        # ---- arm C: 2-worker coordinated run + byte parity ----
        out_mp = os.path.join(tmp, "mp")
        t0 = time.perf_counter()
        rep3 = stages.run_pipeline(calib_path, root, out_mp,
                                   cfg=cfg(workers=2), steps=steps,
                                   log=lambda m: None)
        out["coordinated_s"] = round(time.perf_counter() - t0, 4)
        out["coordinated_vs_single"] = (
            round(out["coordinated_s"] / out["single_s"], 3)
            if out["single_s"] else None)
        info = rep3.coordinator or {}
        out["items"] = info.get("items_total")
        out["steals"] = info.get("steals")
        for name, key in (("merged.ply", "parity_ply"),
                          ("model.stl", "parity_stl")):
            with open(os.path.join(tmp, "sp0", name), "rb") as fa, \
                    open(os.path.join(out_mp, name), "rb") as fb:
                out[key] = fa.read() == fb.read()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_assembly(view_counts=(4, 8), reps: int = 2) -> dict:
    """Incremental-assembly tail certification (ISSUE 17).

    For each view count: one single-process run (the parity anchor), then
    ``reps`` interleaved pairs of 2-worker pods — ``merge.incremental``
    ON (the fold lane) and OFF (the barrier arm) — over the same rendered
    dataset, best-of-``reps`` walls. Certifies:

      - PLY+STL byte parity: incremental ≡ barrier ≡ single-process at
        every view count
      - the fold lane folded the whole chain before the last item
        settled (``folded_views == views``)
      - ``tail_sublinear``: the incremental assembly tail
        (last-item-settled -> artifacts-on-disk) grows SLOWER than the
        view count across the measured points — with every view and pair
        pre-folded, the tail is the postprocess only (Poisson grid work,
        bounded by mesh depth, not by the chain length), while the
        barrier tail re-walks the whole chain
      - ``disabled_overhead``: the pod wall with the knob ON over the
        knob-OFF wall, <= 1.02x — the fold lane consumes completed
        payloads off the critical path, so enabling it must ride free
        (and the knob-off path is the pre-lane coordinator plus a None
        check, so this one ratio bounds both directions). Measured at
        the LARGEST view count: small regimes are dominated by fixed
        pod spin-up (worker fork + per-process warmup), which swings
        tens of percent rep-to-rep on a busy 1-CPU box; per-point
        ratios are recorded alongside. Each view count runs one
        untimed warmup pod first and alternates the arm order per rep
        so neither arm systematically gets the warmer slot.
    """
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    cam, proj = (160, 120), (128, 64)
    out: dict = {"view_counts": list(view_counts), "reps": reps,
                 "backend": "numpy", "workers": 2,
                 "cam": list(cam), "proj": list(proj),
                 "host_cpus": os.cpu_count()}

    def cfg(incremental: bool) -> Config:
        c = Config()
        c.parallel.backend = "numpy"
        c.decode.n_cols, c.decode.n_rows = proj
        c.decode.thresh_mode = "manual"
        c.merge.voxel_size = 4.0
        c.merge.ransac_trials = 512
        c.merge.icp_iters = 10
        c.merge.incremental = incremental
        c.mesh.depth = 5
        c.mesh.density_trim_quantile = 0.0
        c.coordinator.workers = 2
        return c

    steps = ("statistical",)
    points: list[dict] = []
    tmp = tempfile.mkdtemp(prefix="slbench_asm_")
    try:
        rig = syn.default_rig(cam_size=cam, proj_size=proj)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        for views in view_counts:
            root = os.path.join(tmp, f"scans{views}")
            os.makedirs(root)
            step = 360.0 / views
            pivot = np.array([0.0, 0.0, 420.0])
            for i, (R, t) in enumerate(syn.turntable_poses(views, step,
                                                           pivot)):
                frames, _ = syn.render_scene(
                    rig, syn.Scene([obj.transformed(R, t), background]))
                imio.save_stack(
                    os.path.join(root,
                                 f"scan_{int(round(i * step)):03d}deg_scan"),
                    frames)

            sp = os.path.join(tmp, f"sp{views}")
            c0 = cfg(False)
            c0.coordinator.workers = 0
            rep_sp = stages.run_pipeline(calib_path, root, sp, cfg=c0,
                                         steps=steps, log=lambda m: None)
            assert not rep_sp.failed, rep_sp.failed

            # untimed warmup pod: pays the one-off page-cache / fork /
            # process-warmup freshness so neither timed arm eats it
            stages.run_pipeline(calib_path, root,
                                os.path.join(tmp, f"warm{views}"),
                                cfg=cfg(False), steps=steps,
                                log=lambda m: None)

            pt: dict = {"views": views}
            walls: dict[str, list] = {"incremental": [], "disabled": []}
            tails: dict[str, list] = {"incremental": [], "disabled": []}
            folded = 0
            for r in range(reps):
                arms = (("incremental", True), ("disabled", False))
                for arm, inc in (arms if r % 2 == 0 else arms[::-1]):
                    od = os.path.join(tmp, f"{arm}{views}_{r}")
                    t0 = time.perf_counter()
                    rep = stages.run_pipeline(calib_path, root, od,
                                              cfg=cfg(inc), steps=steps,
                                              log=lambda m: None)
                    walls[arm].append(time.perf_counter() - t0)
                    assert not rep.degraded
                    info = (rep.coordinator or {}).get("assembly") or {}
                    if info.get("tail_s") is not None:
                        tails[arm].append(info["tail_s"])
                    if inc:
                        folded = (rep.coordinator or {}).get(
                            "assembly_lane", {}).get("folded_views", 0)
                    for name, key in (("merged.ply", "parity_ply"),
                                      ("model.stl", "parity_stl")):
                        with open(os.path.join(sp, name), "rb") as fa, \
                                open(os.path.join(od, name), "rb") as fb:
                            ok = fa.read() == fb.read()
                        pt[key] = pt.get(key, True) and ok
            pt["incremental_s"] = round(min(walls["incremental"]), 4)
            pt["disabled_s"] = round(min(walls["disabled"]), 4)
            pt["incremental_walls"] = [round(w, 4)
                                       for w in walls["incremental"]]
            pt["disabled_walls"] = [round(w, 4) for w in walls["disabled"]]
            pt["tail_incremental_s"] = round(min(tails["incremental"]), 4)
            pt["tail_disabled_s"] = round(min(tails["disabled"]), 4)
            pt["folded_views"] = folded
            pt["enabled_over_disabled"] = (
                round(pt["incremental_s"] / pt["disabled_s"], 3)
                if pt["disabled_s"] else None)
            points.append(pt)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    out["points"] = points
    # the contract ratio, from the largest regime (see docstring); the
    # per-point ratios stay visible in points[]
    out["disabled_overhead"] = points[-1]["enabled_over_disabled"]
    out["disabled_overhead_views"] = points[-1]["views"]
    if len(points) >= 2:
        lo, hi = points[0], points[-1]
        out["view_ratio"] = round(hi["views"] / lo["views"], 3)
        out["tail_ratio"] = (
            round(hi["tail_incremental_s"] / lo["tail_incremental_s"], 3)
            if lo["tail_incremental_s"] else None)
        out["tail_sublinear"] = (out["tail_ratio"] is not None
                                 and out["tail_ratio"] < out["view_ratio"])
        # the postprocess-only share: the incremental tail vs the barrier
        # tail at the biggest regime (the barrier tail re-walks the chain)
        out["tail_vs_disabled"] = (
            round(hi["tail_incremental_s"] / hi["tail_disabled_s"], 3)
            if hi["tail_disabled_s"] else None)
    out["parity_ply"] = all(p.get("parity_ply") for p in points)
    out["parity_stl"] = all(p.get("parity_stl") for p in points)
    return out


def bench_fabric(views: int = PIPE_VIEWS) -> dict:
    """Pod-fabric cost + locality payoff (ISSUE 15).

    Arms A/B (``single_s`` vs ``fabric_hooked_s``, interleaved best-of-2):
    arm A is the stock single-process pipeline; arm B runs the SAME scan
    with its stage cache swapped for a ``FabricCache`` write-through to a
    live loopback ``BlobServer`` — every payload put is hashed and pushed
    over a real TCP socket, upper-bounding what the blobstore costs a
    worker that never even needs L2. ``fabric_overhead`` = B/A is the
    <= 1.02x contract number.

    Arm C (``fabric_s``): the real thing — the scan sharded across 2
    worker processes over real TCP (``coordinator.listen`` + shared
    secret, private per-worker L1 roots), with ``parity_ply``/
    ``parity_stl`` byte comparisons against arm A, the blobstore's wire
    counters (``wire_bytes``, ``blob_hit_ratio`` = served fetches over
    fetch attempts), and the COLD locality split (``cold_locality_hits``/
    ``_misses`` — racy by nature: a cold 2-worker pod only hits when one
    worker happened to compute or fetch both of a pair's endpoint views).

    Arm D (``resume_s``): the deterministic locality probe — the same
    scan with every L1 (both workers' private roots AND the coordinator's
    assembly cache) pre-seeded from arm A's view payloads, i.e. a pod
    rejoining after a coordinator restart. Workers re-announce their full
    inventory on hello, so EVERY pair grant prefers a holder:
    ``locality_hit_rate`` must be 1.0, pair registration reads straight
    from L1, and ``recompute_avoided_s`` = C - D is what the warm fabric
    bought. Walls are regime records on a 1-CPU box; the contract numbers
    are the overhead ratio, the parity bits, and the locality rate."""
    import shutil
    import tempfile

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import images as imio
    from structured_light_for_3d_model_replication_tpu.io import matfile
    from structured_light_for_3d_model_replication_tpu.pipeline import stages
    from structured_light_for_3d_model_replication_tpu.pipeline.blobstore import (
        BlobClient,
        BlobServer,
        FabricCache,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    out: dict = {"views": views, "backend": "numpy", "workers": 2,
                 "host_cpus": os.cpu_count(),
                 "device_count": _device_count_or_none()}
    tmp = tempfile.mkdtemp(prefix="slbench_fab_")
    try:
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        root = os.path.join(tmp, "scans")
        os.makedirs(root)
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for i, (R, t) in enumerate(syn.turntable_poses(views, step, pivot)):
            frames, _ = syn.render_scene(
                rig, syn.Scene([obj.transformed(R, t), background]))
            imio.save_stack(
                os.path.join(root, f"scan_{int(round(i * step)):03d}deg_scan"),
                frames)

        def cfg(workers: int = 0, listen: str = "", secret: str = "") -> Config:
            c = Config()
            c.parallel.backend = "numpy"
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.coordinator.workers = workers
            c.coordinator.listen = listen
            c.coordinator.secret = secret
            return c

        steps = ("statistical",)
        single_walls, hooked_walls = [], []
        for rep_i in range(2):
            t0 = time.perf_counter()
            rep = stages.run_pipeline(calib_path, root,
                                      os.path.join(tmp, f"sp{rep_i}"),
                                      cfg=cfg(), steps=steps,
                                      log=lambda m: None)
            single_walls.append(time.perf_counter() - t0)
            assert not rep.failed, rep.failed
            # arm B: same single-process run, cache swapped for a
            # write-through FabricCache against a live loopback blobstore
            # (fresh L2 root per rep so every push pays full freight)
            out_hk = os.path.join(tmp, f"hk{rep_i}")
            srv = BlobServer(os.path.join(tmp, f"l2_{rep_i}"), port=0)
            cli = BlobClient(srv.endpoint, connect_timeout_s=10.0)
            try:
                fcache = FabricCache(os.path.join(out_hk, ".slscan-cache"),
                                     cli, log=lambda m: None)
                t0 = time.perf_counter()
                rep2 = stages.run_pipeline(calib_path, root, out_hk,
                                           cfg=cfg(), steps=steps,
                                           log=lambda m: None, cache=fcache)
                hooked_walls.append(time.perf_counter() - t0)
                out["l2_bytes_pushed"] = srv.counters()["bytes_pushed"]
            finally:
                cli.close()
                srv.close()
            assert not rep2.failed, rep2.failed
        out["single_s"] = round(min(single_walls), 4)
        out["fabric_hooked_s"] = round(min(hooked_walls), 4)
        out["single_walls"] = [round(w, 4) for w in single_walls]
        out["hooked_walls"] = [round(w, 4) for w in hooked_walls]
        out["fabric_overhead"] = (
            round(out["fabric_hooked_s"] / out["single_s"], 3)
            if out["single_s"] else None)

        # ---- arm C: cold 2-worker pod over real TCP + byte parity ------
        out_fab = os.path.join(tmp, "fab")
        t0 = time.perf_counter()
        rep3 = stages.run_pipeline(
            calib_path, root, out_fab,
            cfg=cfg(workers=2, listen="127.0.0.1:0", secret="bench-pod"),
            steps=steps, log=lambda m: None)
        out["fabric_s"] = round(time.perf_counter() - t0, 4)
        out["fabric_vs_single"] = (
            round(out["fabric_s"] / out["single_s"], 3)
            if out["single_s"] else None)
        info = rep3.coordinator or {}
        fb = info.get("fabric") or {}
        out["wire_bytes"] = fb.get("bytes_fetched", 0) \
            + fb.get("bytes_pushed", 0)
        out["bytes_fetched"] = fb.get("bytes_fetched", 0)
        out["bytes_pushed"] = fb.get("bytes_pushed", 0)
        out["bytes_deduped"] = fb.get("bytes_deduped", 0)
        attempts = fb.get("fetches", 0) + fb.get("misses", 0)
        out["blob_hit_ratio"] = (
            round(fb.get("fetches", 0) / attempts, 3) if attempts else None)
        out["cold_locality_hits"] = info.get("locality_hits")
        out["cold_locality_misses"] = info.get("locality_misses")
        for name, key in (("merged.ply", "parity_ply"),
                          ("model.stl", "parity_stl")):
            with open(os.path.join(tmp, "sp0", name), "rb") as fa, \
                    open(os.path.join(out_fab, name), "rb") as fb2:
                out[key] = fa.read() == fb2.read()

        # ---- arm D: warm-resume pod — the deterministic locality probe -
        out_res = os.path.join(tmp, "res")
        src_cache = os.path.join(tmp, "sp0", ".slscan-cache")
        seeds = [os.path.join(out_res, ".slscan-cache"),
                 os.path.join(out_res, ".slscan-cache.w0"),
                 os.path.join(out_res, ".slscan-cache.w1")]
        for d in seeds:
            os.makedirs(d, exist_ok=True)
            for f in os.listdir(src_cache):
                if f.startswith("view-") and f.endswith(".npz"):
                    shutil.copy(os.path.join(src_cache, f),
                                os.path.join(d, f))
        t0 = time.perf_counter()
        rep4 = stages.run_pipeline(
            calib_path, root, out_res,
            cfg=cfg(workers=2, listen="127.0.0.1:0", secret="bench-pod"),
            steps=steps, log=lambda m: None)
        out["resume_s"] = round(time.perf_counter() - t0, 4)
        out["recompute_avoided_s"] = (
            round(out["fabric_s"] - out["resume_s"], 4)
            if out.get("fabric_s") else None)
        rinfo = rep4.coordinator or {}
        hits = rinfo.get("locality_hits", 0) or 0
        misses = rinfo.get("locality_misses", 0) or 0
        out["locality_hits"] = hits
        out["locality_misses"] = misses
        out["locality_hit_rate"] = (
            round(hits / (hits + misses), 3) if hits + misses else None)
        with open(os.path.join(tmp, "sp0", "model.stl"), "rb") as fa, \
                open(os.path.join(out_res, "model.stl"), "rb") as fb2:
            out["parity_stl_resume"] = fa.read() == fb2.read()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_serve(tenants: int = 3, scans_per_tenant: int = 2,
                views: int = 2, compute_batch: int = 4,
                rate_hz: float = 5.0, seed: int = 0) -> dict:
    """Multi-tenant serving A/B (ISSUE 12): the ``sl3d serve`` gateway
    under ``tools/loadgen.py`` — N tenants, seeded Poisson arrivals,
    distinct synthetic scans per tenant.

    Arm ``single``: ``serving.max_active_scans=1`` — one tenant's scan in
    the engine at a time, so every batched launch can only fill with that
    scan's own views (fill <= views/scan). Arm ``cross``: all scans
    admitted together, so grant sets interleave tenants and views from
    DIFFERENT scans fill the same bucket launch. The contract number is
    ``launch_fill_gain`` = cross-arm mean views/launch over the single-
    arm's — strictly > 1 whenever tenants overlap (the acceptance bar).

    Headline serving numbers come from the load generator exactly as an
    operator would read them: scans/hour and p50/p99 request latency
    (submit -> terminal, queue wait included). Byte parity: the first
    scan of every tenant in the cross arm is compared byte-for-byte
    against a solo ``run_pipeline`` of the same input (the PR-8
    construction carried to serving).

    Durability A/B (ISSUE 13): the cross arm (``serving.durable`` on —
    every accepted submit fsyncs a request record before its 202) is
    re-run with durability off; ``durability_overhead_x`` is the on/off
    wall ratio and the contract is <= 1.02x. The off arm runs last, so
    compile-cache warmth can only inflate the ratio (conservative).

    HA A/B (ISSUE 14): the same load once more with
    ``serving.ha_enabled`` on — a single gateway that elects itself
    (epoch 1) and renews its lease throughout, with every ledger append
    epoch-stamped + fence-checked. ``ha_overhead_x`` is the HA/cross
    wall ratio; contract <= 1.02x — leases and fencing must cost the
    single-gateway hot path nothing measurable. Election happens BEFORE
    the load starts (the arm waits for role=leader), so the measured
    wall is pure serving; compile warmth is saturated by the earlier
    arms (all arms run identical scans), so arm order doesn't move this
    ratio.

    REQUIRES jax (the batched lane needs a device scanner) — runs under
    ``--serve-only`` (CPU-pinned unless the caller chose a platform) or
    the ``_run_serve_child`` subprocess from ``--pipeline-only``. The
    single arm runs FIRST, so the cross arm inherits its warm compile
    cache — walls are regime records; the fill ratio is schedule
    accounting and compile-neutral."""
    import importlib.util
    import shutil
    import tempfile
    import threading

    from structured_light_for_3d_model_replication_tpu.config import Config
    from structured_light_for_3d_model_replication_tpu.io import (
        images as imio,
        matfile,
    )
    from structured_light_for_3d_model_replication_tpu.pipeline import (
        serving, stages,
    )
    from structured_light_for_3d_model_replication_tpu.utils import (
        synthetic as syn,
    )

    spec = importlib.util.spec_from_file_location(
        "sl3d_loadgen", os.path.join(ROOT, "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    out: dict = {"tenants": tenants, "scans_per_tenant": scans_per_tenant,
                 "views_per_scan": views, "compute_batch": compute_batch,
                 "rate_hz": rate_hz, "seed": seed,
                 "host_cpus": os.cpu_count()}
    import jax

    out["device_count"] = jax.device_count()
    out["backend"] = jax.devices()[0].platform
    tmp = tempfile.mkdtemp(prefix="slbench_serve_")
    try:
        # ---- distinct synthetic scans: tenants x scans_per_tenant. A
        # per-scan satellite offset makes EVERY view's bytes distinct
        # (at 0 deg the turntable transform is the identity, so a pivot
        # shift alone leaves view 0 byte-identical across scans — and
        # identical bytes dedup to one shared cache entry, silently
        # shrinking the engine work both arms are supposed to measure)
        rig = syn.default_rig(cam_size=PIPE_CAM, proj_size=PIPE_PROJ)
        scene = syn.sphere_on_background()
        obj, background = scene.objects
        calib_path = os.path.join(tmp, "calib.mat")
        matfile.save_calibration(calib_path, rig.calibration())
        manifest: dict = {"tenants": {}}
        step = 360.0 / views
        pivot = np.array([0.0, 0.0, 420.0])
        for ti in range(tenants):
            name = f"t{ti:02d}"
            entries = []
            for si in range(scans_per_tenant):
                tgt = os.path.join(tmp, f"in_{name}_{si}")
                shift = 9.0 * (ti * scans_per_tenant + si)
                satellite = syn.Sphere(
                    np.array([48.0 + shift, -92.0, 430.0]), 16.0)
                for vi, (R, t) in enumerate(
                        syn.turntable_poses(views, step, pivot)):
                    frames, _ = syn.render_scene(
                        rig, syn.Scene([obj.transformed(R, t),
                                        satellite.transformed(R, t),
                                        background]))
                    imio.save_stack(
                        os.path.join(
                            tgt,
                            f"scan_{int(round(vi * step)):03d}deg_scan"),
                        frames)
                entries.append({"target": tgt, "calib": calib_path})
            manifest["tenants"][name] = entries

        def mkcfg(max_active: int, durable: bool = True,
                  ha: bool = False) -> Config:
            c = Config()
            c.decode.n_cols, c.decode.n_rows = PIPE_PROJ
            c.decode.thresh_mode = "manual"
            c.merge.voxel_size = 4.0
            c.merge.ransac_trials = 512
            c.merge.icp_iters = 10
            c.mesh.depth = 5
            c.mesh.density_trim_quantile = 0.0
            c.parallel.compute_batch = compute_batch
            c.serving.clean_steps = "statistical"
            c.serving.host = "127.0.0.1"
            c.serving.port = 0
            c.serving.max_active_scans = max_active
            c.serving.durable = durable
            if ha:
                c.serving.ha_enabled = True
                c.serving.ha_lease_s = 5.0
            return c

        def run_arm(tag: str, max_active: int, durable: bool = True,
                    ha: bool = False) -> tuple[dict, dict]:
            root = os.path.join(tmp, f"svc_{tag}")
            httpd, svc = serving.start_gateway(
                root, cfg=mkcfg(max_active, durable=durable, ha=ha),
                log=lambda m: None)
            th = threading.Thread(target=httpd.serve_forever,
                                  kwargs={"poll_interval": 0.1},
                                  daemon=True)
            th.start()
            base = (f"http://{httpd.server_address[0]}:"
                    f"{httpd.server_address[1]}")
            try:
                if ha:
                    # Self-election takes ~one poll tick; wait it out so
                    # the measured wall is pure serving, not election.
                    t_end = time.time() + 60.0
                    while svc.role != "leader" and time.time() < t_end:
                        time.sleep(0.05)
                    assert svc.role == "leader", svc.role
                res = lg.run_load(base, manifest, scans_per_tenant,
                                  rate_hz, seed=seed, log=log)
            finally:
                httpd.shutdown()
                httpd.server_close()
                svc.close()
            outs = {r["scan_id"]: svc.adm.jobs[r["scan_id"]]
                    for r in res.get("results", []) if "scan_id" in r}
            keep = {k: v for k, v in res.items() if k != "results"}
            keep["all_completed"] = all(
                r["state"] in ("done", "degraded")
                for r in res.get("results", []))
            return keep, outs

        out["single"], _ = run_arm("single", max_active=1)
        out["cross"], jobs = run_arm("cross", max_active=tenants)

        # ---- durability overhead A/B (ISSUE 13): the cross arm IS the
        # durable-on sample (serving.durable defaults on); re-run the
        # same load with request records disabled. The off arm runs LAST,
        # inheriting the warmest compile cache — any bias makes the
        # on/off ratio LARGER, so the <= 1.02x contract is conservative.
        out["durable_off"], _ = run_arm("doff", max_active=tenants,
                                        durable=False)
        wall_on = out["cross"].get("wall_s")
        wall_off = out["durable_off"].get("wall_s")
        if wall_on and wall_off:
            out["durability_overhead_x"] = round(wall_on / wall_off, 3)
            out["durability_overhead_ok"] = (
                out["durability_overhead_x"] <= 1.02)
        else:
            out["durability_overhead_x"] = None
            out["durability_overhead_ok"] = None

        # ---- HA overhead A/B (ISSUE 14): same load, durable on, with
        # leader election + per-append epoch fencing active. Compile
        # warmth is saturated after the first arm, so running this last
        # doesn't move the ratio vs the cross (non-HA) sample.
        out["ha"], _ = run_arm("ha", max_active=tenants, ha=True)
        wall_ha = out["ha"].get("wall_s")
        if wall_on and wall_ha:
            out["ha_overhead_x"] = round(wall_ha / wall_on, 3)
            out["ha_overhead_ok"] = out["ha_overhead_x"] <= 1.02
        else:
            out["ha_overhead_x"] = None
            out["ha_overhead_ok"] = None

        fill_c = out["cross"].get("mean_views_per_launch")
        fill_s = out["single"].get("mean_views_per_launch")
        out["launch_fill_gain"] = (round(fill_c / fill_s, 3)
                                   if fill_c and fill_s else None)
        out["cross_fill_above_single"] = bool(
            fill_c and fill_s and fill_c > fill_s)

        # ---- byte parity: first scan of every tenant vs solo ----
        parity_ply = parity_stl = True
        solo_cfg = mkcfg(1)
        checked = 0
        for name, entries in manifest["tenants"].items():
            tgt = entries[0]["target"]
            job = next((j for j in jobs.values()
                        if j.target == os.path.abspath(tgt)), None)
            if job is None or job.state not in ("done", "degraded"):
                parity_ply = parity_stl = False
                continue
            solo_out = os.path.join(tmp, f"solo_{name}")
            rep = stages.run_pipeline(calib_path, tgt, solo_out,
                                      cfg=mkcfg(1),
                                      steps=("statistical",),
                                      log=lambda m: None)
            assert not rep.failed, rep.failed
            for fname, flag in (("merged.ply", "ply"),
                                ("model.stl", "stl")):
                with open(os.path.join(solo_out, fname), "rb") as fa, \
                        open(os.path.join(job.out_dir, fname), "rb") as fb:
                    same = fa.read() == fb.read()
                if flag == "ply":
                    parity_ply = parity_ply and same
                else:
                    parity_stl = parity_stl and same
            checked += 1
        out["parity_ply"] = parity_ply
        out["parity_stl"] = parity_stl
        out["parity_scans_checked"] = checked
        _ = solo_cfg
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _run_serve_child(tenants: int = 3, scans_per_tenant: int = 1,
                     views: int = 2, compute_batch: int = 4,
                     timeout: int = 900) -> dict:
    """Run ``bench_serve`` in a JAX_PLATFORMS=cpu subprocess — same
    containment as ``_run_batched_child``: the numpy parent never
    initializes a backend; the serving gateway + engine live and die in
    the child."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--serve-only",
             f"--tenants={tenants}", f"--scans={scans_per_tenant}",
             f"--views={views}", f"--compute-batch={compute_batch}",
             "--no-record"],
            capture_output=True, text=True, timeout=timeout, env=env)
        for line in reversed(p.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"error": f"no JSON line (rc={p.returncode}, "
                         f"stderr: {p.stderr.strip()[-200:]})"}
    except subprocess.TimeoutExpired:
        return {"error": f"serve child timed out after {timeout}s"}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


# ---------------------------------------------------------------------------
# child: all jax work, per-phase persisted results
# ---------------------------------------------------------------------------

def child_main(out_path: str, views: int, force_cpu: bool) -> None:
    res: dict = {"backend": None, "pallas": None}

    def save() -> None:
        with open(out_path + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(out_path + ".tmp", out_path)

    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    try:
        dev = jax.devices()[0]
    except Exception as e:
        log(f"child: backend init failed ({type(e).__name__}); forcing CPU")
        jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        res["backend_error"] = str(e)[:200]
        views = min(views, CPU_FALLBACK_VIEWS)  # CPU can't afford 24 full views
    res["backend"] = dev.platform
    res["host_cpus"] = os.cpu_count()
    res["device_count"] = jax.device_count()
    log(f"child: backend={dev.platform} device={dev} "
        f"({res['device_count']} device(s), {res['host_cpus']} host cpus)")
    # persistent executable cache: a re-run (or the driver's run after a local
    # warmup) skips XLA compilation, so the compile-vs-steady split below
    # reflects what a warmed deployment sees
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(ROOT, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # older jax without the knob
        log(f"child: compilation cache unavailable ({e})")

    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
        chamfer_distance, merge_360,
    )
    from structured_light_for_3d_model_replication_tpu.models.scanner import SLScanner
    from structured_light_for_3d_model_replication_tpu.ops import (
        pallas_kernels as pk,
    )
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    res["pallas"] = pk.pallas_mode()
    log(f"child: pallas={res['pallas']}")
    save()

    backend = res["backend"]
    cache = load_cache()

    # ---- phase A: decode+triangulate, `views` views @1080p, ONE launch ----
    # plane_eval="quadratic": the gather-free light-plane path — the stored
    # table gather is the one op that is not HBM-bandwidth-shaped on TPU
    # (~11x slower end to end, see BENCH notes); accuracy vs the NumPy table
    # path is pinned by the Chamfer phase below
    rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
    scanner = SLScanner(rig.calibration(), CAM, PROJ, row_mode=1,
                        plane_eval="quadratic")
    base = jax.block_until_ready(jnp.asarray(cache["frames"]))
    t0 = time.perf_counter()
    # distinct per-view content via device-side rolls (one 95 MB upload, not 24)
    views_dev = jax.block_until_ready(
        jnp.stack([jnp.roll(base, i * 7, axis=2) for i in range(views)]))
    log(f"child: view stack {views_dev.shape} resident "
        f"({time.perf_counter() - t0:.1f}s)")

    def run():
        out = scanner.forward_views(views_dev, thresh_mode="manual",
                                    shadow_val=40.0, contrast_val=10.0)
        jax.block_until_ready(out.points)
        return out

    t0 = time.perf_counter()
    out = run()  # compile + warm
    decode_first = time.perf_counter() - t0
    log(f"child: phase A compile+warm {decode_first:.1f}s")
    n_rep = 3 if backend != "cpu" else 1
    best = np.inf
    for _ in range(n_rep):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    scale = N_VIEWS / views
    res["decode_triangulate_s"] = round(best * scale, 4)
    res["decode_compile_s"] = round(max(decode_first - best, 0.0), 2)
    res["decode_backend"] = backend
    try:  # which decode lowering actually ran (fused Mosaic vs jnp path)
        auto_fused = scanner._can_fuse(views_dev)     # dispatch policy
        fuse_capable = scanner._fuse_capable(views_dev)
        res["decode_path"] = "fused-pallas" if auto_fused else "jnp"
    except Exception:
        auto_fused = fuse_capable = False
        res["decode_path"] = "unknown"
    res["views_measured"] = views
    res["mpix_per_s"] = round(N_VIEWS * CAM[0] * CAM[1] / (best * scale) / 1e6, 1)
    n_valid0 = int(np.asarray(out.valid[0]).sum())
    assert n_valid0 > 0, "bench scene produced no valid points"
    log(f"child: phase A best {best:.3f}s for {views} views "
        f"(={res['mpix_per_s']} Mpix/s, {n_valid0} valid pts in view 0)")
    save()

    # A/B the lowering auto-dispatch did NOT choose (r5 decision: the fused
    # scan kernel is the accelerator default — both r5 in-session A/Bs
    # measured it faster (0.1154 vs 0.1489, 0.1091 vs 0.1486) after the r4
    # normalization + tile fixes; SLSCAN_PALLAS=0 flips back to jnp. Keep
    # recording both so the decision stays evidence-backed)
    if fuse_capable and backend != "cpu":
        alt_fused = not auto_fused

        def run_alt():
            out = scanner.forward_views(views_dev, thresh_mode="manual",
                                        shadow_val=40.0, contrast_val=10.0,
                                        use_fused=alt_fused)
            jax.block_until_ready(out.points)

        t0 = time.perf_counter()
        run_alt()  # compile + warm
        alt_first = time.perf_counter() - t0
        alt_best = np.inf
        for _ in range(n_rep):
            t0 = time.perf_counter()
            run_alt()
            alt_best = min(alt_best, time.perf_counter() - t0)
        res["decode_alt_path"] = "fused-pallas" if alt_fused else "jnp"
        res["decode_alt_s"] = round(alt_best * scale, 4)
        res["decode_alt_compile_s"] = round(max(alt_first - alt_best, 0.0), 2)
        log(f"child: phase A alt ({res['decode_alt_path']}) best "
            f"{alt_best:.3f}s (auto={res['decode_path']} "
            f"{res['decode_triangulate_s']}s scaled; alt "
            f"{res['decode_alt_s']}s scaled)")
        if res["decode_alt_s"] < 0.9 * res["decode_triangulate_s"]:
            log(f"child: NOTE — the {res['decode_alt_path']} lowering beat "
                f"the default by >10%; revisit the dispatch default")
        save()

    # ---- bit-exact export verification (BASELINE contract, verdict r3 #3):
    # decode view 0 on-device (integer maps are bit-exact by construction),
    # then triangulate(bitexact=True) — host-NumPy float math at the export
    # boundary (TPU f32 divide/rsqrt are not IEEE-identical, measured r4) —
    # compare the compacted cloud with the NumPy reference bit for bit, and
    # record what it costs.
    from structured_light_for_3d_model_replication_tpu.ops import (
        graycode as gc_mod,
        triangulate as tri_mod,
    )

    try:
        t0 = time.perf_counter()
        dec0 = gc_mod.decode_stack(base, thresh_mode="manual")
        bx_cloud = tri_mod.triangulate(dec0.col_map, dec0.row_map, dec0.mask,
                                       dec0.texture, rig.calibration(),
                                       row_mode=1, bitexact=True)
        bx_pts, _ = tri_mod.compact_cloud(bx_cloud)
        res["bitexact_cost_s"] = round(time.perf_counter() - t0, 3)
        res["bitexact"] = bool(bx_pts.shape == cache["np_pts"].shape
                               and (bx_pts == cache["np_pts"]).all())
        res["bitexact_backend"] = backend
        log(f"child: bitexact export path: match={res['bitexact']} "
            f"({res['bitexact_cost_s']}s for 1 view incl. decode)")
    except Exception as e:
        # a verification-phase failure must never cost the headline merge
        # measurement (this child IS the phase-B record): note it and go on
        res["bitexact"] = None
        res["bitexact_error"] = f"{type(e).__name__}: {e}"[:200]
        log(f"child: bitexact verification FAILED ({res['bitexact_error']})")
    save()

    # ---- phase C before B (cheap): Chamfer vs the NumPy reference cloud ----
    jx_pts = np.asarray(out.points[0])[np.asarray(out.valid[0])]
    np_pts = cache["np_pts"]
    # stride the subsample under the Pallas nn1 gate (131072 points) so the
    # Chamfer runs on the Mosaic kernel instead of the grid path
    stride = max(1, -(-max(len(jx_pts), len(np_pts)) // 131072))
    res["chamfer_mm"] = round(
        float(chamfer_distance(jx_pts[::stride], np_pts[::stride])), 6)
    res["chamfer_backend"] = backend
    log(f"child: Chamfer jax-vs-numpy = {res['chamfer_mm']} mm "
        f"({len(jx_pts)} vs {len(np_pts)} pts)")
    save()

    # ---- phase B: 360-degree merge of the turntable clouds ----
    off = cache["merge_off"]
    clouds = [(cache["merge_pts"][off[i]:off[i + 1]],
               cache["merge_cols"][off[i]:off[i + 1]])
              for i in range(len(off) - 1)]
    fits: list[float] = []

    def merge_log(msg):
        if "fit" in msg:
            try:
                fits.append(float(msg.split("ICP fit ")[1].split(" ")[0]))
            except Exception:
                pass
        log(f"child: {msg}")

    from structured_light_for_3d_model_replication_tpu.config import MergeConfig

    if backend == "cpu":
        # degraded mode is what users hit on a wedged box. With the ICP
        # convergence stop actually firing (r5 fix, 2e-3 relative floor)
        # the full 2048/icp30 register runs 34 s on this host — and,
        # counter-intuitively, FASTER than 1024 trials on XLA:CPU (43 s;
        # chunking artifact) — so the fallback needs no trimmed knobs
        # and its outputs match the 2048-trial config exactly.
        mcfg = MergeConfig(ransac_trials=2048)
    else:
        # trial count is quality-flat on this scene across 512..4096 on
        # BOTH backends (r5 sweeps: on-chip gfit 0.82-0.89 / ifit
        # 0.93-0.94 unordered in trials; CPU ifit 0.916-0.942 likewise;
        # r3 first established 1024 == 4096) — the bench scene's
        # 15-degree pairs are feature-rich. 512 is the fastest MEASURED
        # on-chip arm (register 0.284 s vs 0.359 @1024) and divides the
        # scorer's chunk sizes; the library default stays 4096 for
        # robustness headroom (ADVICE r3). Per-line fitness is the gate.
        mcfg = MergeConfig(ransac_trials=512)
    res["merge_ransac_trials"] = mcfg.ransac_trials
    res["merge_icp_iters"] = mcfg.icp_iters

    # fused decode->merge on accelerators: the merge views' raw frame
    # stacks live on device (residency excluded from timing, exactly like
    # phase A's views_dev) and the timed merge INCLUDES their on-device
    # decode + compaction — the clouds never cross the tunnel. This is
    # the real scan flow (frames in -> merged cloud out); the host-cloud
    # path remains for CPU/fallback and is what the tools' A/Bs use.
    fused_merge = backend != "cpu" and "merge_frames" in cache
    res["merge_includes_view_decode"] = fused_merge
    if fused_merge:
        from structured_light_for_3d_model_replication_tpu.models import (
            reconstruction as rec_mod,
        )

        mrig = syn.default_rig(cam_size=MERGE_CAM, proj_size=MERGE_PROJ)
        mscanner = SLScanner(mrig.calibration(), MERGE_CAM, MERGE_PROJ,
                             row_mode=1, plane_eval="quadratic")
        mframes_dev = jax.block_until_ready(
            jnp.asarray(cache["merge_frames"]))

        def run_merge(tmd, lg=merge_log):
            t_dec = time.perf_counter()
            out = mscanner.forward_views(mframes_dev, thresh_mode="manual",
                                         shadow_val=40.0, contrast_val=10.0)
            dcv = rec_mod.compact_views_device(out.points, out.valid,
                                               out.colors)
            # the compaction's survivor-count sync bounds the decode wall,
            # so the stage dict keeps summing to merge_s
            tmd["view_decode_s"] = round(time.perf_counter() - t_dec, 3)
            return merge_360(dcv, cfg=mcfg, log=lg, timings=tmd)
    else:
        def run_merge(tmd, lg=merge_log):
            return merge_360(clouds, cfg=mcfg, log=lg, timings=tmd)

    tm: dict = {}
    t0 = time.perf_counter()
    merged_p, _, _ = run_merge(tm)
    merge_first = time.perf_counter() - t0
    res["merge_s"] = round(merge_first, 3)
    res["merge_backend"] = backend
    res["merge_points"] = int(len(merged_p))
    res["merge_stage_s"] = tm
    res["merge_icp_fit_mean"] = round(float(np.mean(fits)), 3) if fits else None
    log(f"child: phase B merge first run {merge_first:.2f}s (stages {tm})")
    save()
    # second run reuses every executable (in-process + the persistent cache):
    # the steady/compile split (verdict round 2 #8 — TPU merges were
    # compile-dominated). Skipped when the first run already blew the budget
    # (the split is then visible in the persistent-cache-warmed next run).
    if merge_first < 120 and backend != "cpu":
        tm2: dict = {}
        t0 = time.perf_counter()
        run_merge(tm2, lg=lambda m: None)
        merge_steady = time.perf_counter() - t0
        res["merge_steady_s"] = round(merge_steady, 3)
        res["merge_compile_s"] = round(max(merge_first - merge_steady, 0.0), 3)
        res["merge_s"] = round(merge_steady, 3)
        res["merge_stage_s"] = tm2          # stages of the steady run
        res["merge_stage_first_s"] = tm     # compile-inclusive first run
        log(f"child: phase B merge steady {merge_steady:.2f}s "
            f"(+{res['merge_compile_s']}s compile on first run), "
            f"{len(merged_p)} pts, mean ICP fitness {res['merge_icp_fit_mean']}")
    save()

    # ---- phase D: mesh the merged cloud (A19/A20, the scan-to-print
    # tail). Informational — not part of the headline value (BASELINE
    # scopes the target to decode+triangulate+merge) — and accelerator-
    # only: the CPU fallback child has a 480 s budget that Poisson at
    # merged-cloud scale would blow.
    if backend != "cpu":
        from structured_light_for_3d_model_replication_tpu.models.meshing import (
            reconstruct_mesh,
        )

        try:
            t0 = time.perf_counter()
            verts, faces = reconstruct_mesh(merged_p, log=lambda m: None)
            verts, faces = np.asarray(verts), np.asarray(faces)
            mesh_first = time.perf_counter() - t0
            # bank the compile-inclusive result BEFORE the steady rerun so
            # a watchdog kill mid-rerun cannot lose the measurement
            res["mesh_s"] = round(mesh_first, 3)
            res["mesh_backend"] = backend
            res["mesh_vertices"] = int(len(verts))
            res["mesh_faces"] = int(len(faces))
            save()
            if mesh_first < 120:  # same budget guard as the merge phase
                t0 = time.perf_counter()
                verts, faces = reconstruct_mesh(merged_p, log=lambda m: None)
                verts, faces = np.asarray(verts), np.asarray(faces)
                mesh_steady = time.perf_counter() - t0
                res["mesh_s"] = round(mesh_steady, 3)
                res["mesh_compile_s"] = round(
                    max(mesh_first - mesh_steady, 0.0), 2)
            log(f"child: phase D mesh {res['mesh_s']}s "
                f"(first {mesh_first:.2f}s) {len(verts)} verts "
                f"{len(faces)} faces")
        except Exception as e:
            # meshing must never cost the captured merge record
            res["mesh_error"] = f"{type(e).__name__}: {e}"[:200]
            log(f"child: phase D mesh FAILED ({res['mesh_error']})")
    save()


# ---------------------------------------------------------------------------
# parent: orchestrate with hard timeouts; always print one JSON line
# ---------------------------------------------------------------------------

def _run_child(args: list[str], timeout: int) -> dict | None:
    out_path = os.path.join(ROOT, ".bench_child.json")
    if os.path.exists(out_path):
        os.remove(out_path)
    cmd = [sys.executable, os.path.abspath(__file__), "--child", out_path] + args
    log(f"spawning child: {' '.join(cmd[2:])} (timeout {timeout}s)")
    try:
        # child stdout -> our stderr: the parent's stdout must carry ONLY the
        # final JSON line, and backend init noise would corrupt it
        proc = subprocess.run(cmd, stdout=sys.stderr, timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        log("child TIMED OUT (killed)")
        rc = -9
    if os.path.exists(out_path):
        with open(out_path) as f:
            res = json.load(f)
        res["child_rc"] = rc
        return res
    return None


_PHASE_KEYS = {
    "decode_triangulate_s": ("decode_triangulate_s", "decode_compile_s",
                             "decode_backend", "decode_path",
                             "decode_alt_path", "decode_alt_s",
                             "decode_alt_compile_s", "mpix_per_s",
                             "views_measured", "pallas"),
    "chamfer_mm": ("chamfer_mm", "chamfer_backend"),
    "bitexact": ("bitexact", "bitexact_cost_s", "bitexact_backend"),
    "mesh_s": ("mesh_s", "mesh_compile_s", "mesh_backend", "mesh_vertices",
               "mesh_faces"),
    "merge_s": ("merge_s", "merge_steady_s", "merge_compile_s",
                "merge_backend", "merge_points", "merge_icp_fit_mean",
                "merge_stage_s", "merge_stage_first_s",
                "merge_ransac_trials"),
}


def _fill_missing_phases(dst: dict, src: dict) -> None:
    """Copy whole phases (value + its provenance tags together) from the
    fallback child for phases the ambient child never completed. Copying
    per-key let a dead TPU child's backend labels survive over CPU numbers
    in round 2."""
    for marker, keys in _PHASE_KEYS.items():
        if dst.get(marker) is None and src.get(marker) is not None:
            for k in keys:
                if src.get(k) is not None:
                    dst[k] = src[k]


def _wait_for_accelerator(preflight, window: float, gap: float):
    """Probe the ambient backend until it answers or ``window`` expires.

    A wedged pool tunnel recovers on a server-side lease timescale, so ONE
    probe at one instant must not decide the record (round-3: two rounds of
    CPU-fallback artifacts for exactly that reason). A hung probe already
    burns its own 180 s timeout — only fast failures sleep ``gap`` between
    tries, and three consecutive IDENTICAL failures end the wait early (a
    missing/broken plugin fails deterministically every time; only the
    wedge signature, 'hung', earns the full window).
    Returns (status, detail, attempts, waited_s).
    """
    t0 = time.monotonic()
    attempts = 0
    same_fails = 0
    last_fail = None
    while True:
        attempts += 1
        status, detail = preflight()
        waited = time.monotonic() - t0
        log(f"ambient backend preflight #{attempts}: {status} ({detail}) "
            f"[{waited:.0f}s into the {window:.0f}s retry window]")
        if status == "ok" and detail == "cpu":
            # wedge VARIANT, not topology: on this rig the ambient backend
            # is the accelerator whenever the tunnel is healthy — a cpu
            # verdict means the plugin failed FAST this instant (observed
            # alternating with the hung signature, r4). Keep probing; an
            # accepted cpu verdict would produce a clean-looking
            # backend:cpu record with no error label.
            if waited >= window:
                return "cpu-fallback", detail, attempts, waited
            time.sleep(gap)
            continue
        if status == "ok" or waited >= window:
            return status, detail, attempts, waited
        if status == "failed":
            same_fails = same_fails + 1 if detail == last_fail else 1
            last_fail = detail
            if same_fails >= 3:
                log("deterministic init failure (3 identical) — degrading now")
                return status, detail, attempts, waited
            time.sleep(gap)
        else:
            same_fails = 0
            last_fail = None


def emit(final: dict) -> None:
    # every bench line carries a run_id (ISSUE-6: joinable against flight-
    # recorder journals and reports without log archaeology)
    if "run_id" not in final:
        from structured_light_for_3d_model_replication_tpu.utils import (
            telemetry,
        )

        final["run_id"] = telemetry.new_run_id()
    # every emitted line carries the execution regime (ISSUE-4 satellite):
    # host_cpus always; device_count only when this process ALREADY holds an
    # initialized jax backend — the numpy-backend parent must never claim an
    # accelerator just to count it (its children record their own counts),
    # so a null here reads "no backend in this process", not "one device"
    final.setdefault("host_cpus", os.cpu_count())
    if "device_count" not in final:
        final["device_count"] = None
        try:
            from jax._src import xla_bridge as _xb

            if _xb._backends:
                import jax

                final["device_count"] = jax.device_count()
        except Exception:
            pass
    print(json.dumps(final), flush=True)


def main() -> None:
    final = {
        "metric": "full_360_decode_triangulate_merge_wall",
        "value": None, "unit": "s", "vs_baseline": None, "error": None,
    }

    def alarm_handler(signum, frame):
        final["error"] = (final.get("error") or "") + "; parent deadline hit"
        emit(final)
        os._exit(0)

    signal.signal(signal.SIGALRM, alarm_handler)
    signal.alarm(PARENT_DEADLINE)
    t_alarm = time.monotonic()

    try:
        cache = load_cache()

        # NumPy baseline: 1 view @1080p decode+triangulate, scaled to 24
        from structured_light_for_3d_model_replication_tpu.ops import (
            graycode as gc,
            triangulate as tri,
        )
        from structured_light_for_3d_model_replication_tpu.utils import (
            synthetic as syn,
        )

        rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
        calib = rig.calibration()
        frames = cache["frames"]
        t0 = time.perf_counter()
        dec = gc.decode_stack_np(frames, thresh_mode="manual")
        tri.triangulate_np(dec.col_map, dec.row_map, dec.mask, dec.texture,
                           calib, row_mode=1)
        np_s = (time.perf_counter() - t0) * N_VIEWS
        log(f"NumPy baseline: {np_s / N_VIEWS:.2f}s/view -> {np_s:.1f}s for "
            f"{N_VIEWS} views")
        final["numpy_baseline_s"] = round(np_s, 2)

        # batch-reconstruct pipeline A/B (host-only; a failure here must
        # never cost the headline measurement)
        try:
            log("reconstruct pipeline A/B (serial vs pipelined, numpy "
                "backend, from disk)...")
            final["reconstruct_pipeline"] = bench_reconstruct_pipeline()
            final["reconstruct_pipeline_cold_io"] = bench_reconstruct_pipeline(
                inject_io_latency_s=PIPE_COLD_IO_S)
            for tag in ("reconstruct_pipeline", "reconstruct_pipeline_cold_io"):
                rp = final[tag]
                log(f"{tag}: serial {rp['serial_s']}s vs pipelined "
                    f"{rp['pipelined_s']}s (x{rp['speedup']}, identical="
                    f"{rp['outputs_identical']}, critical "
                    f"{rp['critical_path_s']}s vs serial-sum "
                    f"{rp['serial_sum_s']}s)")
        except Exception as e:
            final["reconstruct_pipeline"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"pipeline A/B FAILED ({final['reconstruct_pipeline']['error']})")

        # view-batched reconstruct A/B (cpu-pinned subprocess: jax without
        # touching the parent's accelerator claim)
        log("reconstruct batched A/B (per-view vs view-batched, jax cpu "
            "subprocess)...")
        final["reconstruct_batched"] = rb = _run_batched_child()
        final["host_cpus"] = os.cpu_count()
        if "error" in rb:
            log(f"batched A/B FAILED ({rb['error']})")
        else:
            log(f"reconstruct_batched: per-view {rb['per_view_s']}s vs "
                f"batched {rb['batched_s']}s (x{rb['speedup']}, identical="
                f"{rb['outputs_identical']}, {rb['views_dispatched']} views "
                f"in {rb['launches']} launches, "
                f"{rb['device_count']} device(s))")

        # fused scan-to-print vs the discrete command chain (host-only)
        try:
            log("pipeline e2e A/B (fused vs discrete chain, numpy backend)...")
            final["pipeline_e2e"] = bench_pipeline_e2e()
            pe = final["pipeline_e2e"]
            log(f"pipeline_e2e: discrete {pe['discrete_s']}s "
                f"({pe['discrete_ply_parses']} PLY parses) vs fused "
                f"{pe['fused_s']}s ({pe['fused_ply_parses']} parses), "
                f"cached rerun {pe['fused_cached_s']}s, equivalent="
                f"{pe['equivalent']}, stl_identical={pe['stl_identical']}")
        except Exception as e:
            final["pipeline_e2e"] = {"error": f"{type(e).__name__}: {e}"[:200]}
            log(f"pipeline e2e A/B FAILED ({final['pipeline_e2e']['error']})")

        # resilience overhead + seeded-chaos recovery (host-only)
        try:
            log("pipeline faults arm (disabled overhead + seeded chaos)...")
            final["pipeline_faults"] = bench_pipeline_faults()
            pf = final["pipeline_faults"]
            log(f"pipeline_faults: disabled {pf['disabled_s']}s vs faulted "
                f"{pf['faulted_s']}s ({pf['retries']} retries, "
                f"{pf['failures']} quarantined, recovered_ok="
                f"{pf['recovered_ok']})")
        except Exception as e:
            final["pipeline_faults"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"pipeline faults arm FAILED "
                f"({final['pipeline_faults']['error']})")

        # flight-recorder overhead + journal validity (host-only)
        try:
            log("pipeline trace arm (disabled overhead + traced run)...")
            final["pipeline_trace"] = bench_pipeline_trace()
            pt = final["pipeline_trace"]
            log(f"pipeline_trace: disabled {pt['disabled_s']}s vs traced "
                f"{pt['traced_s']}s ({pt['journal_events']} events, "
                f"journal_valid={pt['journal_valid']}, lane walls match="
                f"{pt['lane_walls_match']})")
        except Exception as e:
            final["pipeline_trace"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"pipeline trace arm FAILED "
                f"({final['pipeline_trace']['error']})")

        # deadline-layer overhead + bounded-stall recovery (host-only)
        try:
            log("pipeline deadline arm (disabled/enabled overhead + "
                "seeded stall)...")
            final["pipeline_deadline"] = bench_pipeline_deadline()
            pd = final["pipeline_deadline"]
            log(f"pipeline_deadline: disabled {pd['disabled_s']}s vs "
                f"enabled {pd['enabled_s']}s (x{pd['enabled_overhead']}); "
                f"stalled run {pd.get('stalled_s')}s, recovered_ok="
                f"{pd.get('stall_recovered_ok')}")
        except Exception as e:
            final["pipeline_deadline"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"pipeline deadline arm FAILED "
                f"({final['pipeline_deadline']['error']})")

        # multiprocess-coordinator overhead + 2-worker parity (host-only)
        try:
            log("multiproc arm (heartbeat-hook overhead + 2-worker "
                "coordinated run)...")
            final["multiproc"] = bench_multiproc()
            mp = final["multiproc"]
            log(f"multiproc: single {mp['single_s']}s vs hooked "
                f"{mp['hooked_s']}s (x{mp['coordinator_overhead']}); "
                f"coordinated {mp.get('coordinated_s')}s "
                f"(x{mp.get('coordinated_vs_single')}, "
                f"{mp.get('steals')} steal(s)), parity "
                f"ply={mp.get('parity_ply')} stl={mp.get('parity_stl')}")
        except Exception as e:
            final["multiproc"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"multiproc arm FAILED ({final['multiproc']['error']})")

        # pod-fabric overhead + 2-worker real-TCP parity + locality
        # (host-only: numpy backend, loopback sockets)
        try:
            log("fabric arm (blobstore write-through overhead + 2-worker "
                "TCP pod + warm-resume locality probe)...")
            final["fabric"] = bench_fabric()
            fa = final["fabric"]
            log(f"fabric: single {fa['single_s']}s vs hooked "
                f"{fa['fabric_hooked_s']}s (x{fa['fabric_overhead']}); "
                f"pod {fa.get('fabric_s')}s, parity "
                f"ply={fa.get('parity_ply')} stl={fa.get('parity_stl')}, "
                f"{fa.get('wire_bytes')} B over wire, blob hit ratio "
                f"{fa.get('blob_hit_ratio')}; resume {fa.get('resume_s')}s, "
                f"locality hit rate {fa.get('locality_hit_rate')}")
        except Exception as e:
            final["fabric"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
            log(f"fabric arm FAILED ({final['fabric']['error']})")

        # one TPU client at a time, repo-wide: if a validation session (or
        # any other tool) holds the claim lock, QUEUE behind it — racing it
        # is the concurrent-client wedge. Waiting is also the best outcome:
        # the session leaves a warm compile cache and a healthy tunnel.
        from structured_light_for_3d_model_replication_tpu.utils import (
            tpulock,
        )

        tpu_lock = None
        if not tpulock.held_by_parent():
            t0 = time.monotonic()
            tpu_lock = tpulock.acquire_tpu_lock(ROOT, timeout=LOCK_WAIT)
            if tpu_lock is None:
                log(f"TPU claim lock still held after {LOCK_WAIT}s "
                    f"— degrading rather than opening a concurrent claim")
                final["error"] = "tpu claim lock held elsewhere"
            elif time.monotonic() - t0 > 1.0:
                log(f"waited {time.monotonic() - t0:.0f}s for the TPU "
                    f"claim lock")

        # preflight: a wedged accelerator tunnel hangs inside PJRT client
        # init; detect it in 3 min instead of burning the full child budget
        from structured_light_for_3d_model_replication_tpu.utils.preflight import (
            accelerator_preflight,
        )

        if final.get("error") == "tpu claim lock held elsewhere":
            status, detail, attempts, waited = "busy", "lock held", 0, 0.0
        else:
            status, detail, attempts, waited = _wait_for_accelerator(
                accelerator_preflight, TPU_RETRY_WINDOW, TPU_PROBE_GAP)
        final["tpu_probe_attempts"] = attempts
        final["tpu_probe_wait_s"] = round(waited, 1)
        if status == "ok":
            res = _run_child([f"--views={N_VIEWS}"], CHILD_TIMEOUT_TPU)
        else:
            if status == "hung":
                final["error"] = (f"ambient backend hung at init "
                                  f"({attempts} probes over "
                                  f"{final['tpu_probe_wait_s']:.0f}s)")
            elif status == "cpu-fallback":
                final["error"] = ("accelerator plugin failing fast — jax "
                                  "fell back to cpu (wedge variant; "
                                  f"{attempts} probes)")
            elif status != "busy":  # busy already set its own error above
                final["error"] = f"ambient backend init failed: {detail}"
            import glob as _glob

            recs = sorted(_glob.glob(os.path.join(ROOT, "BENCH_SELF_r*.json")))
            if recs:
                # a wedged tunnel is an infrastructure failure, not a code
                # one — point the record at the newest clean first-party TPU
                # line so the degraded run can't be read as the build's perf
                final["self_recorded_tpu_run"] = os.path.basename(recs[-1])
            res = None
        complete = res is not None and res.get("merge_s") is not None
        if not complete:
            note = "ambient-backend child incomplete"
            if res is not None:
                note += f" (got phases: {sorted(res.keys())})"
            log(note + "; retrying with forced CPU")
            prior = final.get("error")
            final["error"] = ((prior + "; ") if prior else "") + \
                "ambient child failed; cpu fallback"
            # fit the fallback inside what's left of the parent deadline
            # (60 s reserve for result assembly); skip it when nothing
            # useful could finish
            remaining = PARENT_DEADLINE - (time.monotonic() - t_alarm) - 60
            if remaining >= 120:
                res_cpu = _run_child(
                    [f"--views={CPU_FALLBACK_VIEWS}", "--force-cpu"],
                    int(min(CHILD_TIMEOUT_CPU, remaining)))
                if res is None:
                    res = res_cpu
                elif res_cpu is not None:
                    _fill_missing_phases(res, res_cpu)
            else:
                log("no parent budget left for a CPU fallback child")

        if res is None:
            # last resort: report the NumPy number itself so a real number
            # exists on the record
            final["value"] = round(np_s, 2)
            final["vs_baseline"] = None
            final["backend"] = "numpy"
            final["error"] = (final.get("error") or "") + "; all jax children failed"
            emit(final)
            return

        for k in ("decode_triangulate_s", "decode_compile_s", "decode_backend",
                  "decode_path", "decode_alt_path", "decode_alt_s",
                  "decode_alt_compile_s", "mpix_per_s", "merge_s", "merge_steady_s",
                  "merge_compile_s", "merge_backend", "chamfer_mm",
                  "chamfer_backend", "bitexact", "bitexact_cost_s",
                  "bitexact_backend", "pallas", "views_measured",
                  "merge_points", "merge_icp_fit_mean", "merge_stage_s",
                  "merge_stage_first_s", "merge_ransac_trials",
                  "mesh_s", "mesh_compile_s", "mesh_backend",
                  "mesh_vertices", "mesh_faces", "mesh_error",
                  "backend_error"):
            if k in res and res[k] is not None:
                final[k] = res[k]
        # top-level backend is derived from the per-phase provenance tags —
        # a fallback-filled run reads "tpu+cpu", never a bare "tpu" over CPU
        # numbers (round-2 verdict weak #5)
        backends = sorted({res.get(k) for k in
                           ("decode_backend", "merge_backend",
                            "chamfer_backend", "bitexact_backend")} - {None})
        final["backend"] = "+".join(backends) if backends else None
        dt = res.get("decode_triangulate_s")
        mg = res.get("merge_s")
        if dt is not None:
            final["value"] = round(dt + (mg or 0.0), 3)
            # the NumPy reference implements only decode+triangulate (the
            # reference has no merge twin to time): the ratio is phase-like
            # -for-like, and suppressed entirely on a degraded run
            if final["error"] is None:
                final["vs_baseline"] = round(np_s / dt, 2)
            if mg is None:
                final["error"] = (final.get("error") or "") + "; merge phase missing"
        else:
            final["value"] = round(np_s, 2)
            final["error"] = (final.get("error") or "") + "; decode phase missing"
    except Exception as e:
        final["error"] = (final.get("error") or "") + f"; {type(e).__name__}: {e}"
    signal.alarm(0)
    emit(final)


if __name__ == "__main__":
    if "--pipeline-only" in sys.argv[1:]:
        # standalone record of the batch-reconstruct pipeline A/B: one JSON
        # line on stdout, no jax, no accelerator lock — safe anywhere.
        # Two arms: warm page cache (overlap visible only with >1 CPU) and
        # injected cold-IO latency (the latency-hiding the executor is for)
        line = {"metric": "batch_reconstruct_pipeline_wall", "unit": "s",
                "value": None, "error": None}
        line["host_cpus"] = os.cpu_count()
        try:
            line.update(bench_reconstruct_pipeline())
            line["value"] = line.get("pipelined_s")
            line["cold_io"] = bench_reconstruct_pipeline(
                inject_io_latency_s=PIPE_COLD_IO_S)
            # view-batched A/B runs jax in a cpu-pinned subprocess so this
            # entry stays accelerator-lock-free end to end
            line["reconstruct_batched"] = _run_batched_child()
            # HBM-resident fastpath A/B: same containment (jax stays in
            # the child), byte parity + transfer-byte ratio certified there.
            # FUSED_SMOKE scale, not PIPE_VIEWS: the full-pipeline A/B at 6
            # views would dominate this arm's wall; the ratio contract is
            # scale-independent and the big regime comes from --fused-only
            line["fused_resident"] = _run_fused_child(views=2,
                                                      compute_batch=2)
            # capture-rate ingest A/B: same containment (jax stays in the
            # child); byte parity + the >=6x frame-byte ratio certified
            # there — both are scale-independent, so smoke scale suffices
            line["packed_ingest"] = _run_packed_child(views=2,
                                                      compute_batch=2)
            line["pipeline_e2e"] = bench_pipeline_e2e()
            line["merge_stream"] = bench_merge_stream()
            line["pipeline_faults"] = bench_pipeline_faults()
            line["pipeline_trace"] = bench_pipeline_trace()
            line["pipeline_deadline"] = bench_pipeline_deadline()
            line["multiproc"] = bench_multiproc()
            # multi-tenant serving A/B: same containment (jax stays in a
            # cpu-pinned child); launch-fill gain + byte parity certified
            # there — both are schedule/bit contracts, so one scan per
            # tenant suffices; the big regime comes from --serve-only
            line["serve"] = _run_serve_child(tenants=3, scans_per_tenant=1)
            fused = line["pipeline_e2e"].get("fused_s")
            disabled = line["pipeline_faults"].get("disabled_s")
            if fused and disabled:
                # the zero-overhead-by-default contract, as a ratio readers
                # can eyeball against run-to-run noise
                line["pipeline_faults"]["overhead_vs_e2e"] = round(
                    disabled / fused, 3)
            trace_off = line["pipeline_trace"].get("disabled_s")
            if fused and trace_off:
                # the flight recorder's twin of the same contract (<=1.02x
                # disabled overhead; CI's TRACE_SMOKE asserts it)
                line["pipeline_trace"]["overhead_vs_e2e"] = round(
                    trace_off / fused, 3)
            dl_off = line["pipeline_deadline"].get("disabled_s")
            if fused and dl_off:
                # cross-arm reference only: the contract ratio
                # (overhead_vs_e2e, computed in-arm) interleaves
                # identical datasets; this one spans two arms and
                # carries their dataset/page-cache bias
                line["pipeline_deadline"]["fused_ref_ratio"] = round(
                    dl_off / fused, 3)
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--assembly-only" in sys.argv[1:]:
        # standalone record of the incremental-assembly tail A/B
        # (fold-lane pod vs barrier pod vs single-process, byte-parity
        # checked at every view count): one JSON line on stdout, plus
        # BENCH_ASSEMBLY_r01.json in the repo root (skipped with
        # --no-record). The merge/assembly lane is jax, so pin to CPU
        # unless the caller already chose a platform.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        counts, reps = (4, 8), 2
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                counts = tuple(int(v) for v in a.split("=")[1].split(","))
            elif a.startswith("--reps="):
                reps = int(a.split("=")[1])
        line = {"metric": "assembly_tail_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_assembly(counts, reps))
            line["value"] = line["points"][-1]["tail_incremental_s"]
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        # emit first: it stamps run_id/host_cpus/device_count into the
        # line, and the record must carry the same regime fields
        emit(line)
        if "--no-record" not in sys.argv[1:]:
            with open(os.path.join(ROOT, "BENCH_ASSEMBLY_r01.json"),
                      "w") as f:
                json.dump(line, f, indent=2, sort_keys=True)
                f.write("\n")
        sys.exit(0)
    if "--fabric-only" in sys.argv[1:]:
        # standalone record of the pod-fabric A/B (stock vs blobstore
        # write-through single-process, cold 2-worker TCP pod, warm-resume
        # locality probe): one JSON line on stdout, no jax, no accelerator
        # lock — the numpy backend end to end (ci_tier1's BENCH_FABRIC
        # block runs this with its own budget, separate from
        # --pipeline-only so neither arm eats the other's timeout)
        views = PIPE_VIEWS
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
        line = {"metric": "fabric_pod_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_fabric(views))
            line["value"] = line.get("fabric_s")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--stream-only" in sys.argv[1:]:
        # standalone record of the streaming-merge A/B (barrier vs streamed
        # fused pipeline, byte-parity-checked): one JSON line on stdout.
        # Decode runs the numpy backend; the merge itself is jax, so pin to
        # CPU unless the caller chose a platform — a bare invocation must
        # never claim an accelerator (ci_tier1's STREAM_SMOKE runs this)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        views = PIPE_VIEWS
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
        line = {"metric": "merge_stream_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_merge_stream(views))
            line["value"] = line.get("streamed_s")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--fused-only" in sys.argv[1:]:
        # standalone record of the HBM-resident fastpath A/B (discrete vs
        # fused_clean batched pipeline, byte-parity + transfer-byte ratio):
        # one JSON line on stdout. REQUIRES jax; pins itself to CPU unless
        # the caller already chose a platform (run with JAX_PLATFORMS=tpu
        # explicitly for an on-chip line).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        views, compute_batch = PIPE_VIEWS, 3
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
            elif a.startswith("--compute-batch="):
                compute_batch = int(a.split("=")[1])
        line = {"metric": "fused_resident_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_fused_resident(views, compute_batch))
            line["value"] = line.get("fused_s")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--packed-only" in sys.argv[1:]:
        # standalone record of the capture-rate ingest A/B (raw vs packed
        # bit-plane ingest, byte-parity + >=6x frame-byte ratio): one JSON
        # line on stdout. REQUIRES jax; pins itself to CPU unless the
        # caller already chose a platform (run with JAX_PLATFORMS=tpu
        # explicitly for an on-chip line).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        views, compute_batch = PIPE_VIEWS, 3
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
            elif a.startswith("--compute-batch="):
                compute_batch = int(a.split("=")[1])
        line = {"metric": "packed_ingest_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_packed_ingest(views, compute_batch))
            line["value"] = line.get("packed_s")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--batched-only" in sys.argv[1:]:
        # standalone record of the view-batched reconstruct A/B: one JSON
        # line on stdout. This arm REQUIRES jax; unless the caller already
        # chose a platform it pins itself to CPU so a bare invocation can
        # never claim an accelerator by accident (run with
        # JAX_PLATFORMS=tpu explicitly for an on-chip line).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        views, compute_batch = BATCHED_VIEWS, BATCHED_COMPUTE
        for a in sys.argv[1:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
            elif a.startswith("--compute-batch="):
                compute_batch = int(a.split("=")[1])
        line = {"metric": "batch_reconstruct_batched_wall", "unit": "s",
                "value": None, "error": None}
        try:
            line.update(bench_reconstruct_batched(views, compute_batch))
            line["value"] = line.get("batched_s")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        emit(line)
        sys.exit(0)
    if "--serve-only" in sys.argv[1:]:
        # standalone record of the multi-tenant serving A/B: one JSON line
        # on stdout, plus BENCH_SERVE_r02.json in the repo root (skipped
        # with --no-record, which the --pipeline-only child passes). This
        # arm REQUIRES jax (the cross-tenant fill contract lives in the
        # batched engine lane); pins itself to CPU unless the caller
        # already chose a platform.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        tenants, scans, views, compute_batch = 3, 2, 2, 4
        rate_hz, seed = 5.0, 0
        for a in sys.argv[1:]:
            if a.startswith("--tenants="):
                tenants = int(a.split("=")[1])
            elif a.startswith("--scans="):
                scans = int(a.split("=")[1])
            elif a.startswith("--views="):
                views = int(a.split("=")[1])
            elif a.startswith("--compute-batch="):
                compute_batch = int(a.split("=")[1])
            elif a.startswith("--rate="):
                rate_hz = float(a.split("=")[1])
            elif a.startswith("--seed="):
                seed = int(a.split("=")[1])
        line = {"metric": "serve_mean_views_per_launch", "unit": "views",
                "value": None, "error": None}
        try:
            line.update(bench_serve(tenants=tenants,
                                    scans_per_tenant=scans, views=views,
                                    compute_batch=compute_batch,
                                    rate_hz=rate_hz, seed=seed))
            line["value"] = line.get("cross", {}).get(
                "mean_views_per_launch")
        except Exception as e:
            line["error"] = f"{type(e).__name__}: {e}"[:200]
        if "--no-record" not in sys.argv[1:]:
            from structured_light_for_3d_model_replication_tpu.utils import (
                telemetry as _tel,
            )

            line.setdefault("run_id", _tel.new_run_id())
            with open(os.path.join(ROOT, "BENCH_SERVE_r02.json"),
                      "w") as f:
                json.dump(line, f, indent=2, sort_keys=True)
                f.write("\n")
        emit(line)
        sys.exit(0)
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        views = N_VIEWS
        force_cpu = False
        for a in sys.argv[3:]:
            if a.startswith("--views="):
                views = int(a.split("=")[1])
            elif a == "--force-cpu":
                force_cpu = True
        child_main(sys.argv[2], views, force_cpu)
        sys.exit(0)
    main()
