"""Benchmark: full 360-degree scan compute (24 views x 46 frames @ 1080p),
Gray decode + ray-plane triangulation, TPU (flagship SLScanner path) vs the
bit-exact NumPy CPU backend.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value        wall-clock seconds for all 24 views on the TPU (data resident
               in HBM, steady state, best of 3)
  vs_baseline  NumPy-backend seconds for the same work / TPU seconds (speedup;
               the reference publishes no numbers — BASELINE.md records
               "published: {}" — so its own single-process CPU path, which our
               NumPy backend reproduces, is the baseline)
"""
from __future__ import annotations

import json
import time

import numpy as np

N_VIEWS = 24
CAM = (1920, 1080)
PROJ = (1920, 1080)
NP_MEASURE_VIEWS = 3  # NumPy path is linear in views; measure 3, scale


def make_view_stack(rig) -> np.ndarray:
    """Render the canonical sphere-on-wall scene through the full rig so the
    decode+triangulate output carries real valid points (not just masked
    throughput)."""
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    frames, _ = syn.render_scene(rig, syn.sphere_on_background())
    return frames


def main() -> None:
    import jax
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.models.scanner import SLScanner
    from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
    from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
    calib = rig.calibration()
    frames = make_view_stack(rig)

    # ---- NumPy CPU backend (the reference-equivalent path) ----
    t0 = time.perf_counter()
    for _ in range(NP_MEASURE_VIEWS):
        dec = gc.decode_stack_np(frames, thresh_mode="manual")
        tri.triangulate_np(dec.col_map, dec.row_map, dec.mask, dec.texture,
                           calib, row_mode=1)
    np_s = (time.perf_counter() - t0) / NP_MEASURE_VIEWS * N_VIEWS

    # ---- TPU flagship path: per-view stacks resident in HBM ----
    scanner = SLScanner(calib, CAM, PROJ, row_mode=1)
    base_dev = jnp.asarray(frames)
    views = [jnp.roll(base_dev, i * 7, axis=2) for i in range(N_VIEWS)]
    views = [jax.block_until_ready(v) for v in views]
    s = jnp.float32(40.0)
    c = jnp.float32(10.0)

    def run_all():
        outs = [scanner._fwd(v, s, c) for v in views]  # async dispatch
        jax.block_until_ready([o.points for o in outs])
        return outs

    outs = run_all()  # compile + warm
    best = min(
        (lambda t: (run_all(), time.perf_counter() - t)[1])(time.perf_counter())
        for _ in range(3)
    )
    # sanity AFTER timing: a device->host readback degrades the axon tunnel's
    # pipelined dispatch for subsequent async batches (measured 0.1ms ->
    # ~35ms per launch), so nothing may touch host memory mid-benchmark
    n_valid = int(np.asarray(outs[0].valid).sum())
    assert n_valid > 0, "bench scene produced no valid points"

    mpix = N_VIEWS * CAM[0] * CAM[1] / best / 1e6
    print(json.dumps({
        "metric": "decode_triangulate_360_24view_1080p_wall",
        "value": round(best, 4),
        "unit": f"s (={mpix:.0f} Mpix/s)",
        "vs_baseline": round(np_s / best, 2),
    }))


if __name__ == "__main__":
    main()
