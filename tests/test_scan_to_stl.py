"""The flagship Tab-6 flow end-to-end through the CLI: ``auto-scan`` (sim
turntable + a protocol-faithful *rendering* phone over live HTTP) ->
``reconstruct`` -> ``merge-360`` -> ``mesh``, asserting a valid STL of the
synthetic object (reference: server/gui.py:1700-1787 drives exactly this
chain from the GUI's auto-scan tab).

The phone here is a physical-camera simulation: it pre-renders the scene
under every Gray-code pattern at every turntable pose and uploads frame k of
view v on the (v*F+k)-th capture command. It reads NOTHING from the rig —
pattern order (01.png..NN.png) and frames-per-view are the wire contract
(SURVEY.md §2: the 46-frame file contract), and the sweep's rotation
schedule (turns x step) is the auto-scan contract, so a real phone pointed
at a real projector would produce byte-equivalent uploads."""
import json
import os
import socket
import threading
import urllib.request

import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.cli import main as cli_main
from structured_light_for_3d_model_replication_tpu.io import images as imio
from structured_light_for_3d_model_replication_tpu.io import matfile
from structured_light_for_3d_model_replication_tpu.io import ply as plyio
from structured_light_for_3d_model_replication_tpu.io import stl as stlio
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

CAM, PROJ = (160, 120), (128, 64)
TURNS, STEP = 3, 120.0
PIVOT_DEPTH = 420.0  # sphere_on_background center depth
RADIUS = 70.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _render_sweep(rig, tmpdir):
    """Pre-encode the full sweep as PNG payloads: what a phone's camera
    would capture, frame by frame, as auto-scan drives projector+table."""
    scene = syn.sphere_on_background(depth=PIVOT_DEPTH, radius=RADIUS)
    obj, background = scene.objects  # the table rotates the object, not the wall
    pivot = np.array([0.0, 0.0, PIVOT_DEPTH])
    payloads = []
    for i, (R, t) in enumerate(syn.turntable_poses(TURNS, STEP, pivot)):
        frames, _ = syn.render_scene(
            rig, syn.Scene([obj.transformed(R, t), background]))
        for k, frame in enumerate(frames):
            p = os.path.join(tmpdir, f"enc_{i}_{k}.png")
            imio.save_image(p, frame)
            payloads.append(open(p, "rb").read())
    assert len(payloads) == TURNS * gc.frames_per_view(*PROJ)
    return payloads


class RenderingPhone(threading.Thread):
    """Long-polls /poll_command, dedups command ids, answers each fresh
    capture with the next pre-rendered frame — the FakePhone of
    test_acquire.py with a camera instead of a constant payload."""

    def __init__(self, base_url: str, payloads: list[bytes]):
        super().__init__(daemon=True)
        self.base = base_url
        self.payloads = payloads
        self.stop_flag = threading.Event()
        self.captures = 0
        self.errors: list[str] = []
        self.last_id = None

    def run(self):
        while not self.stop_flag.is_set():
            try:
                with urllib.request.urlopen(self.base + "/poll_command",
                                            timeout=5) as r:
                    cmd = json.loads(r.read())
            except OSError:
                # server not up yet / long-poll idle timeout: retry gently
                self.stop_flag.wait(0.05)
                continue
            if cmd["action"] == "capture" and cmd["id"] != self.last_id:
                self.last_id = cmd["id"]
                if self.captures >= len(self.payloads):
                    self.errors.append("capture past pre-rendered sweep")
                    return
                body, ctype = self._multipart(self.payloads[self.captures])
                try:
                    req = urllib.request.Request(
                        self.base + "/upload", data=body,
                        headers={"Content-Type": ctype}, method="POST")
                    with urllib.request.urlopen(req, timeout=5) as r:
                        if json.loads(r.read())["status"] != "ok":
                            self.errors.append("upload not ok")
                except OSError as e:  # pragma: no cover - diagnostic path
                    self.errors.append(f"upload failed: {e}")
                self.captures += 1

    @staticmethod
    def _multipart(payload: bytes):
        boundary = "sweepboundary7"
        body = (
            f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="file"; filename="f.png"\r\n'
            "Content-Type: image/png\r\n\r\n"
        ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
        return body, f"multipart/form-data; boundary={boundary}"


@pytest.fixture(scope="module")
def swept_scans(tmp_path_factory):
    """Drive ``sl3d auto-scan`` against the rendering phone."""
    tmp = tmp_path_factory.mktemp("sweep")
    rig = syn.default_rig(cam_size=CAM, proj_size=PROJ)
    payloads = _render_sweep(rig, str(tmp))
    port = _free_port()
    phone = RenderingPhone(f"http://127.0.0.1:{port}", payloads)
    phone.start()
    scans_root = str(tmp / "scans")
    try:
        rc = cli_main([
            "auto-scan", scans_root,
            "--set", "acquire.simulate=true",
            "--set", f"acquire.http_port={port}",
            "--set", f"acquire.turns={TURNS}",
            "--set", f"acquire.degrees_per_turn={STEP}",
            "--set", "acquire.settle_ms_scan=0",
            "--set", "acquire.rotate_timeout_s=10",
            "--set", "acquire.capture_timeout_s=30",
            "--set", f"projector.width={PROJ[0]}",
            "--set", f"projector.height={PROJ[1]}",
        ])
    finally:
        phone.stop_flag.set()
        phone.join(timeout=5)
    assert rc == 0
    assert phone.errors == []
    assert phone.captures == len(payloads)
    calib = str(tmp / "calib.mat")
    matfile.save_calibration(calib, rig.calibration())
    return scans_root, calib


def test_auto_scan_wrote_view_contract(swept_scans):
    scans_root, _ = swept_scans
    views = sorted(os.listdir(scans_root))
    assert views == ["scan_000deg_scan", "scan_120deg_scan",
                     "scan_240deg_scan"]
    n = gc.frames_per_view(*PROJ)
    for v in views:
        names = sorted(os.listdir(os.path.join(scans_root, v)))
        assert names[0] == "01.png" and len(names) == n


def test_scan_to_stl_chain(swept_scans, tmp_path):
    scans_root, calib = swept_scans

    views_dir = str(tmp_path / "views")
    rc = cli_main(["reconstruct", scans_root, "--calib", calib,
                   "--mode", "batch", "--output", views_dir,
                   "--set", f"decode.n_cols={PROJ[0]}",
                   "--set", f"decode.n_rows={PROJ[1]}",
                   "--set", "decode.thresh_mode=manual"])
    assert rc == 0
    plys = [f for f in os.listdir(views_dir) if f.endswith(".ply")]
    assert len(plys) == TURNS
    for f in plys:
        assert len(plyio.read_ply(os.path.join(views_dir, f))["points"]) > 500

    merged = str(tmp_path / "merged.ply")
    rc = cli_main(["merge-360", views_dir, merged,
                   "--set", "merge.voxel_size=4.0",
                   "--set", "merge.ransac_trials=1024",
                   "--set", "merge.icp_iters=15",
                   "--set", "merge.final_voxel=0",
                   "--set", "merge.outlier_nb=0"])
    assert rc == 0
    pts = plyio.read_ply(merged)["points"]
    assert len(pts) > 1000

    out_stl = str(tmp_path / "model.stl")
    rc = cli_main(["mesh", merged, out_stl,
                   "--set", "mesh.depth=5",
                   "--set", "mesh.density_trim_quantile=0"])
    assert rc == 0
    verts, faces, _ = stlio.read_stl(out_stl)
    assert len(faces) > 50
    # the mesh must actually contain the scanned object: some surface near
    # the sphere (r=70 about [0,0,420]), and nothing wildly out of scene
    d = np.linalg.norm(verts - np.array([0.0, 0.0, PIVOT_DEPTH]), axis=1)
    assert d.min() < RADIUS * 1.3
    assert np.isfinite(verts).all()
    assert verts[:, 2].max() < 700.0  # scene back wall is at z=560
