"""TPU session/watch tooling: the pieces whose failure loses a recovery
window — the whole-tree limit-kill (an orphaned device client mid-claim is
the documented tunnel-wedge trigger) and the clean-bench-line guard (a
degraded line written as BENCH_SELF would later be cited as the clean
first-party TPU record)."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_session_module():
    spec = importlib.util.spec_from_file_location(
        "tpu_session", os.path.join(_ROOT, "tools", "tpu_session.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def session():
    return _load_session_module()


def test_parse_clean_bench_line_accepts_clean_tpu(session):
    line = {"metric": "m", "value": 1.9, "backend": "tpu", "error": None}
    out = "noise\n" + json.dumps(line) + "\n"
    assert session.parse_clean_bench_line(out, log=lambda m: None) == line


def test_parse_clean_bench_line_rejects_cpu_fallback(session):
    msgs = []
    line = {"value": 106.0, "backend": "cpu",
            "error": "ambient child failed; cpu fallback"}
    out = json.dumps(line)
    assert session.parse_clean_bench_line(out, log=msgs.append) is None
    assert any("degraded" in m for m in msgs)


def test_parse_clean_bench_line_rejects_errored_tpu(session):
    line = {"value": 3.5, "backend": "tpu", "error": "parent deadline hit"}
    assert session.parse_clean_bench_line(
        json.dumps(line), log=lambda m: None) is None


def test_parse_clean_bench_line_handles_garbage(session):
    assert session.parse_clean_bench_line("", log=lambda m: None) is None
    assert session.parse_clean_bench_line("no json here",
                                          log=lambda m: None) is None
    # a bare number parses as JSON but is not a record
    assert session.parse_clean_bench_line("3.553", log=lambda m: None) is None


def test_run_step_limit_kill_takes_down_grandchild(session, tmp_path):
    """The limit-kill must clear the step's whole process tree: killing only
    the direct child orphans the device-client grandchild it spawned, which
    then holds a pool claim concurrently with the watcher's next probe."""
    pidfile = tmp_path / "grandchild.pid"
    prog = (
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c', 'import time; "
        "time.sleep(60)'])\n"
        f"open({str(pidfile)!r}, 'w').write(str(p.pid))\n"
        "time.sleep(60)\n"
    )
    t0 = time.time()
    # limit must cover TWO interpreter startups on a loaded one-core host
    # (observed >3 s under a concurrent full-suite run) — a kill before
    # the grandchild exists would pass vacuously or crash on the pidfile
    rc, _ = session.run_step("t", [sys.executable, "-c", prog], limit=10)
    assert rc == -9
    assert time.time() - t0 < 40
    if not pidfile.exists() or not pidfile.read_text().strip():
        pytest.skip("step starved pre-spawn; group-kill property not "
                    "evaluable under this load")
    # the grandchild must be gone (or a zombie about to be reaped), not
    # running: signal 0 probes existence
    gpid = int(pidfile.read_text())
    for _ in range(50):
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break
        # may exist briefly as a zombie owned by init; confirm it is not
        # actually running
        stat = subprocess.run(["ps", "-o", "stat=", "-p", str(gpid)],
                              capture_output=True, text=True).stdout.strip()
        if not stat or stat.startswith("Z"):
            break
        time.sleep(0.1)
    else:
        pytest.fail(f"grandchild {gpid} still alive after group kill")


def test_run_step_normal_completion(session):
    rc, out = session.run_step(
        "t", [sys.executable, "-c", "print('hello')"], limit=30)
    assert rc == 0
    assert "hello" in out


def test_parse_clean_bench_line_skips_scalar_json_noise(session):
    good = json.dumps({"backend": "tpu", "error": None, "value": 1.0})
    line = session.parse_clean_bench_line(good + "\n42\nnull\n[]")
    assert line is not None and line["value"] == 1.0


def test_tpu_lock_excludes_second_holder(tmp_path):
    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    first = tpulock.acquire_tpu_lock(str(tmp_path), timeout=0)
    assert first is not None
    # a second process must NOT get the claim while the first holds it:
    # flock is per-open-file, so exercise it cross-process
    probe = (
        "from structured_light_for_3d_model_replication_tpu.utils import "
        "tpulock; import sys; "
        f"sys.exit(0 if tpulock.acquire_tpu_lock({str(tmp_path)!r}, "
        "timeout=0) is None else 1)"
    )
    env = {k: v for k, v in os.environ.items() if k != tpulock.HOLD_ENV}
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    assert subprocess.run([sys.executable, "-c", probe], env=env).returncode == 0
    first.close()  # fd close releases — the no-stale-lock property
    assert subprocess.run([sys.executable, "-c", probe], env=env).returncode == 1


def test_tpu_lock_parent_held_passthrough(tmp_path, monkeypatch):
    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    first = tpulock.acquire_tpu_lock(str(tmp_path), timeout=0)
    monkeypatch.setenv(tpulock.HOLD_ENV, "1")
    # a child whose parent holds the claim gets a sentinel, not a deadlock
    second = tpulock.acquire_tpu_lock(str(tmp_path), timeout=0)
    assert second is not None
    second.close()
    first.close()


def test_tpu_lock_orphan_child_reclaims(tmp_path, monkeypatch):
    """A pid-valued HOLD_ENV claim is watched: when the holding ancestor
    dies while the covered child still runs, the child must re-take the
    flock itself — otherwise the orphaned TPU client runs claim-less and a
    new client can start concurrently (the documented wedge trigger)."""
    import signal

    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    env = {k: v for k, v in os.environ.items() if k != tpulock.HOLD_ENV}
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    holder = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time; "
         "from structured_light_for_3d_model_replication_tpu.utils import "
         "tpulock; "
         "f = tpulock.acquire_tpu_lock(sys.argv[1], timeout=0); "
         "print('held', flush=True); time.sleep(120)",
         str(tmp_path)],
        stdout=subprocess.PIPE, text=True, env=env)
    child_f = None
    try:
        assert holder.stdout.readline().strip() == "held"
        # covered child; acquire_tpu_lock also arms the 10 s production
        # watcher on this fd — the extra 0.1 s-poll thread below is the one
        # this test waits on (both probe the same flock; idempotent)
        monkeypatch.setenv(tpulock.HOLD_ENV, str(holder.pid))
        child_f = tpulock.acquire_tpu_lock(str(tmp_path), timeout=0)
        assert child_f is not None
        import threading

        t = threading.Thread(target=tpulock._watch_holder,
                             args=(child_f, holder.pid, 0.1), daemon=True)
        t.start()
        holder.send_signal(signal.SIGKILL)
        holder.wait()
        t.join(timeout=10)
        assert not t.is_alive()
        # the claim must be held again — now by US (this process)
        held, detail = tpulock.probe_tpu_lock(str(tmp_path))
        assert held, detail
        assert str(os.getpid()) in detail and "orphan re-claim" in detail
        child_f.close()
        child_f = None
        held, _ = tpulock.probe_tpu_lock(str(tmp_path))
        assert not held
    finally:
        if child_f is not None:
            child_f.close()
        if holder.poll() is None:
            holder.kill()
            holder.wait()


def test_tpu_lock_released_by_sigkill(tmp_path):
    """The no-stale-lock property the design rests on: the kernel drops the
    flock the instant the holder dies — even SIGKILL, the signal the wedge
    playbook sometimes requires — so no reaping/cleanup logic exists to rot."""
    import signal

    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    env = {k: v for k, v in os.environ.items() if k != tpulock.HOLD_ENV}
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    holder = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time; sys.path.insert(0, sys.argv[2]); "
         "from structured_light_for_3d_model_replication_tpu.utils import "
         "tpulock; "
         "f = tpulock.acquire_tpu_lock(sys.argv[1], timeout=0); "
         "print('held', flush=True); time.sleep(60)",
         str(tmp_path), _ROOT],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        assert holder.stdout.readline().strip() == "held"
        held, _ = tpulock.probe_tpu_lock(str(tmp_path))
        assert held
        holder.send_signal(signal.SIGKILL)
        holder.wait()
        for _ in range(50):  # kernel releases on fd close; allow reaping lag
            held, detail = tpulock.probe_tpu_lock(str(tmp_path))
            if not held:
                break
            time.sleep(0.1)
        assert not held, detail
    finally:
        if holder.poll() is None:
            holder.kill()
            holder.wait()
