"""Pod-fabric blob service (pipeline/blobstore.py) + endpoint grammar
(parallel/netutil.py).

Contract under test (ISSUE 15): payloads move by content-addressed name
with the transfer digest verified on BOTH ends, so a corrupt or torn blob
is always a *miss* — never a wrong answer; the FabricCache is a
write-through two-level cache (local StageCache L1, blob fabric L2) whose
promotion path re-verifies through the normal ``__digest__`` machinery;
and the inventory diff protocol is additive and replay-safe.
"""
import os
import socket

import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.parallel import netutil
from structured_light_for_3d_model_replication_tpu.pipeline.blobstore import (
    BlobClient,
    BlobServer,
    FabricCache,
)
from structured_light_for_3d_model_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset()


@pytest.fixture()
def server(tmp_path):
    srv = BlobServer(str(tmp_path / "l2"), port=0)
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = BlobClient(server.endpoint, connect_timeout_s=5.0, io_timeout_s=5.0)
    yield c
    c.close()


def _arrays(seed=0):
    rng = np.random.default_rng(seed)
    return {"points": rng.normal(size=(40, 3)).astype(np.float32)}


# ---------------------------------------------------------------------------
# netutil: one endpoint grammar for coordinator, worker, and blobstore
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("text,want", [
    ("10.0.0.2:5555", ("10.0.0.2", 5555)),
    ("scanhost:9", ("scanhost", 9)),
    ("[::1]:5555", ("::1", 5555)),          # IPv6 literals must not break
    ("[fe80::1%eth0]:80", ("fe80::1%eth0", 80)),
    (":5555", ("127.0.0.1", 5555)),
    ("5555", ("127.0.0.1", 5555)),
    ("scanhost", ("scanhost", 0)),
    ("", ("127.0.0.1", 0)),
])
def test_parse_endpoint(text, want):
    assert netutil.parse_endpoint(text) == want


@pytest.mark.parametrize("text", [
    "::1",              # unbracketed IPv6 is ambiguous — rejected, not guessed
    "[::1",             # unclosed bracket
    "[::1]extra",
    "host:notaport",
    "host:70000",
    "host:-1",
])
def test_parse_endpoint_rejects(text):
    with pytest.raises(ValueError):
        netutil.parse_endpoint(text)


def test_format_endpoint_rebrackets_ipv6():
    assert netutil.format_endpoint("10.0.0.2", 5555) == "10.0.0.2:5555"
    assert netutil.format_endpoint("::1", 5555) == "[::1]:5555"
    # round trip: format -> parse is identity
    assert netutil.parse_endpoint(netutil.format_endpoint("::1", 9)) \
        == ("::1", 9)


def test_parse_endpoint_defaults_flow_through():
    assert netutil.parse_endpoint("", default_host="0.0.0.0",
                                  default_port=7) == ("0.0.0.0", 7)
    assert netutil.parse_endpoint(":9", default_host="0.0.0.0") \
        == ("0.0.0.0", 9)


# ---------------------------------------------------------------------------
# BlobServer + BlobClient: the wire
# ---------------------------------------------------------------------------

def test_push_fetch_roundtrip(server, client):
    data = os.urandom(4096)
    assert client.push("view-aaaa1111bbbb2222", data) == "pushed"
    assert client.fetch("view-aaaa1111bbbb2222") == data
    c = server.counters()
    assert c["pushes"] == 1 and c["fetches"] == 1
    assert c["bytes_pushed"] == c["bytes_fetched"] == 4096
    assert server.names() == ["view-aaaa1111bbbb2222"]


def test_fetch_absent_is_a_miss(server, client):
    assert client.fetch("view-0000000000000000") is None
    c = server.counters()
    assert c["fetches"] == 0 and c["misses"] == 1


def test_repeat_push_dedups(server, client):
    data = b"x" * 1000
    assert client.push("view-dead", data) == "pushed"
    assert client.push("view-dead", data) == "deduped"
    c = server.counters()
    assert c["pushes"] == 1 and c["dedups"] == 1
    assert c["bytes_deduped"] == 1000


def test_corrupt_server_blob_is_a_client_miss(server, client):
    """Bit rot in the L2 store cannot cross the wire: the transfer digest
    is computed over the CURRENT file bytes, and the npz-level promotion
    check is the second fence — here we corrupt between push and fetch, so
    the served bytes self-describe as valid but fail the caller's digest
    comparison path via FabricCache (below). At the raw client level, a
    torn read (size lies) is the detectable case."""
    data = os.urandom(512)
    assert client.push("view-feed", data) == "pushed"
    path = os.path.join(server.root, "view-feed.npz")
    with open(path, "wb") as f:
        f.write(data[:100])     # torn file: header size/sha now match the
    got = client.fetch("view-feed")     # TORN bytes — wire is consistent
    assert got == data[:100] or got is None
    # ...which is exactly why FabricCache re-verifies through StageCache


def test_bad_names_never_touch_the_store(server, client, tmp_path):
    secret_file = tmp_path / "l2" / ".." / "escape.npz"
    assert client.fetch("../escape") is None
    assert client.push("../escape", b"x") is None
    assert client.push("a/b", b"x") is None
    assert client.push("", b"x") is None
    assert not os.path.exists(str(secret_file))
    assert server.names() == []


def test_torn_push_is_rejected_never_published(server):
    """A push whose body does not match its announced sha256 must NOT
    publish — the server-side half of 'verified on both ends'."""
    import json
    with socket.create_connection((server.host, server.port)) as s:
        f = s.makefile("rwb")
        body = b"corrupted-in-flight"
        hdr = {"op": "put", "name": "view-beef", "size": len(body),
               "sha256": "0" * 64}
        f.write((json.dumps(hdr) + "\n").encode() + body)
        f.flush()
        rep = json.loads(f.readline())
    assert "error" in rep
    assert server.names() == []
    assert server.counters()["rejects"] == 1


def test_shared_secret_gates_the_blobstore(tmp_path):
    srv = BlobServer(str(tmp_path / "l2"), port=0, secret="scan-pod-1")
    try:
        # wrong secret: hello rejected, every call degrades to a miss
        bad = BlobClient(srv.endpoint, secret="nope", connect_timeout_s=5.0)
        assert bad.push("view-aaaa", b"data") is None
        assert bad.fetch("view-aaaa") is None
        bad.close()
        # no hello at all: first op answers unauthorized
        anon = BlobClient(srv.endpoint, connect_timeout_s=5.0)
        assert anon.fetch("view-aaaa") is None
        anon.close()
        good = BlobClient(srv.endpoint, secret="scan-pod-1",
                          connect_timeout_s=5.0)
        assert good.push("view-aaaa", b"data") == "pushed"
        assert good.fetch("view-aaaa") == b"data"
        good.close()
    finally:
        srv.close()


def test_client_survives_server_restart(tmp_path):
    """One silent reconnect per call: a bounced blobstore (coordinator
    failover) costs at most one retried transfer, not a failed item."""
    srv = BlobServer(str(tmp_path / "l2"), port=0)
    cli = BlobClient(srv.endpoint, connect_timeout_s=5.0, io_timeout_s=5.0)
    assert cli.push("view-aaaa", b"one") == "pushed"
    host, port = srv.host, srv.port
    srv.close()
    srv = BlobServer(str(tmp_path / "l2"), host=host, port=port)
    try:
        assert cli.fetch("view-aaaa") == b"one"
    finally:
        cli.close()
        srv.close()


def test_transient_fault_absorbs_into_retry(server, client):
    faults.configure("blob.fetch:transient@1")
    assert client.push("view-aaaa", b"data") == "pushed"
    assert client.fetch("view-aaaa") == b"data"   # attempt 2 wins


def test_permanent_fault_degrades_to_miss(server, client):
    assert client.push("view-aaaa", b"data") == "pushed"
    faults.configure("blob.fetch:permanent")
    assert client.fetch("view-aaaa") is None      # miss, NOT an exception
    faults.reset()
    faults.configure("blob.push:permanent")
    assert client.push("view-bbbb", b"data") is None


def test_unreachable_endpoint_times_out_to_miss():
    cli = BlobClient("127.0.0.1:1", connect_timeout_s=0.3, io_timeout_s=0.3)
    assert cli.fetch("view-aaaa") is None
    assert cli.push("view-aaaa", b"x") is None
    cli.close()


# ---------------------------------------------------------------------------
# FabricCache: L1/L2 semantics
# ---------------------------------------------------------------------------

def test_l2_promotion_into_l1(tmp_path, server):
    """Producer pushes through its cache; a consumer with a COLD private
    L1 misses locally, fetches by digest, promotes, and the second read is
    a pure L1 hit (no second fetch)."""
    prod_cli = BlobClient(server.endpoint, connect_timeout_s=5.0)
    producer = FabricCache(str(tmp_path / "w0"), prod_cli)
    key = producer.key("view", config_json="{}")
    producer.put("view", key, **_arrays())
    assert server.counters()["pushes"] == 1

    cons_cli = BlobClient(server.endpoint, connect_timeout_s=5.0)
    consumer = FabricCache(str(tmp_path / "w1"), cons_cli)
    out = consumer.get("view", key)
    np.testing.assert_array_equal(out["points"], _arrays()["points"])
    assert server.counters()["fetches"] == 1
    assert os.path.exists(consumer._path("view", key))   # promoted to L1
    consumer.get("view", key)
    assert server.counters()["fetches"] == 1             # L1 hit, no refetch
    prod_cli.close()
    cons_cli.close()


def test_put_is_write_through(tmp_path, server, client):
    cache = FabricCache(str(tmp_path / "w0"), client)
    key = cache.key("view", config_json="{}")
    cache.put("view", key, **_arrays())
    assert cache.get("view", key) is not None            # L1
    assert server.names() == [f"view-{key[:16]}"]        # and L2


def test_corrupt_l2_blob_is_an_evicted_miss(tmp_path, server, client):
    """The second fence: a blob that is wire-consistent but npz-corrupt
    (rot BEFORE the push digest was computed) promotes into L1, fails the
    normal ``__digest__`` verification, evicts, and reads as a miss —
    never a wrong answer."""
    cache = FabricCache(str(tmp_path / "w0"), client)
    key = cache.key("view", config_json="{}")
    name = f"view-{key[:16]}"
    # plant a corrupt-but-complete blob in L2 under the right name
    good = FabricCache(str(tmp_path / "scratch"), None)
    good.put("view", key, **_arrays())
    blob = bytearray(open(good._path("view", key), "rb").read())
    mid = len(blob) // 2
    for i in range(mid, mid + 16):
        blob[i] ^= 0xFF
    assert client.push(name, bytes(blob)) == "pushed"    # wire sha matches
    assert cache.get("view", key) is None                # promoted, failed
    assert not os.path.exists(cache._path("view", key))  # verify, evicted
    assert cache.stats()["evicted"] == 1


def test_fabric_cache_without_client_is_plain_l1(tmp_path):
    cache = FabricCache(str(tmp_path / "w0"), None)
    key = cache.key("view", config_json="{}")
    assert cache.get("view", key) is None
    cache.put("view", key, **_arrays())
    assert cache.get("view", key) is not None
    assert cache.drain_inventory() == [f"view-{key[:16]}"]


# ---------------------------------------------------------------------------
# inventory protocol
# ---------------------------------------------------------------------------

def test_inventory_drains_once_and_requeues(tmp_path, server, client):
    cache = FabricCache(str(tmp_path / "w0"), client)
    k1 = cache.key("view", config_json='{"v": 1}')
    k2 = cache.key("view", config_json='{"v": 2}')
    cache.put("view", k1, **_arrays(1))
    cache.put("view", k2, **_arrays(2))
    diff = cache.drain_inventory()
    assert diff == sorted([f"view-{k1[:16]}", f"view-{k2[:16]}"])
    assert cache.drain_inventory() == []                 # drained exactly once
    # the carrying heartbeat failed: requeue, next drain retries the diff
    cache.requeue_inventory(diff)
    assert cache.drain_inventory() == diff


def test_promotion_joins_the_inventory(tmp_path, server):
    """A FETCHED blob is inventory too — the worker now holds it locally,
    so pair grants can prefer this host."""
    prod = FabricCache(str(tmp_path / "w0"),
                       BlobClient(server.endpoint, connect_timeout_s=5.0))
    key = prod.key("view", config_json="{}")
    prod.put("view", key, **_arrays())
    cons = FabricCache(str(tmp_path / "w1"),
                       BlobClient(server.endpoint, connect_timeout_s=5.0))
    assert cons.drain_inventory() == []
    assert cons.get("view", key) is not None
    assert cons.drain_inventory() == [f"view-{key[:16]}"]


def test_local_names_is_the_bootstrap_inventory(tmp_path):
    cache = FabricCache(str(tmp_path / "w0"), None)
    key = cache.key("view", config_json="{}")
    cache.put("view", key, **_arrays())
    cache.drain_inventory()
    # a resumed worker re-announces EVERYTHING its L1 holds on hello
    resumed = FabricCache(str(tmp_path / "w0"), None)
    assert resumed.local_names() == [f"view-{key[:16]}"]
    assert FabricCache(str(tmp_path / "empty"), None).local_names() == []
