"""Unit tests for the elastic fleet + authenticated front door (ISSUE 18).

Contract under test, all on injectable clocks (zero real sleeps):

  * :func:`fleet.decide` is pure and ordered — floor first, breaker
    storms never scale up, backlog scales toward ceil(backlog/per) plus
    queued scans, in-flight work holds, scale-in needs sustained idle;
  * :class:`fleet.FlapTracker` doubles backoff per death inside the
    window, caps it, pins a flapping rank at the cap, and forgets clean
    retirements;
  * the supervisor's decisions journal BEFORE they enact and are
    epoch-fenced: a superseded lease stops the tick cold, a FencedWrite
    from the ledger propagates (the loop's demote trigger);
  * SIGKILL-shaped death (a reaped proc) drops the lane's leases,
    journals the exit, and respawns the RANK at gen+1 under backoff;
  * :func:`fleet.replay_fleet` folds the ledger back to the live final
    state and ignores stale-epoch lines a zombie raced in;
  * the front door: TenantAuth 401/403 matrix, RateLimiter 429s with
    retry_after_s, the ScanService wiring of both, and `/usage` metering
    (``fold_usage``) agreeing with an AdmissionController-driven ledger.
"""
import json
import os

import pytest

from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.parallel import fleet
from structured_light_for_3d_model_replication_tpu.parallel.admission import (
    AdmissionController,
    RateLimiter,
    ScanJob,
    TenantAuth,
    fold_usage,
    hash_key,
    replay_serving,
    write_tenant,
)
from structured_light_for_3d_model_replication_tpu.parallel.election import (
    FencedWrite,
)
from structured_light_for_3d_model_replication_tpu.utils import faults


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture()
def clock():
    return FakeClock()


def P(**kw):
    return fleet.FleetParams(**kw)


SIG0 = {"queued_scans": 0, "active_scans": 0, "pending_items": 0,
        "granted_items": 0, "queue_wait_p50_s": 0.0,
        "queue_wait_p99_s": 0.0, "open_breakers": 0}


def sig(**kw):
    s = dict(SIG0)
    s.update(kw)
    return s


# ---------------------------------------------------------------------------
# decide(): the pure scaling function
# ---------------------------------------------------------------------------

def test_decide_scales_up_on_backlog():
    d = fleet.decide(sig(pending_items=9), live=0, idle_s=0.0,
                     p=P(scale_up_queue=4, max_workers=4))
    assert d["action"] == "scale-up"
    assert d["target"] == 3          # ceil(9/4)


def test_decide_counts_queued_scans_as_future_backlog():
    d = fleet.decide(sig(pending_items=1, queued_scans=2), live=0,
                     idle_s=0.0, p=P(scale_up_queue=4, max_workers=8))
    assert d["action"] == "scale-up"
    assert d["target"] == 3          # ceil(1/4) + 2 queued


def test_decide_clamps_to_max():
    d = fleet.decide(sig(pending_items=1000), live=0, idle_s=0.0,
                     p=P(max_workers=2))
    assert d["target"] == 2


def test_decide_holds_below_floor_never():
    d = fleet.decide(SIG0, live=0, idle_s=0.0, p=P(min_workers=1))
    assert d["action"] == "scale-up"
    assert d["target"] == 1


def test_decide_breaker_storm_never_scales_up():
    d = fleet.decide(sig(pending_items=50, open_breakers=1), live=1,
                     idle_s=0.0, p=P(max_workers=8))
    assert d["action"] == "hold"
    assert d["target"] == 1


def test_decide_holds_while_work_in_flight():
    for s in (sig(active_scans=1), sig(granted_items=2)):
        d = fleet.decide(s, live=3, idle_s=100.0, p=P())
        assert d["action"] == "hold", s
        assert d["target"] == 3


def test_decide_scale_in_requires_sustained_idle():
    p = P(scale_in_idle_s=5.0)
    assert fleet.decide(SIG0, live=2, idle_s=4.9, p=p)["action"] == "hold"
    d = fleet.decide(SIG0, live=2, idle_s=5.0, p=p)
    assert d["action"] == "scale-in"
    assert d["target"] == 0


def test_decide_never_scales_in_below_floor():
    d = fleet.decide(SIG0, live=2, idle_s=60.0, p=P(min_workers=1))
    assert d["target"] == 1


# ---------------------------------------------------------------------------
# FlapTracker: backoff caps + flap damping
# ---------------------------------------------------------------------------

def test_backoff_doubles_per_death_and_caps(clock):
    ft = fleet.FlapTracker(window_s=600.0, threshold=0, backoff_s=0.5,
                           backoff_max_s=4.0, clock=clock)
    assert ft.backoff(0) == 0.0
    got = []
    for _ in range(5):
        ft.record_exit(0)
        got.append(ft.backoff(0))
        clock.advance(1.0)
    assert got == [0.5, 1.0, 2.0, 4.0, 4.0]      # capped


def test_flapping_pins_to_max_backoff(clock):
    ft = fleet.FlapTracker(window_s=60.0, threshold=3, backoff_s=0.5,
                           backoff_max_s=30.0, clock=clock)
    ft.record_exit(1)
    ft.record_exit(1)
    assert not ft.flapping(1)
    ft.record_exit(1)
    assert ft.flapping(1)
    assert ft.backoff(1) == 30.0


def test_window_drain_resets_history(clock):
    ft = fleet.FlapTracker(window_s=60.0, threshold=3, backoff_s=0.5,
                           backoff_max_s=30.0, clock=clock)
    for _ in range(3):
        ft.record_exit(2)
    assert ft.flapping(2)
    clock.advance(61.0)
    assert not ft.flapping(2)
    assert ft.backoff(2) == 0.0


def test_clean_retirement_clears_history(clock):
    ft = fleet.FlapTracker(window_s=600.0, threshold=3, backoff_s=0.5,
                           backoff_max_s=30.0, clock=clock)
    ft.record_exit(0)
    ft.record_exit(0)
    ft.record_exit(0, clean=True)
    assert ft.deaths(0) == 0
    assert ft.backoff(0) == 0.0


# ---------------------------------------------------------------------------
# FleetSupervisor: decision loop on fakes (no sockets, no processes)
# ---------------------------------------------------------------------------

class FakeProc:
    _pid = 40000

    def __init__(self):
        FakeProc._pid += 1
        self.pid = FakeProc._pid
        self.returncode = None

    def poll(self):
        return self.returncode

    def terminate(self):
        if self.returncode is None:
            self.returncode = 143

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        self.returncode = 137


class FakeLeases:
    def worker_items(self, worker):
        return []


class FakeLedger:
    def __init__(self):
        self.events = []
        self.fence_exc = None

    def event(self, type_, **fields):
        if self.fence_exc is not None:
            raise self.fence_exc
        self.events.append(dict(fields, type=type_))

    def actions(self):
        return [e["action"] for e in self.events if e["type"] == "fleet"]


class FakeAdm:
    def __init__(self):
        self.ledger = FakeLedger()
        self.leases = FakeLeases()
        self.sig = dict(SIG0)
        self.dropped = []

    def signals(self):
        return dict(self.sig)

    def drop_lane(self, lane, reason="worker-dead"):
        self.dropped.append((lane, reason))
        return 0


class FakeLease:
    def __init__(self):
        self.superseded_now = False

    def superseded(self):
        return self.superseded_now


def make_sup(tmp_path, clock, adm=None, lease=None, **scfg_over):
    cfg = Config()
    cfg.serving.fleet_enabled = True
    cfg.serving.fleet_max_workers = 4
    cfg.serving.fleet_scale_up_queue = 4
    cfg.serving.fleet_backoff_s = 0.5
    cfg.serving.fleet_backoff_max_s = 4.0
    cfg.serving.fleet_flap_threshold = 3
    cfg.serving.fleet_flap_window_s = 60.0
    for k, v in scfg_over.items():
        setattr(cfg.serving, k, v)
    adm = adm or FakeAdm()
    events = {"demote": [], "crash": []}
    sup = fleet.FleetSupervisor(
        str(tmp_path), cfg, adm, str(tmp_path / "cache"),
        steps=("statistical",), log=lambda m: None, lease=lease,
        on_demote=lambda why: events["demote"].append(why),
        on_crash=lambda where, e: events["crash"].append((where, e)),
        clock=clock, spawn_fn=lambda rank, gen: FakeProc())
    return sup, adm, events


def test_supervisor_scales_up_and_journals_signals(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock)
    adm.sig = sig(pending_items=9)
    sup._tick()
    st = sup.state()
    assert st["live"] == [0, 1, 2]           # ceil(9/4) = 3
    assert st["target"] == 3
    acts = adm.ledger.actions()
    assert acts == ["scale-up", "spawn", "spawn", "spawn"]
    # the decision carries the deciding snapshot
    ev = adm.ledger.events[0]
    assert ev["signals"]["pending_items"] == 9


def test_supervisor_death_respawns_rank_at_next_generation(tmp_path,
                                                           clock):
    sup, adm, _ = make_sup(tmp_path, clock)
    adm.sig = sig(pending_items=4)
    sup._tick()
    assert sup.state()["live"] == [0]
    sup._workers[0]["proc"].returncode = 137      # SIGKILL-shaped
    sup._tick()
    assert ("fw0", "worker-exit-137") in adm.dropped
    assert "worker-exit" in adm.ledger.actions()
    assert sup.state()["live"] == []
    assert sup.state()["respawning"] == [0]
    clock.advance(0.5)                            # past first backoff
    sup._tick()
    st = sup.state()
    assert st["live"] == [0]
    assert st["generations"][0] == 1              # healed, not new
    assert "respawn" in adm.ledger.actions()


def test_supervisor_flap_damping_caps_respawn_rate(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock)
    adm.sig = sig(pending_items=1)
    sup._tick()
    for _ in range(3):                            # three quick deaths
        sup._workers[0]["proc"].returncode = 137
        sup._tick()
        clock.advance(sup.flap.backoff_max_s)
        sup._tick()
    exits = [e for e in adm.ledger.events
             if e["type"] == "fleet" and e["action"] == "worker-exit"]
    assert exits[-1]["flapping"] is True
    assert exits[-1]["backoff_s"] == sup.flap.backoff_max_s


def test_supervisor_scale_in_retires_then_reaps_clean(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock, fleet_scale_in_idle_s=5.0)
    adm.sig = sig(pending_items=8)
    sup._tick()
    assert sup.state()["live"] == [0, 1]
    adm.sig = dict(SIG0)
    clock.advance(5.0)
    sup._tick()
    st = sup.state()
    assert st["target"] == 0
    assert st["retiring"] == ["fw0", "fw1"]
    assert sup.is_retiring("fw0")                 # the bridge's question
    for w in list(sup._workers.values()):
        w["proc"].returncode = 0                  # clean shutdown exit
    sup._tick()
    assert sup.state()["live"] == []
    acts = adm.ledger.actions()
    assert acts.count("retire") == 2
    assert acts.count("retired") == 2
    assert "worker-exit" not in acts              # retirement != death


def test_retiring_worker_killed_midway_is_not_respawned(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock, fleet_scale_in_idle_s=5.0)
    adm.sig = sig(pending_items=4)
    sup._tick()
    adm.sig = dict(SIG0)
    clock.advance(5.0)
    sup._tick()                                   # retire fw0
    sup._workers[0]["proc"].returncode = 137      # killed while draining
    sup._tick()
    assert sup.state()["live"] == []
    assert sup.state()["respawning"] == []        # wanted gone: stays gone


def test_superseded_lease_stops_decisions_and_demotes(tmp_path, clock):
    lease = FakeLease()
    sup, adm, events = make_sup(tmp_path, clock, lease=lease)
    adm.sig = sig(pending_items=9)
    lease.superseded_now = True
    sup._tick()
    assert sup.state()["live"] == []              # nothing enacted
    assert adm.ledger.actions() == []             # nothing journaled
    assert events["demote"]                       # the service was told
    assert sup._stop.is_set()


def test_fenced_journal_write_propagates(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock)
    adm.sig = sig(pending_items=4)
    adm.ledger.fence_exc = FencedWrite("stale epoch 1 < 2")
    with pytest.raises(FencedWrite):
        sup._tick()
    assert sup.state()["live"] == []              # journal-first: no spawn


def test_spawn_fault_retries_under_backoff(tmp_path, clock):
    faults.configure("worker.spawn:transient@1")
    try:
        sup, adm, _ = make_sup(tmp_path, clock)
        adm.sig = sig(pending_items=4)
        sup._tick()
        assert sup.state()["live"] == []
        assert "spawn-failed" in adm.ledger.actions()
        assert sup.state()["respawning"] == [0]
        clock.advance(1.0)
        sup._tick()                               # retry succeeds
        assert sup.state()["live"] == [0]
    finally:
        faults.reset()


def test_decide_fault_crash_is_supervisor_fatal(tmp_path, clock):
    faults.configure("fleet.decide:crash@1")
    try:
        sup, adm, _ = make_sup(tmp_path, clock)
        adm.sig = sig(pending_items=4)
        with pytest.raises(faults.InjectedCrash):
            sup._tick()
        assert sup.state()["live"] == []
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# replay_fleet: the scaling history folds back, epoch-fenced
# ---------------------------------------------------------------------------

def _ledger_lines(tmp_path, lines):
    path = str(tmp_path / "ledger.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    return path


def test_replay_fleet_reproduces_final_state(tmp_path, clock):
    sup, adm, _ = make_sup(tmp_path, clock)
    real = AdmissionController(str(tmp_path / "ledger.jsonl"), "r1",
                               log=lambda m: None)
    sup.adm = adm          # keep fakes for procs; journal to a REAL ledger
    adm.ledger = real.ledger
    adm.sig = sig(pending_items=8)
    sup._tick()                                   # spawn fw0, fw1
    sup._workers[0]["proc"].returncode = 137
    sup._tick()                                   # fw0 dies
    clock.advance(0.5)
    sup._tick()                                   # fw0 respawns gen 1
    real.close()
    rs = fleet.replay_fleet(str(tmp_path / "ledger.jsonl"))
    st = sup.state()
    assert rs["live"] == st["live"] == [0, 1]
    assert rs["target"] == st["target"] == 2
    assert rs["generations"][0] == st["generations"][0] == 1
    assert rs["generations"][1] == 0


def test_replay_fleet_ignores_stale_epoch_lines(tmp_path):
    path = _ledger_lines(tmp_path, [
        {"type": "fleet", "epoch": 2, "action": "spawn", "rank": 0,
         "gen": 3, "target": 1},
        # a zombie's raced-in line from the deposed epoch 1: ignored
        {"type": "fleet", "epoch": 1, "action": "spawn", "rank": 7,
         "gen": 9, "target": 5},
        {"type": "fleet", "epoch": 2, "action": "worker-exit", "rank": 0,
         "gen": 3},
    ])
    rs = fleet.replay_fleet(path)
    assert rs["live"] == []
    assert rs["target"] == 1
    assert rs["stale_ignored"] == 1
    assert 7 not in rs["generations"]


def test_replay_fleet_tolerates_torn_tail(tmp_path):
    path = _ledger_lines(tmp_path, [
        {"type": "fleet", "action": "spawn", "rank": 0, "gen": 0,
         "target": 1},
    ])
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"type": "fleet", "action": "spawn", "ra')   # torn
    rs = fleet.replay_fleet(path)
    assert rs["live"] == [0]


# ---------------------------------------------------------------------------
# front door: TenantAuth 401/403, RateLimiter 429
# ---------------------------------------------------------------------------

def test_auth_matrix(tmp_path):
    path = str(tmp_path / "tenants.json")
    write_tenant(path, "alice", "alice-key")
    write_tenant(path, "bob", "bob-key")
    auth = TenantAuth(path)
    assert auth.check("alice", "alice-key") is None
    assert auth.check("alice", "")["reason"] == "auth-required"
    assert auth.check("alice", "nope")["reason"] == "auth-invalid"
    # a key valid for SOMEONE is 403, not 401: identity known, role wrong
    assert auth.check("alice", "bob-key")["reason"] == "auth-forbidden"
    assert auth.check("mallory", "mallory")["reason"] == "auth-invalid"
    assert sorted(auth.known()) == ["alice", "bob"]


def test_auth_fails_closed_without_file(tmp_path):
    auth = TenantAuth(str(tmp_path / "missing.json"))
    assert auth.check("alice", "any")["reason"] == "auth-invalid"


def test_auth_file_reload_on_rotation(tmp_path):
    path = str(tmp_path / "tenants.json")
    write_tenant(path, "alice", "old-key")
    auth = TenantAuth(path)
    assert auth.check("alice", "old-key") is None
    write_tenant(path, "alice", "new-key")       # atomic rewrite: new stat
    assert auth.check("alice", "old-key")["reason"] == "auth-invalid"
    assert auth.check("alice", "new-key") is None


def test_tenants_file_stores_hashes_only(tmp_path):
    path = str(tmp_path / "tenants.json")
    write_tenant(path, "alice", "super-secret", rate_limit=5)
    raw = open(path, encoding="utf-8").read()
    assert "super-secret" not in raw
    assert hash_key("super-secret") in raw


def test_rate_limiter_sliding_window(clock):
    rl = RateLimiter(2, window_s=60.0, clock=clock)
    assert rl.allow("t") is None
    assert rl.allow("t") is None
    rej = rl.allow("t")
    assert rej["reason"] == "rate-limited"
    assert rej["retry_after_s"] > 0
    assert rl.allow("other") is None              # per-tenant windows
    clock.advance(61.0)
    assert rl.allow("t") is None                  # window drained


def test_rate_limiter_per_tenant_override(clock):
    rl = RateLimiter(0, clock=clock)              # 0 = unlimited default
    for _ in range(10):
        assert rl.allow("free") is None
    assert rl.allow("capped", 1, 60.0) is None
    assert rl.allow("capped", 1, 60.0)["reason"] == "rate-limited"


# ---------------------------------------------------------------------------
# service-level wiring: submit 401/403/429 + /usage fold parity
# ---------------------------------------------------------------------------

def _svc(tmp_path, **scfg_over):
    from structured_light_for_3d_model_replication_tpu.pipeline.serving import (
        ScanService,
    )

    cfg = Config()
    cfg.serving.auth_enabled = True
    for k, v in scfg_over.items():
        setattr(cfg.serving, k, v)
    root = str(tmp_path / "svc")
    os.makedirs(root, exist_ok=True)
    write_tenant(os.path.join(root, "tenants.json"), "alice", "ak")
    write_tenant(os.path.join(root, "tenants.json"), "bob", "bk",
                 rate_limit=1, rate_window_s=3600.0)
    return ScanService(root, cfg=cfg, log=lambda m: None)


def test_submit_auth_gate_401_403_429(tmp_path):
    from structured_light_for_3d_model_replication_tpu.pipeline.serving import (
        _REASON_HTTP,
    )

    svc = _svc(tmp_path)
    try:
        ok, body = svc.submit({"tenant": "alice"})
        assert not ok and body["reason"] == "auth-required"
        ok, body = svc.submit({"tenant": "alice", "api_key": "wrong"})
        assert not ok and body["reason"] == "auth-invalid"
        ok, body = svc.submit({"tenant": "alice", "api_key": "bk"})
        assert not ok and body["reason"] == "auth-forbidden"
        # authenticated but bad payload: auth happens FIRST, then 400
        ok, body = svc.submit({"tenant": "alice", "api_key": "ak"})
        assert not ok and body["reason"] == "bad-request"
        # bob's per-tenant override: second submit inside the window 429s
        svc.submit({"tenant": "bob", "api_key": "bk"})
        ok, body = svc.submit({"tenant": "bob", "api_key": "bk"})
        assert not ok and body["reason"] == "rate-limited"
        assert body["retry_after_s"] > 0
        assert _REASON_HTTP["auth-required"] == 401
        assert _REASON_HTTP["auth-invalid"] == 401
        assert _REASON_HTTP["auth-forbidden"] == 403
        assert _REASON_HTTP["rate-limited"] == 429
    finally:
        svc.close()


def test_auth_disabled_costs_one_none_check(tmp_path):
    svc = _svc(tmp_path, auth_enabled=False)
    try:
        assert svc._auth is None                  # the 1.02x contract
        ok, body = svc.submit({"tenant": "alice"})
        assert not ok and body["reason"] == "bad-request"   # not auth
    finally:
        svc.close()


def test_usage_fold_parity_vs_ledger(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    adm = AdmissionController(path, "r1", log=lambda m: None)
    for i, (tenant, state) in enumerate(
            (("alice", "done"), ("alice", "degraded"), ("bob", "failed"))):
        sid = f"{tenant}-s{i}"
        job = ScanJob(sid, tenant, f"/t{i}", "/c", f"/o{i}")
        ok, _ = adm.submit(job)
        assert ok
        adm.add_items(sid, [{"index": 0, "src": "v0"}])
        job.state = "admitted"
        (iid, gen, _spec), = adm.next_views("lane0", 1)
        assert adm.complete(iid, "lane0", gen)
        adm.finish(sid, state)
    sid = "carol-s9"
    ok, _ = adm.submit(ScanJob(sid, "carol", "/t9", "/c", "/o9"))
    assert ok                                     # stays in flight
    adm.close()
    u = fold_usage(replay_serving(path))
    assert u["alice"]["submitted"] == 2
    assert u["alice"]["done"] == 1
    assert u["alice"]["degraded"] == 1
    assert u["alice"]["views_completed"] == 2
    assert u["alice"]["in_flight"] == 0
    assert u["bob"]["failed"] == 1
    assert u["bob"]["views_completed"] == 1
    assert u["carol"]["in_flight"] == 1
    assert u["carol"]["compute_s"] == 0.0
    assert u["alice"]["compute_s"] >= 0.0
