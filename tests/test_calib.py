"""Calibration pipeline tests: chessboard detection, corner Gray decode, the
stereo solve on synthetic geometry, inspectors, and JAX undistortion."""
from __future__ import annotations

import os

import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.calib import chessboard as cb
from structured_light_for_3d_model_replication_tpu.calib import inspect as insp
from structured_light_for_3d_model_replication_tpu.calib import pipeline as cpipe
from structured_light_for_3d_model_replication_tpu.calib.geometry import (
    build_calibration,
)
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc

cv2 = pytest.importorskip("cv2")

BOARD = cb.BoardSpec(rows=5, cols=6, square_size=20.0)


def render_board_image(width=320, height=240, origin=(60, 50), cell=24):
    """Fronto-parallel chessboard drawing with inner corners at known pixels."""
    img = np.full((height, width), 255, np.uint8)
    ox, oy = origin
    # (rows+1) x (cols+1) squares so there are rows x cols inner corners
    for i in range(BOARD.cols + 1):
        for j in range(BOARD.rows + 1):
            if (i + j) % 2 == 0:
                y0, x0 = oy + i * cell, ox + j * cell
                img[y0 : y0 + cell, x0 : x0 + cell] = 20
    # a checker intersection at block boundary x0 sits between pixels: x0 - 0.5
    corners = np.array(
        [
            [ox + (j + 1) * cell - 0.5, oy + (i + 1) * cell - 0.5]
            for i in range(BOARD.cols)
            for j in range(BOARD.rows)
        ],
        np.float32,
    )
    return img, corners


def test_find_corners_synthetic_board():
    img, expected = render_board_image()
    found = cb.find_corners(img, BOARD)
    assert found is not None and found.shape == (BOARD.rows * BOARD.cols, 2)
    # detection may enumerate the grid in reverse order; compare as sets
    d = np.abs(found[None, :, :] - expected[:, None, :]).sum(-1)
    assert d.min(axis=1).max() < 1.0  # every expected corner matched sub-pixel


def test_decode_at_points_recovers_projector_coords():
    pw, ph = 128, 64
    stack = gc.generate_pattern_stack(pw, ph, brightness=200)
    rng = np.random.default_rng(0)
    x = rng.integers(0, pw, 50).astype(np.float64) + rng.uniform(0, 0.45, 50)
    y = rng.integers(0, ph, 50).astype(np.float64) + rng.uniform(0, 0.45, 50)
    pts = np.column_stack([x, y])
    col, row = cpipe.decode_at_points(stack[2:], pts, 7, 6)
    np.testing.assert_array_equal(col, np.floor(x))
    np.testing.assert_array_equal(row, np.floor(y))


def _synth_rig():
    cam_K = np.array([[300.0, 0, 160], [0, 300.0, 120], [0, 0, 1]])
    proj_K = np.array([[400.0, 0, 128], [0, 400.0, 64], [0, 0, 1]])
    ang = np.radians(12.0)
    R = np.array(
        [[np.cos(ang), 0, np.sin(ang)], [0, 1, 0], [-np.sin(ang), 0, np.cos(ang)]]
    )
    T = np.array([[-120.0], [5.0], [30.0]])
    return cam_K, proj_K, R, T


def _project(K, R, t, pts):
    p = pts @ R.T + t.reshape(1, 3)
    p = p / p[:, 2:3]
    return (p @ K.T)[:, :2]


def make_observations(n_poses=8):
    """Synthetic matched triples: a board posed in front of both devices."""
    cam_K, proj_K, R, T = _synth_rig()
    obj = cb.board_object_points(BOARD).astype(np.float64)
    rng = np.random.default_rng(7)
    obs = []
    for i in range(n_poses):
        rx, ry, rz = rng.uniform(-0.35, 0.35, 3)
        Rb, _ = cv2.Rodrigues(np.array([rx, ry, rz]))
        tb = np.array([rng.uniform(-30, 30), rng.uniform(-25, 25),
                       rng.uniform(380, 560)])
        world = obj @ Rb.T + tb  # board points in camera frame
        cam_px = _project(cam_K, np.eye(3), np.zeros(3), world)
        proj_px = _project(proj_K, R, T.reshape(3), world)
        obs.append(
            cpipe.PoseObservation(
                f"pose{i:02d}",
                obj.astype(np.float32),
                cam_px.astype(np.float32),
                proj_px.astype(np.float32),
            )
        )
    return obs, cam_K, proj_K, R, T


def test_stereo_calibration_recovers_geometry():
    obs, cam_K, proj_K, R, T = make_observations()
    sol = cpipe.calibrate_stereo(obs, (320, 240), (256, 128), log=lambda *_: None)
    assert sol.rms_stereo < 0.5
    np.testing.assert_allclose(sol.cam_K[0, 0], cam_K[0, 0], rtol=0.02)
    np.testing.assert_allclose(sol.proj_K[0, 0], proj_K[0, 0], rtol=0.02)
    np.testing.assert_allclose(
        np.linalg.norm(sol.T), np.linalg.norm(T), rtol=0.02
    )
    np.testing.assert_allclose(sol.R, R, atol=0.01)


def test_reprojection_errors_and_pose_selection():
    obs, *_ = make_observations()
    # corrupt one pose's projector decode with non-rigid noise (a constant
    # offset would be absorbed into that pose's extrinsics) to make it prunable
    bad = obs[3]
    noise = np.random.default_rng(11).normal(0, 6.0, bad.proj_pts.shape)
    obs[3] = bad._replace(proj_pts=(bad.proj_pts + noise).astype(np.float32))
    errors = cpipe.reprojection_errors(obs, (320, 240), (256, 128))
    assert set(errors) == {o.name for o in obs}
    keep = cpipe.select_poses(errors, max_cam_err=1.0, max_proj_err=0.5)
    assert "pose03" not in keep and len(keep) >= 3


def test_summarize_and_quality_bands():
    cam_K, proj_K, R, T = _synth_rig()
    calib = build_calibration(cam_K, np.zeros(5), proj_K, R, T, 320, 240,
                              256, 128, include_ray_field=False)
    s = insp.summarize_calibration(calib, reprojection_error_px=0.3)
    assert s["quality"] == "EXCELLENT"
    np.testing.assert_allclose(s["baseline_mm"], np.linalg.norm(T), rtol=1e-6)
    assert abs(s["euler_deg"]["pitch"] - 12.0) < 1e-3
    assert insp.quality_band(0.7) == "GOOD" and insp.quality_band(1.5) == "POOR"
    assert "baseline" in insp.format_summary(s)


def test_collect_calibration_data_end_to_end(tmp_path):
    """Full folder-level path: white/black + pattern frames on disk -> matched
    observations with decoded projector coordinates."""
    pw, ph = 128, 64
    cw, chh = 320, 240
    base, corners = render_board_image(cw, chh)
    stack = gc.generate_pattern_stack(pw, ph, brightness=200)
    # camera sees the projector raster through a fixed affine pixel map
    xi = (np.arange(cw) * pw) // cw
    yi = (np.arange(chh) * ph) // chh
    for pose in ("p0", "p1", "p2"):
        d = tmp_path / pose
        d.mkdir()
        cam_view = stack[:, yi[:, None], xi[None, :]]
        # white frame must contain the detectable board
        cv2.imwrite(str(d / "01.png"), base)
        cv2.imwrite(str(d / "02.png"), np.zeros_like(base))
        for f in range(2, stack.shape[0]):
            cv2.imwrite(str(d / f"{f + 1:02d}.png"), cam_view[f])
    obs, img_shape = cpipe.collect_calibration_data(
        str(tmp_path), board=BOARD, proj_size=(pw, ph),
        save_previews=True, log=lambda *_: None,
    )
    assert img_shape == (cw, chh)
    assert len(obs) == 3
    assert os.path.isdir(tmp_path / "corners_preview")
    o = obs[0]
    # decoded projector coords must equal the affine map at the corner pixels
    exp_col = (o.cam_pts[:, 0].astype(int) * pw) // cw
    exp_row = (o.cam_pts[:, 1].astype(int) * ph) // chh
    np.testing.assert_allclose(o.proj_pts[:, 0], exp_col, atol=1.0)
    np.testing.assert_allclose(o.proj_pts[:, 1], exp_row, atol=1.0)


def test_undistort_points_roundtrip():
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.calib import undistort as ud

    dist = np.array([-0.28, 0.12, 1e-3, -5e-4, -0.02], np.float32)
    rng = np.random.default_rng(3)
    pts = rng.uniform(-0.6, 0.6, (200, 2)).astype(np.float32)
    distorted = np.asarray(ud.distort_points(jnp.asarray(pts), dist))
    back = np.asarray(ud.undistort_points(jnp.asarray(distorted), dist))
    np.testing.assert_allclose(back, pts, atol=2e-4)


def test_undistort_image_identity_and_shift():
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.calib import undistort as ud

    K = np.array([[200.0, 0, 64], [0, 200.0, 48], [0, 0, 1]])
    img = np.arange(96 * 128, dtype=np.float32).reshape(96, 128) % 251
    out = np.asarray(ud.undistort_image(jnp.asarray(img), K, np.zeros(5)))
    np.testing.assert_allclose(out, img, atol=1e-3)
    stack = np.stack([img, img[::-1]])
    out_s = np.asarray(ud.undistort_stack(stack, K, np.zeros(5)))
    np.testing.assert_allclose(out_s[1], img[::-1], atol=1e-3)
