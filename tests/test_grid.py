"""Grid-hash neighbor engine: exactness vs scipy above the brute-force cutoff."""
import jax.numpy as jnp
import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.ops import grid as gridlib
from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib


@pytest.fixture(scope="module")
def big_cloud():
    rng = np.random.default_rng(3)
    n = 100_000  # above _BRUTE_MAX -> grid path
    pts = np.concatenate([
        rng.normal(0, 30, (n // 2, 3)),
        rng.uniform(-60, 60, (n // 2, 3)),
    ]).astype(np.float32)
    return pts


def test_grid_radius_count_exact(big_cloud):
    pts = big_cloud
    n = pts.shape[0]
    valid = np.ones(n, bool)
    r = 2.0
    c_j = np.asarray(knnlib.radius_count(jnp.asarray(pts), jnp.asarray(valid), r))
    c_n = knnlib.radius_count_np(pts, valid, r)
    # boundary-epsilon ties only
    assert (c_j == c_n).mean() > 0.999
    assert np.abs(c_j - c_n).max() <= 2


def test_grid_knn_mostly_exact(big_cloud):
    pts = big_cloud
    n = pts.shape[0]
    valid = np.ones(n, bool)
    idx_j, d2_j = knnlib.knn(jnp.asarray(pts), jnp.asarray(valid), 10)
    idx_n, d2_n = knnlib.knn_np(pts, valid, 10)
    dj = np.sqrt(np.asarray(d2_j))
    dn = np.sqrt(d2_n)
    finite = np.isfinite(dj)
    assert finite.mean() > 0.999  # candidate sets nearly always fill k
    # exact on the bulk; the sparse tail (k-th neighbor beyond 2 cell rings)
    # may overestimate — the documented, outlier-filter-friendly direction
    ok = finite & np.isfinite(dn)
    diff = np.abs(dj[ok] - dn[ok])
    assert (diff < 1e-3).mean() > 0.995
    assert (dj[ok] + 1e-3 >= dn[ok]).all()  # never underestimates


def test_grid_masked_points_never_neighbors(big_cloud):
    pts = big_cloud.copy()
    valid = np.ones(pts.shape[0], bool)
    valid[::7] = False
    grid = gridlib.build_grid(jnp.asarray(pts), jnp.asarray(valid), 2.0)
    idx, d2 = gridlib.grid_knn(grid, 6)
    idx = np.asarray(idx)
    d2 = np.asarray(d2)
    hit = np.isfinite(d2)
    assert valid[idx[hit]].all()  # no invalid point ever appears as a neighbor


def test_grid_dense_cell_shrink():
    rng = np.random.default_rng(0)
    # 70k points crammed into a tiny box: occupancy forces cell shrink + rings
    pts = rng.uniform(0, 4.0, (70_000, 3)).astype(np.float32)
    valid = np.ones(70_000, bool)
    c_j = np.asarray(knnlib.radius_count(jnp.asarray(pts), jnp.asarray(valid), 1.0))
    c_n = knnlib.radius_count_np(pts, valid, 1.0)
    # dense case: counts in the thousands; allow tiny relative slack for
    # boundary ties but the structure must be exact
    rel = np.abs(c_j - c_n) / np.maximum(c_n, 1)
    assert np.median(rel) < 1e-3
    assert (rel < 0.01).mean() > 0.999


def test_grid_queries_raise_on_accelerator_backends(big_cloud, monkeypatch):
    # round-3 verdict weak #6: the bucket gathers crash the TPU runtime
    # (worker fault, not an exception) at merge-cloud shapes — the query
    # entry points must refuse accelerator backends LOUDLY instead of
    # letting any input shape take the runtime down
    import jax

    pts = big_cloud[:4096]  # tiny grid: only the gate is under test
    valid = np.ones(len(pts), bool)
    g = gridlib.build_grid(jnp.asarray(pts), jnp.asarray(valid), 4.0)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(RuntimeError, match="host-only"):
        gridlib.grid_knn(g, 8)
    with pytest.raises(RuntimeError, match="host-only"):
        gridlib.grid_radius_count(g, 4.0)
    with pytest.raises(RuntimeError, match="host-only"):
        gridlib.grid_query_knn(g, jnp.asarray(pts[:64]), 1)


def test_knn_dense_approx_matches_exact(big_cloud):
    # the accelerator large-N dispatch (dense rows + approx_min_k); on the
    # CPU test backend approx_min_k is exact, and semantics (masking,
    # self-exclusion, clamping, chunk padding) must match knn_np regardless
    pts = big_cloud[:12_000]  # small enough for the 1-core CPU suite; spans
    n = pts.shape[0]          # multiple query chunks, hitting padding seams
    valid = np.ones(n, bool)
    valid[::11] = False
    idx_j, d2_j = knnlib.knn_dense_approx(jnp.asarray(pts), jnp.asarray(valid),
                                          8, recall_target=1.0)
    idx_n, d2_n = knnlib.knn_np(pts, valid, 8)
    dj = np.sqrt(np.maximum(np.asarray(d2_j), 0))[valid]
    dn = np.sqrt(d2_n)[valid]
    assert np.isfinite(dj).all()
    np.testing.assert_allclose(dj, dn, atol=1e-2)
    assert valid[np.asarray(idx_j)[valid]].all()  # invalid never a neighbor
    assert (np.asarray(idx_j)[valid] != np.arange(n)[valid][:, None]).all()


def test_knn_np_k1_and_single_valid_shapes():
    """scipy squeezes the k axis at kk == 1; knn_np must restore (n, k)
    on the correct side (a bare atleast_2d silently TRANSPOSED the k=1
    fast path, and the degenerate fill loop IndexError'd with exactly
    one valid point — both caught by review, r5)."""
    rng = np.random.default_rng(11)
    pts = rng.normal(size=(6, 3)).astype(np.float32)
    # k=1 without self-exclusion: nearest neighbor IS the point itself
    idx, d2 = knnlib.knn_np(pts, None, 1, exclude_self=False)
    assert idx.shape == (6, 1) and d2.shape == (6, 1)
    np.testing.assert_array_equal(idx[:, 0], np.arange(6))
    np.testing.assert_allclose(d2[:, 0], 0.0, atol=1e-10)
    # one valid point among six: every row's only candidate is that point
    valid = np.zeros(6, bool)
    valid[2] = True
    idx, d2 = knnlib.knn_np(pts, valid, 3)
    assert idx.shape == (6, 3) and d2.shape == (6, 3)
    # invalid rows' only candidate is the one valid point (repeated fill);
    # the valid row excludes itself leaving nothing -> inf distances
    assert (idx[~valid] == 2).all()
    assert np.isinf(d2[2]).all()
