"""Sharded scan pipeline on the 8-virtual-device CPU mesh: results must match
the single-device flagship model, and collectives must produce global stats."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.models.scanner import SLScanner
from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.parallel import mesh as meshlib
from structured_light_for_3d_model_replication_tpu.parallel.scan import (
    build_sharded_scan_step,
)
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn


@pytest.fixture(scope="module")
def views():
    rig = syn.default_rig(cam_size=(64, 48), proj_size=(64, 32))
    scene = syn.sphere_on_background(depth=420, radius=70)
    frames = []
    for ang in range(0, 360, 90):  # 4 views
        s = scene.transformed(syn.rotate_y(ang), np.zeros(3))
        f, _ = syn.render_scene(rig, s)
        frames.append(f)
    return rig, np.stack(frames)  # [4, F, 48, 64]


def test_mesh_shapes():
    m = meshlib.make_mesh()
    assert m.devices.size == 8 and m.axis_names == ("data", "model")
    m2 = meshlib.make_mesh(n_model=4)
    assert m2.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        meshlib.make_mesh(n_data=3, n_model=3)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (1, 8)])
def test_sharded_matches_single_device(views, shape):
    rig, frames_v = views
    calib = rig.calibration()
    pw, ph = rig.proj_size
    cw, ch = rig.cam_size
    v = frames_v.shape[0]

    scanner = SLScanner(calib, rig.cam_size, rig.proj_size, row_mode=1)
    ref = scanner.forward_views(frames_v, thresh_mode="manual")

    m = meshlib.make_mesh(n_data=shape[0], n_model=shape[1])
    step = build_sharded_scan_step(m, proj_size=rig.proj_size, row_mode=1)
    rays_hw = np.asarray(scanner.rays).reshape(ch, cw, 3)
    shadow = jnp.full((v,), 40.0, jnp.float32)
    contrast = jnp.full((v,), 10.0, jnp.float32)
    cloud, stats = step(jnp.asarray(frames_v), jnp.asarray(rays_hw), scanner.oc,
                        scanner.plane_col, scanner.plane_row, shadow, contrast)

    # row_mode=1 keeps pixel-slot ordering: global result must match exactly
    np.testing.assert_array_equal(np.asarray(cloud.valid),
                                  np.asarray(ref.valid).reshape(v, -1))
    np.testing.assert_allclose(np.asarray(cloud.points),
                               np.asarray(ref.points).reshape(v, -1, 3), atol=1e-3)

    n_valid_ref = int(np.asarray(ref.valid).sum())
    assert int(stats["n_valid"]) == n_valid_ref
    pts = np.asarray(ref.points).reshape(-1, 3)
    ok = np.asarray(ref.valid).reshape(-1)
    np.testing.assert_allclose(np.asarray(stats["centroid"]), pts[ok].mean(0),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(stats["bb_min"]), pts[ok].min(0), atol=1e-3)
    np.testing.assert_allclose(np.asarray(stats["bb_max"]), pts[ok].max(0), atol=1e-3)


def test_merge_360_mesh_path_matches_single_device(rng):
    """The integrated multi-chip merge (merge_360(mesh=...)): pair
    registration sharded across the mesh + slab-sharded postprocess must
    land on the same surface as the single-device merge (transforms differ
    at RNG-key level — per-device key folding — so parity is geometric,
    not bitwise)."""
    from structured_light_for_3d_model_replication_tpu.config import MergeConfig
    from structured_light_for_3d_model_replication_tpu.models import (
        reconstruction as rec,
    )

    base = np.concatenate([
        rng.normal(0, 18, (4000, 3)),
        rng.normal((35, 0, 0), 10, (2000, 3)),
    ]).astype(np.float32)
    clouds = []
    for ang in [0.0, 12.0, 24.0, 36.0]:
        R = np.asarray(syn.rotate_y(ang), np.float32)
        w = base @ R.T
        vis = w[:, 2] < np.percentile(w[:, 2], 70)
        clouds.append((w[vis].astype(np.float32),
                       np.full((int(vis.sum()), 3), 128, np.uint8)))

    cfg = MergeConfig(voxel_size=2.0, ransac_trials=1024, icp_iters=15,
                      final_voxel=1.0, outlier_nb=10)
    mesh = meshlib.make_mesh(n_data=8, n_model=1)
    p_m, c_m, T_m = rec.merge_360(clouds, cfg, log=lambda m: None, mesh=mesh)
    p_s, c_s, T_s = rec.merge_360(clouds, cfg, log=lambda m: None)
    assert len(T_m) == 4 and np.isfinite(p_m).all()
    assert len(p_m) == len(c_m)
    # both merges sit on the same surface
    d = rec.chamfer_distance(p_m, p_s)
    assert d < 2.0 * cfg.voxel_size, d


def test_scanner_forward_matches_ops(views):
    rig, frames_v = views
    calib = rig.calibration()
    pw, ph = rig.proj_size
    from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri

    scanner = SLScanner(calib, rig.cam_size, rig.proj_size, row_mode=1)
    got = scanner.forward(frames_v[0], thresh_mode="manual")
    res = gc.decode_stack_np(frames_v[0], n_cols=pw, n_rows=ph, thresh_mode="manual")
    want = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                              calib, row_mode=1)
    np.testing.assert_array_equal(np.asarray(got.valid), want.valid)
    ok = want.valid
    assert np.abs(np.asarray(got.points)[ok] - want.points[ok]).max() < 1e-3
