"""AndroidCameraClient against a FakeAndroidDevice implementing the
conformance spec (docs/android_protocol.md — derived from the reference's
CameraHostServer.kt / Camera2Controller.kt). The fake reproduces the real
app's silently-ignores-unknown-keys behavior, so a client emitting wrong
wire key names fails these tests instead of no-opping on a real phone."""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from structured_light_for_3d_model_replication_tpu.acquire.android import (
    AndroidCameraClient,
    CameraSettings,
)

# the exact settings key set the reference app parses
# (Camera2Controller.kt:167-185)
SPEC_KEYS = {"camera_id", "jpeg_quality", "ae_mode", "exposure_time_ns",
             "iso", "exposure_compensation", "af_mode", "focus_distance",
             "awb_mode", "eis", "ois", "zoom_ratio"}

_JPEG = b"\xff\xd8\xff\xe0" + b"\x00" * 64 + b"\xff\xd9"


class _FakeHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # pragma: no cover
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        if self.path == "/status":
            self._json({"ok": True, "device": "FakePixel", "sdkInt": 34,
                        "activeCameraId": "0", "cameraIds": ["0", "1"],
                        "port": self.server.server_address[1]})
        elif self.path == "/capabilities":
            self._json({"cameras": [{
                "cameraId": "0", "facing": "back", "rawSupported": False,
                "aeCompensationRange": [-24, 24], "isoRange": [50, 6400],
                "exposureTimeNsRange": [1000, 1_000_000_000],
                "maxDigitalZoom": 8.0}], "notes": "fake"})
        else:
            self.send_error(404)

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", "0"))
        raw = self.rfile.read(n) if n else b""
        body = json.loads(raw) if raw else {}
        if self.path == "/settings":
            # the real app's `as?` casts ignore unknown keys without error;
            # the fake RECORDS them so tests can assert none were sent
            self.server.seen_keys.update(body)          # type: ignore
            applied = dict(self.server.applied)          # type: ignore
            for k in SPEC_KEYS & set(body):
                applied[_CAMEL[k]] = body[k]
            self.server.applied = applied                # type: ignore
            self._json({"ok": True, "applied": applied})
        elif self.path == "/capture/jpeg":
            self.server.seen_keys.update(body)           # type: ignore
            meta = {k: self.server.applied.get(k) for k in  # type: ignore
                    ("cameraId", "jpegQuality", "aeMode", "exposureTimeNs",
                     "iso", "afMode", "focusDistance", "zoomRatio")}
            self.send_response(200)
            self.send_header("Content-Type", "image/jpeg")
            self.send_header("X-Capture-Meta", json.dumps(meta))
            self.send_header("Content-Length", str(len(_JPEG)))
            self.end_headers()
            self.wfile.write(_JPEG)
        else:
            self.send_error(404)


_CAMEL = {"camera_id": "cameraId", "jpeg_quality": "jpegQuality",
          "ae_mode": "aeMode", "exposure_time_ns": "exposureTimeNs",
          "iso": "iso", "exposure_compensation": "exposureCompensation",
          "af_mode": "afMode", "focus_distance": "focusDistance",
          "awb_mode": "awbMode", "eis": "eis", "ois": "ois",
          "zoom_ratio": "zoomRatio"}


@pytest.fixture()
def fake_device():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FakeHandler)
    httpd.seen_keys = set()           # type: ignore[attr-defined]
    httpd.applied = {"cameraId": "0", "jpegQuality": 95}  # type: ignore
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_status_and_capabilities(fake_device):
    c = AndroidCameraClient("127.0.0.1", fake_device.server_address[1])
    assert c.reachable()
    st = c.status()
    assert st["ok"] and st["cameraIds"] == ["0", "1"]
    caps = c.capabilities()
    assert caps["cameras"][0]["isoRange"] == [50, 6400]


def test_settings_emit_only_reference_wire_keys(fake_device):
    # the key-name regression class: a wrong name (e.g. 'exposure_ns')
    # no-ops silently on the real app; assert every emitted key is one the
    # reference actually parses, and that the manual values land
    c = AndroidCameraClient("127.0.0.1", fake_device.server_address[1])
    s = CameraSettings(exposure_ns=8_333_333, iso=100, focus_diopters=2.5,
                       awb_mode="daylight", zoom=1.5, stabilization=True,
                       jpeg_quality=97, ae_mode="off", af_mode="off",
                       exposure_compensation=-2, camera_id="0")
    resp = c.apply_settings(s)
    assert resp["ok"]
    unknown = fake_device.seen_keys - SPEC_KEYS
    assert not unknown, f"keys the device app would ignore: {unknown}"
    ap = resp["applied"]
    assert ap["exposureTimeNs"] == 8_333_333
    assert ap["focusDistance"] == 2.5
    assert ap["zoomRatio"] == 1.5
    assert ap["eis"] is True and ap["ois"] is True
    assert ap["jpegQuality"] == 97 and ap["iso"] == 100


def test_mixed_stabilization_state_expressible():
    # EIS's frame warp corrupts correspondence, OIS doesn't — ois-only must
    # be expressible, and explicit flags win over the convenience bool
    d = CameraSettings(eis=False, ois=True).to_dict()
    assert d == {"eis": False, "ois": True}
    d = CameraSettings(eis=False, stabilization=True).to_dict()
    assert d["eis"] is False and d["ois"] is True


def test_capture_jpeg_returns_bytes_and_meta(fake_device, tmp_path):
    c = AndroidCameraClient("127.0.0.1", fake_device.server_address[1])
    c.apply_settings(CameraSettings(iso=200))
    jpeg, meta = c.capture_jpeg()
    assert jpeg.startswith(b"\xff\xd8") and jpeg.endswith(b"\xff\xd9")
    assert meta["iso"] == 200
    # sequencer CaptureFn contract: capture_to_path writes the frame
    out = tmp_path / "frame.jpg"
    meta2 = c.capture_to_path(str(out))
    assert out.read_bytes() == jpeg
    assert meta2["iso"] == 200


def test_unreachable_is_false():
    assert not AndroidCameraClient("127.0.0.1", 1).reachable()


def test_capture_retry_absorbs_injected_transient(fake_device, tmp_path):
    """Resilience: an injected http.capture fault (standing in for a dropped
    Wi-Fi association) is absorbed by the bounded retry and the frame still
    lands — atomically, with no staging debris."""
    from structured_light_for_3d_model_replication_tpu.utils import faults

    c = AndroidCameraClient("127.0.0.1", fake_device.server_address[1],
                            backoff_s=0.0)
    faults.configure("http.capture:transient")
    try:
        out = tmp_path / "frame.jpg"
        c.capture_to_path(str(out))
    finally:
        faults.reset()
    assert c.retry_count == 1
    assert out.read_bytes().startswith(b"\xff\xd8")
    assert [f for f in tmp_path.iterdir() if ".tmp" in f.name] == []


def test_capture_exhausted_budget_raises_and_writes_nothing(fake_device,
                                                            tmp_path):
    from structured_light_for_3d_model_replication_tpu.utils import faults

    c = AndroidCameraClient("127.0.0.1", fake_device.server_address[1],
                            retries=1, backoff_s=0.0)
    faults.configure("http.capture:transientx99")  # outlasts the budget
    try:
        with pytest.raises(faults.TransientFault) as ei:
            c.capture_to_path(str(tmp_path / "frame.jpg"))
    finally:
        faults.reset()
    assert ei.value._sl3d_attempts == 2  # 1 try + 1 retry
    assert list(tmp_path.iterdir()) == []  # no partial frame, no .tmp


def test_http_4xx_is_permanent_5xx_transient():
    import io
    import urllib.error

    perm = urllib.error.HTTPError("u", 404, "nf", {}, io.BytesIO())
    srv = urllib.error.HTTPError("u", 503, "restarting", {}, io.BytesIO())
    assert not AndroidCameraClient._transient(perm)
    assert AndroidCameraClient._transient(srv)
    assert AndroidCameraClient._transient(urllib.error.URLError("drop"))
