"""Real-accelerator smoke test for the flagship paths.

The round-2 failure class was "tiny-shape Pallas probe passes, flagship-shape
jit crashes at Mosaic lowering" — invisible to the CPU suite (conftest forces
the cpu platform) and only caught when the bench's TPU child died. This test
re-execs in a subprocess WITHOUT the cpu forcing so it sees the ambient
backend, and drives the exact entry points that crashed in round 2 at their
real shapes: SLScanner.forward and forward_views at 1080p, plus nn1 /
radius_count at ICP-sized inputs. Skipped (not silently passed) when no
accelerator is attached.
"""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r'''
import json, sys
import numpy as np
import jax
import jax.numpy as jnp

out = {"backend": jax.default_backend()}
if out["backend"] == "cpu":
    print(json.dumps(out))
    sys.exit(0)

# share the bench's persistent executable cache: re-runs skip XLA compiles
# (this box has ONE host core — compiles dominate the first run)
import os
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.getcwd(), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

from structured_light_for_3d_model_replication_tpu.ops import (
    graycode as gc,
    pallas_kernels as pk,
)
from structured_light_for_3d_model_replication_tpu.models.scanner import SLScanner
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

out["pallas"] = pk.pallas_mode()
CAM = (1920, 1080)

# the projector pattern stack itself is a perfectly decodable 1080p capture
frames = jnp.asarray(gc.generate_pattern_stack(CAM[0], CAM[1]))
rig = syn.default_rig(cam_size=CAM, proj_size=CAM)
for plane_eval, run_views in (("table", False), ("quadratic", True)):
    sc = SLScanner(rig.calibration(), CAM, CAM, row_mode=1,
                   plane_eval=plane_eval)
    r1 = sc.forward(frames, thresh_mode="manual")
    jax.block_until_ready(r1.points)
    out[f"forward_{plane_eval}_finite"] = bool(
        np.isfinite(np.asarray(r1.points)).all())
    if not run_views:  # the batched path once is enough (compile cost)
        continue
    v2 = jnp.stack([frames, frames])
    r2 = sc.forward_views(v2, thresh_mode="manual")
    jax.block_until_ready(r2.points)
    out[f"views_{plane_eval}_shape_ok"] = (r2.points.shape[0] == 2
                                           and bool((np.asarray(r2.valid[0])
                                                     == np.asarray(r1.valid)).all()))

# kernels at ICP/outlier-filter shapes
pts = jnp.asarray(np.random.default_rng(0).normal(
    scale=50.0, size=(8192, 3)).astype(np.float32))
idx, d2 = pk.nn1(pts + 0.001, pts)
jax.block_until_ready(d2)
out["nn1_finite"] = bool(np.isfinite(np.asarray(d2)).all())
cnt = pk.radius_count_pallas(pts, None, 5.0)
jax.block_until_ready(cnt)
out["radius_nonneg"] = int(np.asarray(cnt).min()) >= 0

# statistical outlier at merged-cloud scale (> knn's 65536 brute gate): the
# round-3 bench TPU child died here — the grid-hash knn path faulted the TPU
# runtime at H=512k/M=100/rings=2, killing the whole merge phase
from structured_light_for_3d_model_replication_tpu.ops import pointcloud as pc
from structured_light_for_3d_model_replication_tpu.ops import knn as knnlib
big = jnp.asarray(np.random.default_rng(1).normal(
    scale=60.0, size=(170_000, 3)).astype(np.float32))
big_valid = jnp.ones(big.shape[0], bool)
mask = np.asarray(pc.statistical_outlier_mask(big, big_valid, 20, 2.0))
out["outlier_merge_scale_ok"] = bool(0.5 < mask.mean() <= 1.0)
cnt = np.asarray(knnlib.radius_count(big, big_valid, 5.0))
out["radius_merge_scale_ok"] = bool((cnt >= 0).all() and cnt.max() > 0)

# the voxelized ring probe IS the accelerator production path for merged
# clouds (the bench's postprocess passes the final-voxel hint) — exercise
# it here at scale so an accelerator-only fault can't hide behind the CPU
# parity tests (ADVICE r3: it landed during a tunnel outage, CPU-validated
# only), and pin its agreement with the opt-in approximate route
pv, cv, vv = pc.voxel_downsample(big, jnp.zeros(big.shape, jnp.uint8),
                                 big_valid, 3.0)
m_exact = np.asarray(pc.statistical_outlier_mask(pv, vv, 20, 2.0,
                                                 voxelized_cell=3.0))
m_apx = np.asarray(pc.statistical_outlier_mask(pv, vv, 20, 2.0,
                                               approximate=True))
nv = np.asarray(vv)
out["outlier_voxelized_probe_ok"] = bool(
    0.5 < m_exact[nv].mean() <= 1.0
    and (m_exact[nv] == m_apx[nv]).mean() > 0.99)

# the Pallas bisection kernel IS the voxelized engine wherever Mosaic
# compiles (the m_exact above already ran it on this backend) — pin the
# COMPILED kernel's statistics against the host cKDTree twin exactly:
# the selection is by in-VMEM difference distances, so any hardware
# surprise (rounding, lowering) must surface here, not in a bench line
pv_np = np.asarray(pv)[nv]
m_twin = pc.statistical_outlier_mask_np(pv_np, np.ones(len(pv_np), bool),
                                        20, 2.0)
out["outlier_bisect_vs_twin_agree"] = float(
    (m_exact[nv] == m_twin).mean())
out["outlier_bisect_twin_ok"] = bool(
    out["outlier_bisect_vs_twin_agree"] >= 0.9999)

# bit-exact export on the ambient backend: the path now fetches the
# integer maps and computes through the NumPy twin (TPU f32 divide/rsqrt
# round differently from IEEE, so device-eager could never honor the
# contract — measured false on real TPU, r4); this check guards the
# plumbing (device arrays in, byte-identical cloud out)
from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
rng_b = np.random.default_rng(4)
cm = rng_b.integers(0, 1920, (270, 480)).astype(np.int32)
rm = rng_b.integers(0, 1080, (270, 480)).astype(np.int32)
mk = rng_b.random((270, 480)) > 0.5
tx = rng_b.integers(0, 256, (270, 480, 3)).astype(np.uint8)
calib_b = syn.default_rig(cam_size=(480, 270)).calibration()
# DEVICE arrays in: the check must exercise the fetch-to-host plumbing
c_bx = tri.triangulate(jnp.asarray(cm), jnp.asarray(rm), jnp.asarray(mk),
                       jnp.asarray(tx), calib_b, row_mode=1, bitexact=True)
c_np = tri.triangulate_np(cm, rm, mk, tx, calib_b, row_mode=1)
out["bitexact_on_device"] = bool(
    (np.asarray(c_bx.points) == c_np.points).all()
    and (np.asarray(c_bx.valid) == c_np.valid).all())

# on-device decode+triangulate CHAMFER vs the bit-exact NumPy twin on a
# real rendered scene (r4 regression class: the one recovery window
# measured 0.064 mm — 500x round 3 — from the fused kernel's plane
# normalization; the fix landed CPU-validated only. Pin BOTH lowerings
# here so device f32 divide/rsqrt behavior is asserted on hardware.)
from structured_light_for_3d_model_replication_tpu.models.reconstruction import (
    chamfer_distance,
)
MCAM = (512, 256)   # tile-aligned so the fused lowering is capable too
mrig = syn.default_rig(cam_size=MCAM, proj_size=MCAM)
vf, _ = syn.render_scene(mrig, syn.sphere_on_background())
dec_np = gc.decode_stack_np(np.asarray(vf), n_cols=MCAM[0], n_rows=MCAM[1],
                            thresh_mode="manual")
cl_np = tri.triangulate_np(dec_np.col_map, dec_np.row_map, dec_np.mask,
                           dec_np.texture, mrig.calibration(), row_mode=1)
np_pts, _ = tri.compact_cloud(cl_np)
sc2 = SLScanner(mrig.calibration(), MCAM, MCAM, row_mode=1,
                plane_eval="quadratic")
stack1 = jnp.asarray(vf)[None]
r_jnp = sc2.forward_views(stack1, thresh_mode="manual", use_fused=False)
dev_pts = np.asarray(r_jnp.points[0])[np.asarray(r_jnp.valid[0])]
out["chamfer_n_dev"] = int(len(dev_pts))
out["chamfer_mm_jnp"] = float(chamfer_distance(dev_pts, np_pts)) \
    if len(dev_pts) else None
if sc2._fuse_capable(stack1):
    r_f = sc2.forward_views(stack1, thresh_mode="manual", use_fused=True)
    f_pts = np.asarray(r_f.points[0])[np.asarray(r_f.valid[0])]
    out["chamfer_mm_fused"] = float(chamfer_distance(f_pts, np_pts)) \
        if len(f_pts) else None

# kabsch orthogonality ON DEVICE: the TPU's bf16-class default matmul
# precision bent rotations by 2e-2 before the precision pins; the CPU
# suite cannot see that class of error
from structured_light_for_3d_model_replication_tpu.ops import registration as reg
rng_k = np.random.default_rng(3)
pk_ = jnp.asarray(rng_k.normal(size=(512, 3, 3)).astype(np.float32) * 50)
qk_ = jnp.asarray(rng_k.normal(size=(512, 3, 3)).astype(np.float32) * 50)
Rk = np.asarray(reg.kabsch(pk_, qk_))[:, :3, :3]
orth_err = float(np.abs(np.einsum("tij,tkj->tik", Rk, Rk)
                        - np.eye(3)).max())
out["kabsch_orthogonal_on_device"] = bool(orth_err < 1e-4)

# meshing path (Poisson grid solve + surface nets) at a modest depth: the
# grid-path lesson is that accelerator-only faults hide from the CPU suite
from structured_light_for_3d_model_replication_tpu.config import MeshConfig
from structured_light_for_3d_model_replication_tpu.models.meshing import (
    reconstruct_mesh,
)
rng_m = np.random.default_rng(2)
dirs = rng_m.normal(size=(20_000, 3)).astype(np.float32)
dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
sphere = 40.0 * dirs + np.float32([0, 0, 400])
verts, faces = reconstruct_mesh(
    sphere, cfg=MeshConfig(mode="watertight", depth=7))
out["mesh_tpu_ok"] = bool(len(verts) > 100 and len(faces) > 100
                          and np.isfinite(np.asarray(verts)).all())
print(json.dumps(out))
'''


def test_flagship_paths_on_accelerator():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    # one TPU client at a time, repo-wide: a manual run of this test while
    # a validation session/bench is mid-claim is the concurrent-client
    # wedge. (Under tools/tpu_session.py the parent holds the lock and
    # sets the pass-through env.) Held for the test's lifetime; the flock
    # dies with the process.
    from structured_light_for_3d_model_replication_tpu.utils import tpulock

    lock = tpulock.acquire_tpu_lock(_ROOT, timeout=60)  # noqa: F841
    if lock is None:
        pytest.skip("another TPU client holds .tpu_lock")
    # fast preflight: a wedged accelerator tunnel hangs inside backend init
    # OR inside the first device execution (both signatures observed; the
    # shared probe runs init + one tiny op) — skip rather than stall
    from structured_light_for_3d_model_replication_tpu.utils.preflight import (
        accelerator_preflight,
    )

    # 60 s probe, not the full 180: a healthy tunnel answers init+one-op in
    # ~5-10 s, and this gate only decides skip-vs-run — during a wedge the
    # full-length probe burned 3 min of EVERY suite run before skipping
    status, detail = accelerator_preflight(timeout=60.0, cwd=_ROOT)
    if status != "ok":
        pytest.skip(f"accelerator preflight {status}: {detail}")
    proc = subprocess.run([sys.executable, "-c", _SCRIPT],
                          capture_output=True, text=True, timeout=1800,
                          env=env, cwd=_ROOT)
    assert proc.returncode == 0, (
        f"accelerator smoke subprocess died:\n{proc.stderr[-4000:]}")
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    if out["backend"] == "cpu":
        pytest.skip("no accelerator backend attached")
    for key in ("forward_table_finite", "forward_quadratic_finite",
                "views_quadratic_shape_ok",
                "nn1_finite", "radius_nonneg", "outlier_merge_scale_ok",
                "outlier_voxelized_probe_ok", "outlier_bisect_twin_ok",
                "radius_merge_scale_ok", "mesh_tpu_ok",
                "kabsch_orthogonal_on_device"):
        assert out.get(key) is True, (key, out)
    # hard contract: bitexact export computes through the NumPy twin on
    # host, so it must hold on ANY backend (device-eager could not — TPU
    # f32 divide/rsqrt rounding, measured false on the real chip, r4)
    assert out.get("bitexact_on_device") is True, out
    # hard contract (VERDICT r4 #3): on-device chamfer vs the NumPy twin
    # must be sub-micron-class on a real rendered scene — the r4 window's
    # 0.064 mm fused-kernel regression must be shown dead on hardware,
    # for BOTH lowerings (the fused one stays reachable via SLSCAN_PALLAS)
    assert out.get("chamfer_n_dev", 0) > 1000, out
    assert out.get("chamfer_mm_jnp") is not None \
        and out["chamfer_mm_jnp"] < 1e-3, out
    if "chamfer_mm_fused" in out:
        assert out.get("chamfer_mm_fused") is not None \
            and out["chamfer_mm_fused"] < 1e-3, out
