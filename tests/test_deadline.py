"""ISSUE 7: deadlines everywhere — monotonic Deadline/CancelToken units,
stall/slow fault-grammar kinds, the lane watchdog's soft/hard breach
protocol, bounded writeback drain, token-cancellation propagation, the
stall-at-every-site matrix (the analog of PR 3's crash-at-every-site),
the run-budget abort, and the report's stall ledger."""
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from structured_light_for_3d_model_replication_tpu.cli import main as cli_main
from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.io import ply as plyio
from structured_light_for_3d_model_replication_tpu.pipeline import (
    report as replib,
)
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import (
    deadline as dl,
)
from structured_light_for_3d_model_replication_tpu.utils import faults

STEPS = ("statistical",)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("dlds"))
    rc = cli_main(["synth", root, "--views", "3",
                   "--cam", "160x120", "--proj", "128x64"])
    assert rc == 0
    return root


def _cfg(**dl_overrides) -> Config:
    # deliberately cheap numerics: these tests assert TERMINATION and
    # failure-routing, not merge quality — the parity suites own that
    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = 128, 64
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 256
    cfg.merge.icp_iters = 5
    cfg.mesh.depth = 4
    cfg.mesh.density_trim_quantile = 0.0
    for k, v in dl_overrides.items():
        setattr(cfg.deadlines, k, v)
    return cfg


@pytest.fixture(autouse=True)
def _clean_fault_and_ctx_state():
    yield
    faults.reset()
    dl.deactivate(None)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def test_deadline_monotonic_math_and_classification():
    d = dl.Deadline.after(0.2, "unit")
    assert d is not None and not d.expired
    assert 0.0 < d.remaining() <= 0.2
    d.check()  # not expired: no raise
    # the 0 == unbounded convention
    assert dl.Deadline.after(0.0) is None
    assert dl.Deadline.after(None) is None
    expired = dl.Deadline.after(1e-6)
    time.sleep(0.01)
    assert expired.expired
    with pytest.raises(dl.DeadlineExceeded):
        expired.check("tiny op")
    # classification contract: deadline hits are transient (scheduling
    # outcomes), cancellations are permanent (abandon, never retry)
    assert faults.is_transient(dl.DeadlineExceeded("x")) is True
    assert faults.is_transient(dl.Cancelled("x")) is False
    assert isinstance(dl.DeadlineExceeded("x"), TimeoutError)


def test_cancel_token_level_semantics():
    t = dl.CancelToken()
    assert not t.cancelled
    t.check()  # no raise while low
    t.cancel("stop it")
    assert t.cancelled and t.reason == "stop it"
    with pytest.raises(dl.Cancelled, match="stop it"):
        t.check("an op")
    t.clear()  # the watchdog's progress-resumed path lowers the level
    assert not t.cancelled
    t.check()


def test_wait_future_bounds_and_disambiguates_timeouts():
    with ThreadPoolExecutor(max_workers=1) as pool:
        # bounded: a slow future raises DeadlineExceeded at the budget
        slow = pool.submit(time.sleep, 1.0)
        t0 = time.monotonic()
        with pytest.raises(dl.DeadlineExceeded):
            dl.wait_future(slow, 0.1, what="slow sleep")
        assert time.monotonic() - t0 < 0.9
        # a work function that ITSELF raises TimeoutError must propagate
        # as that error, never loop as an unexpired poll window

        def raises_timeout():
            raise TimeoutError("from the work")

        bad = pool.submit(raises_timeout)
        with pytest.raises(TimeoutError, match="from the work"):
            dl.wait_future(bad, 5.0)
        # results pass through; settle-wait never raises the work error
        ok = pool.submit(lambda: 42)
        assert dl.wait_future(ok, 5.0) == 42
        assert dl.wait_settled(bad, 1.0) is True
        still = pool.submit(time.sleep, 0.5)
        assert dl.wait_settled(still, 0.05) is False


# ---------------------------------------------------------------------------
# fault grammar: stall / slow
# ---------------------------------------------------------------------------

def test_fault_grammar_parses_stall_and_slow():
    r = faults.FaultRule.parse("register.pair~3->4:stall(2.5)@2x3")
    assert (r.site, r.kind, r.match) == ("register.pair", "stall", "3->4")
    assert r.duration_s == 2.5 and r.arm_at == 2 and r.times == 3
    r2 = faults.FaultRule.parse("frame.load:slow(0.25)")
    assert r2.kind == "slow" and r2.block_s == 0.25 and r2.times == 1
    r3 = faults.FaultRule.parse("cache.get:stall")
    assert r3.block_s == faults.STALL_DEFAULT_S
    with pytest.raises(ValueError):  # only stall/slow take a duration
        faults.FaultRule.parse("frame.load:transient(2)")


def test_stall_blocks_then_resumes_and_slow_is_a_straggler():
    faults.configure("a.site:stall(0.15),b.site:slow(0.1)")
    t0 = time.monotonic()
    faults.fire("a.site")     # blocks ~0.15s, then returns normally
    stall_wall = time.monotonic() - t0
    assert 0.1 <= stall_wall < 1.0
    t0 = time.monotonic()
    faults.fire("b.site")
    assert 0.05 <= time.monotonic() - t0 < 1.0
    # both fired exactly once and are exhausted (times=1 default)
    assert faults.active_plan().counts() == {"a.site": 1, "b.site": 1}
    faults.fire("a.site")     # no block on the second hit
    assert faults.active_plan().counts()["a.site"] == 1


def test_stall_is_cancel_aware_via_ambient_token():
    ctx = dl.RunContext()
    prev = dl.activate(ctx)
    try:
        faults.configure("a.site:stall(30)")
        threading.Timer(0.1, lambda: ctx.token.cancel("watchdog")).start()
        t0 = time.monotonic()
        with pytest.raises(dl.Cancelled, match="watchdog"):
            faults.fire("a.site")
        assert time.monotonic() - t0 < 5.0
    finally:
        dl.deactivate(prev)


# ---------------------------------------------------------------------------
# the watchdog
# ---------------------------------------------------------------------------

def _wait_for(cond, timeout=5.0):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_watchdog_soft_then_hard_breach_and_recovery(tmp_path):
    token = dl.CancelToken()
    logs = []
    w = dl.Watchdog(0.1, 0.25, token, poll_s=0.02, out_dir=str(tmp_path),
                    run_id="testrun", log=logs.append)
    w.start()
    try:
        # silence -> soft breach first, then hard: token raised + stack
        # dump persisted
        assert _wait_for(lambda: any(b["level"] == "soft"
                                     for b in w.breaches))
        assert _wait_for(lambda: token.cancelled)
        assert any(b["level"] == "hard" for b in w.breaches)
        stalls = tmp_path / "stalls.json"
        assert _wait_for(stalls.exists)
        payload = json.loads(stalls.read_text())
        assert payload["schema"] == dl.STALLS_SCHEMA
        assert payload["run_id"] == "testrun"
        assert payload["breaches"] and payload["thread_stacks"]
        # some thread's stack really is in the dump
        assert any("Thread" in ln or "File" in ln
                   for ln in payload["thread_stacks"])
        # progress resumes -> the cancel LEVEL drops (stall-break, not
        # run abort)
        w.beat("load")
        assert _wait_for(lambda: not token.cancelled)
        assert any("HARD STALL" in m for m in logs)
        assert any("possible stall" in m for m in logs)
    finally:
        w.stop()


def test_watchdog_suspend_covers_barrier_stages():
    token = dl.CancelToken()
    w = dl.Watchdog(0.08, 0.0, token, poll_s=0.02)
    w.start()
    try:
        w.suspend()       # a barrier stage: silence is expected
        time.sleep(0.3)
        assert not w.breaches
        w.resume()        # suspended time must not count as silence
        assert _wait_for(lambda: bool(w.breaches))  # real silence now does
    finally:
        w.stop()


# ---------------------------------------------------------------------------
# bounded writeback drain (satellite)
# ---------------------------------------------------------------------------

def test_writeback_drain_is_bounded_by_timeout(tmp_path):
    import numpy as np

    faults.configure("ply.write~stuck:stall(1.5)")
    q = plyio.WritebackQueue()
    try:
        pts = np.zeros((4, 3), np.float32)
        q.submit(str(tmp_path / "stuck.ply"), pts)
        q.submit(str(tmp_path / "fine.ply"), pts)
        t0 = time.monotonic()
        with pytest.raises(plyio.PlyWriteError) as ei:
            q.drain(timeout_s=0.3)
        wall = time.monotonic() - t0
        assert wall < 1.2, "drain must not wait out the stalled writer"
        assert "stuck.ply" in str(ei.value)
        assert "pending" in str(ei.value)
        errors = dict(ei.value.errors)
        assert isinstance(errors[str(tmp_path / "stuck.ply")],
                          dl.DeadlineExceeded)
    finally:
        faults.reset()
        q.close(wait=True, timeout_s=0.2)
    # unbounded drain still works once the stall resolves
    q2 = plyio.WritebackQueue()
    with q2:
        import numpy as np

        f = q2.submit(str(tmp_path / "ok.ply"), np.zeros((4, 3), "f4"))
        assert q2.drain() == [str(tmp_path / "ok.ply")]
        assert f.done()


# ---------------------------------------------------------------------------
# token propagation: the acquire sweep stops cleanly
# ---------------------------------------------------------------------------

def test_auto_scan_360_cancels_cleanly_between_views(tmp_path):
    from structured_light_for_3d_model_replication_tpu.acquire.autoscan import (
        auto_scan_360,
    )
    from structured_light_for_3d_model_replication_tpu.acquire.turntable import (
        LoopbackTurntable,
    )

    class Seq:
        def capture_scan(self, view_dir):
            os.makedirs(view_dir, exist_ok=True)

    token = dl.CancelToken()
    logs = []

    def progress(info):
        if info["view"] == 2:
            token.cancel("operator hit stop")

    res = auto_scan_360(Seq(), LoopbackTurntable(), str(tmp_path / "sweep"),
                        turns=6, step_deg=60.0, progress=progress,
                        token=token, log=logs.append)
    # views 1 and 2 captured; the token raised during view 2's progress
    # stops the sweep BEFORE view 3 — nothing half-captures
    assert len(res.view_dirs) == 2
    assert any("cancelled" in m and "operator hit stop" in m for m in logs)


# ---------------------------------------------------------------------------
# pipeline integration: stall-at-every-site matrix + run budget
# ---------------------------------------------------------------------------

_MATRIX_SITES = ["frame.load", "compute.view", "ply.write~merged",
                 "cache.get", "cache.put", "register.pair"]


@pytest.mark.parametrize("site", _MATRIX_SITES)
def test_stall_at_any_site_terminates_the_run(dataset, tmp_path, site):
    """ISSUE 7 acceptance (the crash-at-every-site analog): a seeded stall
    at EVERY existing fault site terminates the run — quarantine+DEGRADED
    where a bounded per-item wait guards the site, clean completion where
    the stall self-resolves inside its bound — never a hang. Worker-thread
    sites additionally pin down the deterministic outcome."""
    out = str(tmp_path / "out")
    calib = os.path.join(dataset, "calib.mat")
    cfg = _cfg(load_s=0.3, write_s=0.5, register_s=0.6, drain_s=1.0,
               soft_stall_s=3.0, hard_stall_s=10.0, watchdog_poll_s=0.1)
    faults.configure(f"{site}:stall(0.8)")
    t0 = time.monotonic()
    try:
        rep = stages.run_pipeline(calib, dataset, out, cfg=cfg,
                                  steps=STEPS, log=lambda m: None)
    finally:
        faults.reset()
    wall = time.monotonic() - t0
    assert wall < 120.0, f"stall at {site} cost {wall:.0f}s — unbounded?"
    # the run terminated with consistent artifacts either way
    assert rep.stl_path and os.path.getsize(rep.stl_path) > 0
    assert plyio.read_ply(rep.merged_ply)["points"].shape[0] > 0
    if rep.degraded:
        assert rep.manifest_path and os.path.exists(rep.manifest_path)
        for r in rep.failures:
            assert r.error_type in ("DeadlineExceeded", "Cancelled")
    if site == "frame.load":
        # a stalled prefetch is guarded by a bounded worker-future wait:
        # deterministic DeadlineExceeded quarantine, run DEGRADED
        assert rep.degraded and len(rep.failures) == 1
        assert rep.failures[0].error_type == "DeadlineExceeded"
        assert rep.failures[0].stage == "load"
        assert rep.views_computed == 2


def test_slow_straggler_completes_clean_and_trips_only_soft(dataset,
                                                            tmp_path):
    out = str(tmp_path / "out")
    calib = os.path.join(dataset, "calib.mat")
    cfg = _cfg(soft_stall_s=60.0, hard_stall_s=300.0)
    faults.configure("frame.load:slow(0.3)")
    try:
        rep = stages.run_pipeline(calib, dataset, out, cfg=cfg,
                                  steps=STEPS, log=lambda m: None)
    finally:
        faults.reset()
    assert not rep.degraded and rep.failed == []
    assert not os.path.exists(os.path.join(out, "stalls.json"))


def test_run_budget_aborts_with_manifest(dataset, tmp_path):
    out = str(tmp_path / "out")
    calib = os.path.join(dataset, "calib.mat")
    cfg = _cfg()
    cfg.pipeline.run_budget_s = 0.05
    with pytest.raises(dl.DeadlineExceeded):
        stages.run_pipeline(calib, dataset, out, cfg=cfg, steps=STEPS,
                            log=lambda m: None)
    mpath = os.path.join(out, "failures.json")
    assert os.path.exists(mpath)
    with open(mpath) as f:
        manifest = json.load(f)
    assert manifest["aborted"] is True
    assert manifest["run_budget_s"] == 0.05
    assert manifest["failures"][0]["error_type"] == "DeadlineExceeded"


def test_disabled_layer_keeps_bare_blocking_waits(dataset, tmp_path):
    """deadlines.enabled=false: no run context is installed, no watchdog
    thread runs, and the run completes exactly as before PR 7."""
    out = str(tmp_path / "out")
    calib = os.path.join(dataset, "calib.mat")
    cfg = _cfg(enabled=False)
    before = {t.name for t in threading.enumerate()}
    rep = stages.run_pipeline(calib, dataset, out, cfg=cfg, steps=STEPS,
                              log=lambda m: None)
    after = {t.name for t in threading.enumerate()}
    assert rep.failed == []
    assert "sl3d-watchdog" not in (after - before)
    assert not os.path.exists(os.path.join(out, "stalls.json"))


# ---------------------------------------------------------------------------
# report: the stall ledger
# ---------------------------------------------------------------------------

def test_report_renders_stall_ledger(tmp_path):
    out = tmp_path / "run"
    out.mkdir()
    lines = [
        {"type": "meta", "schema": "sl3d-trace-v1", "run_id": "r1",
         "t0_unix": 0.0, "host_cpus": 1, "backend": "numpy"},
        {"type": "span", "ev": "lane", "lane": "load", "t": 0.1,
         "dur": 0.5, "th": "w"},
        {"type": "instant", "ev": "lane.heartbeat", "lane": "compute",
         "t": 0.8, "th": "m"},
        {"type": "instant", "ev": "watchdog.stall", "level": "soft",
         "age_s": 1.2, "lanes": {"load": 1.2}, "t": 1.9, "th": "wd"},
        {"type": "instant", "ev": "watchdog.stall", "level": "hard",
         "age_s": 2.4, "lanes": {"load": 2.4}, "t": 3.1, "th": "wd"},
    ]
    with open(out / "trace.jsonl", "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    (out / "stalls.json").write_text(json.dumps({
        "schema": dl.STALLS_SCHEMA, "run_id": "r1",
        "soft_stall_s": 1.0, "hard_stall_s": 2.0,
        "breaches": [{"level": "hard", "age_s": 2.4,
                      "lane_ages": {"load": 2.4}}],
        "thread_stacks": ["Thread 0x1 (sl3d-prefetch):",
                          '  File "x.py", line 1, in f'],
    }))
    a = replib.analyze_run(str(out))
    assert len(a.stall_events) == 2
    assert a.stalls and a.stalls["breaches"]
    assert a.lane_last_beat["load"] == pytest.approx(0.6)
    assert a.lane_last_beat["compute"] == pytest.approx(0.8)
    text = replib.render_report(a)
    assert "stall ledger" in text
    assert "HARD" in text and "thread-stack dump" in text
    assert "last-heartbeat age" in text
    # an INTERRUPTED run (no end marker) still renders it
    assert "INTERRUPTED" in text


def test_report_stall_ledger_clean_line(tmp_path):
    out = tmp_path / "run"
    out.mkdir()
    with open(out / "trace.jsonl", "w") as f:
        f.write(json.dumps({"type": "meta", "schema": "sl3d-trace-v1",
                            "run_id": "r2", "t0_unix": 0.0}) + "\n")
        f.write(json.dumps({"type": "end", "t": 1.0}) + "\n")
    text = replib.render_report(replib.analyze_run(str(out)))
    assert "stall ledger: clean" in text


# ---------------------------------------------------------------------------
# wall-clock sweep satellite
# ---------------------------------------------------------------------------

def test_await_pose_selection_timeout_is_monotonic_bounded(tmp_path):
    from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
        await_pose_selection,
    )

    t0 = time.monotonic()
    assert await_pose_selection(str(tmp_path), timeout=0.15,
                                poll=0.02) is None
    assert time.monotonic() - t0 < 2.0


def test_no_wall_clock_deadline_arithmetic_in_package():
    """The sweep satellite, kept honest: no `time.time() +` deadline
    arithmetic anywhere in the package (monotonic is the convention;
    time.time() remains fine for timestamps)."""
    import re

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(
        stages.__file__)))
    offenders = []
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            with open(p, encoding="utf-8") as f:
                src = f.read()
            if re.search(r"time\.time\(\)\s*\+", src):
                offenders.append(p)
    assert offenders == []
