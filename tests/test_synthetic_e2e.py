"""End-to-end on synthetic geometry: render -> decode -> triangulate -> compare
against closed-form ground truth. This is the harness the reference never had
(SURVEY.md section 4): decode must reproduce exact projector coordinates, and
triangulated points must match analytic scene geometry to sub-quantization error.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.ops import graycode as gc
from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri
from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn


@pytest.fixture(scope="module")
def rendered():
    rig = syn.default_rig()
    scene = syn.sphere_on_background()
    frames, gt = syn.render_scene(rig, scene, noise_sigma=0.0)
    return rig, scene, frames, gt


def test_decode_recovers_exact_projector_coords(rendered):
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual",
                             shadow_val=40, contrast_val=10)
    lit = gt["lit"] & res.mask
    assert lit.mean() > 0.3  # a solid fraction of the frame is lit scene
    # decode must be EXACT on lit pixels: the rendered pattern value is the
    # pattern at the ground-truth projector pixel.
    np.testing.assert_array_equal(res.col_map[lit], gt["proj_col"][lit])
    np.testing.assert_array_equal(res.row_map[lit], gt["proj_row"][lit])
    # background (unlit) pixels must be masked out
    assert not res.mask[~gt["lit"] & ~gt["hit"]].any()


@pytest.mark.parametrize("row_mode", [0, 1])
def test_triangulation_matches_analytic_geometry(rendered, row_mode):
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual")
    calib = rig.calibration()
    cloud = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                               calib, row_mode=row_mode, epipolar_tol=2.0)
    gt_pts = gt["points"].reshape(-1, 3)
    lit = (gt["lit"] & res.mask).reshape(-1)
    ok = np.asarray(cloud.valid) & lit
    assert ok.sum() > 0.5 * lit.sum()
    err = np.linalg.norm(np.asarray(cloud.points)[ok] - gt_pts[ok], axis=1)
    # error bounded by projector-pixel quantization (~0.5 px at this geometry)
    assert np.median(err) < 1.5, np.median(err)
    assert np.percentile(err, 99) < 5.0


def test_triangulation_row_mode_2_concatenates_both_clouds(rendered):
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual")
    calib = rig.calibration()
    cloud = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                               calib, row_mode=2)
    n = res.col_map.size
    assert cloud.points.shape[0] == 2 * n
    gt_pts = gt["points"].reshape(-1, 3)
    lit = (gt["lit"] & res.mask).reshape(-1)
    # column half: quantization-bounded like mode 0
    ok_c = np.asarray(cloud.valid)[:n] & lit
    err_c = np.linalg.norm(np.asarray(cloud.points)[:n][ok_c] - gt_pts[ok_c], axis=1)
    assert np.median(err_c) < 1.5
    # row half: coarser (only 128 projector rows, shallower vertical baseline)
    ok_r = np.asarray(cloud.valid)[n:] & lit
    assert ok_r.sum() > 0.5 * lit.sum()
    err_r = np.linalg.norm(np.asarray(cloud.points)[n:][ok_r] - gt_pts[ok_r], axis=1)
    assert np.median(err_r) < 6.0, np.median(err_r)


def test_epipolar_filter_rejects_decode_corruption(rendered):
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual")
    calib = rig.calibration()
    # corrupt a block of column decodes; epipolar check must reject most of it
    col_bad = res.col_map.copy()
    h, w = col_bad.shape
    col_bad[h // 3: h // 2, w // 3: w // 2] += 31
    clean = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                               calib, row_mode=1)
    bad = tri.triangulate_np(col_bad, res.row_map, res.mask, res.texture,
                             calib, row_mode=1)
    block = np.zeros((h, w), bool)
    block[h // 3: h // 2, w // 3: w // 2] = True
    block &= gt["lit"] & res.mask
    kept_clean = np.asarray(clean.valid).reshape(h, w)[block].mean()
    kept_bad = np.asarray(bad.valid).reshape(h, w)[block].mean()
    assert kept_clean > 0.9
    assert kept_bad < 0.1


def test_jax_triangulation_matches_numpy(rendered):
    """Masks bit-exact; coordinates ULP-bounded (XLA fuses mul+add into FMA,
    so compiled float32 differs from NumPy by 1-2 ULP — the pinned contract)."""
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual")
    calib = rig.calibration()
    for row_mode in (0, 1, 2):
        c_np = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                                  calib, row_mode=row_mode)
        c_jx = tri.triangulate(jnp.asarray(res.col_map), jnp.asarray(res.row_map),
                               jnp.asarray(res.mask), jnp.asarray(res.texture),
                               calib, row_mode=row_mode)
        np.testing.assert_array_equal(np.asarray(c_jx.valid), c_np.valid)
        np.testing.assert_array_equal(np.asarray(c_jx.colors), c_np.colors)
        ok = c_np.valid
        diff = np.abs(np.asarray(c_jx.points)[ok] - c_np.points[ok])
        assert diff.max() < 1e-3, diff.max()


def test_bitexact_triangulation_matches_numpy_exactly_1080p(rng):
    """bitexact=True must remove even the FMA ULP gap: every point slot
    (valid or not) bit-identical to triangulate_np at full 1080p — the
    BASELINE "bit-exact point cloud vs CPU path" contract, now literal.

    Decode maps are synthesized directly (uniform random projector coords +
    ~half-lit mask) rather than rendered: rendering 46 1080p frames is ~60 s
    of fixture time and exactness is a property of the triangulation
    arithmetic, not of where the maps came from."""
    h, w = 1080, 1920
    col_map = rng.integers(0, 1920, (h, w)).astype(np.int32)
    row_map = rng.integers(0, 1080, (h, w)).astype(np.int32)
    mask = rng.random((h, w)) > 0.5
    texture = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
    rig = syn.default_rig(cam_size=(w, h), proj_size=(1920, 1080))
    calib = rig.calibration()
    for row_mode in (0, 1, 2):
        c_np = tri.triangulate_np(col_map, row_map, mask, texture, calib,
                                  row_mode=row_mode)
        c_bx = tri.triangulate(col_map, row_map, mask, texture, calib,
                               row_mode=row_mode, bitexact=True)
        np.testing.assert_array_equal(np.asarray(c_bx.valid), c_np.valid)
        # bitwise equality over EVERY slot, not an epsilon over valid ones
        assert (np.asarray(c_bx.points) == c_np.points).all()
        np.testing.assert_array_equal(np.asarray(c_bx.colors), c_np.colors)


def test_bitexact_rejects_quadratic_plane_eval():
    with pytest.raises(ValueError, match="bitexact"):
        tri.triangulate(np.zeros((4, 4), np.int32), np.zeros((4, 4), np.int32),
                        np.ones((4, 4), bool), np.zeros((4, 4, 3), np.uint8),
                        syn.default_rig(cam_size=(4, 4)).calibration(),
                        plane_eval="quadratic", bitexact=True)


def test_compact_cloud(rendered):
    rig, scene, frames, gt = rendered
    pw, ph = rig.proj_size
    res = gc.decode_stack_np(frames, n_cols=pw, n_rows=ph, thresh_mode="manual")
    cloud = tri.triangulate_np(res.col_map, res.row_map, res.mask, res.texture,
                               rig.calibration(), row_mode=1)
    pts, cols = tri.compact_cloud(cloud)
    assert pts.shape[0] == int(np.sum(cloud.valid))
    assert pts.shape[1] == 3 and cols.shape == pts.shape
    assert cols.dtype == np.uint8


def test_turntable_poses_roundtrip():
    poses = syn.turntable_poses(12, 30.0, pivot=np.array([0, 0, 420.0]))
    assert len(poses) == 12
    R, t = poses[3]  # 90 degrees
    p = np.array([10.0, 5.0, 420.0])
    q = R @ p + t
    # rotating about the y-axis through the pivot preserves distance to the axis
    ax = np.array([0, 0, 420.0])
    assert np.isclose(np.linalg.norm((q - ax)[[0, 2]]), np.linalg.norm((p - ax)[[0, 2]]))
    assert np.isclose(q[1], p[1])
    # 12 steps of 30 degrees compose to identity
    Rf, tf = syn.turntable_poses(13, 30.0, pivot=ax)[-1]
    np.testing.assert_allclose(Rf, np.eye(3), atol=1e-12)
    np.testing.assert_allclose(tf, 0, atol=1e-9)


def test_quadratic_plane_eval_matches_table(rendered):
    """The gather-free quadratic plane path must agree with the stored table
    to float32 tolerance and keep the same epipolar accept set (~all pixels)."""
    import jax.numpy as jnp

    from structured_light_for_3d_model_replication_tpu.ops import triangulate as tri

    rig, scene, frames, gt = rendered
    calib = rig.calibration()
    dec = gc.decode_stack_np(frames, n_cols=rig.proj_size[0],
                             n_rows=rig.proj_size[1], thresh_mode="manual")
    a = tri.triangulate_np(dec.col_map, dec.row_map, dec.mask, dec.texture,
                           calib, row_mode=1, plane_eval="table")
    b = tri.triangulate_np(dec.col_map, dec.row_map, dec.mask, dec.texture,
                           calib, row_mode=1, plane_eval="quadratic")
    both = np.asarray(a.valid) & np.asarray(b.valid)
    assert both.sum() > 0.99 * max(np.asarray(a.valid).sum(), 1)
    d = np.abs(np.asarray(a.points)[both] - np.asarray(b.points)[both])
    assert d.max() < 1e-2, d.max()  # sub-0.01mm at ~400mm depth

    j = tri.triangulate(jnp.asarray(dec.col_map), jnp.asarray(dec.row_map),
                        jnp.asarray(dec.mask), jnp.asarray(dec.texture),
                        calib, row_mode=1, plane_eval="quadratic")
    d2 = np.abs(np.asarray(j.points)[both] - np.asarray(b.points)[both])
    assert d2.max() < 1e-2
