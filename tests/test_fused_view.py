"""ISSUE 10: the HBM-resident view fastpath (pipeline.fused_clean).

The fused drain contract (ops/fused_view + pipeline/stages):
  - decode -> compact -> clean -> final-compact runs entirely on device;
    the ONE host sync is a single device_get at the collect boundary —
    and the output bytes are IDENTICAL to the discrete arm (host masking
    + _clean_arrays re-upload), single-device and under the 8-virtual-
    device mesh, full batches and ragged tails alike
  - the fused helpers take the count as a dynamic argument: no per-count
    retrace (jit cache keyed on the bucket ladder only)
  - the cleaned device buffers hand to the streaming registrar
    (prep_view_device), so the streamed merge is byte-identical too while
    the cloud path moves >=3x fewer device<->host bytes than discrete
  - a clean.fused fault inside a fused batch degrades the batch to the
    per-view lane where only the victim quarantines; a stall at the site
    terminates under the PR-7 watchdog — never a hang
"""
import os

import numpy as np
import pytest

from structured_light_for_3d_model_replication_tpu.cli import main as cli_main
from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.ops import (
    fused_view as fused_view_mod,
)
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import faults

VIEWS = 5
PROJ = (64, 32)
STEPS = ("statistical",)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("fusedds"))
    rc = cli_main(["synth", root, "--views", str(VIEWS),
                   "--cam", "96x72", "--proj", f"{PROJ[0]}x{PROJ[1]}"])
    assert rc == 0
    return root


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.reset()


def _cfg(compute_batch: int, fused: bool, shard: bool = True) -> Config:
    cfg = Config()
    cfg.parallel.backend = "jax"
    cfg.parallel.io_workers = 4
    cfg.parallel.compute_batch = compute_batch
    cfg.parallel.shard_views = shard
    cfg.decode.n_cols, cfg.decode.n_rows = PROJ
    cfg.decode.thresh_mode = "manual"
    cfg.pipeline.fused_clean = fused
    return cfg


def _pipe_cfg(compute_batch: int, fused: bool) -> Config:
    cfg = _cfg(compute_batch, fused)
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 128
    cfg.merge.icp_iters = 4
    cfg.mesh.depth = 3
    cfg.mesh.density_trim_quantile = 0.0
    return cfg


def _run(dataset, out_dir, cfg, log=None):
    calib = os.path.join(dataset, "calib.mat")
    return stages.reconstruct(calib, dataset, mode="batch",
                              output=str(out_dir), cfg=cfg,
                              log=log or (lambda m: None))


def _pipeline(dataset, out_dir, cfg):
    calib = os.path.join(dataset, "calib.mat")
    return stages.run_pipeline(calib, dataset, str(out_dir), cfg=cfg,
                               steps=STEPS, log=lambda m: None)


def _assert_identical_dirs(a, b, n=VIEWS):
    names_a, names_b = sorted(os.listdir(a)), sorted(os.listdir(b))
    assert names_a == names_b and len(names_a) == n
    for f in names_a:
        assert (a / f).read_bytes() == (b / f).read_bytes(), \
            f"{f}: fused PLY differs from discrete"


def _cloud_bytes(overlap: dict) -> int:
    # the cloud path's device<->host traffic: total h2d minus the
    # irreducible frame-stripe uploads, plus d2h
    return (int(overlap.get("transfer_bytes_h2d", 0))
            - int(overlap.get("transfer_bytes_frames", 0))
            + int(overlap.get("transfer_bytes_d2h", 0)))


# ---------------------------------------------------------------------------
# byte parity: fused vs discrete drain
# ---------------------------------------------------------------------------

def test_fused_reconstruct_byte_identical_sharded(dataset, tmp_path):
    """The acceptance A/B under the conftest 8-device mesh: a full batch
    (4 views) plus a ragged tail (1 view), fused drain vs discrete —
    byte-identical PLYs, with the fused launch accounting recorded."""
    rep_d = _run(dataset, tmp_path / "discrete", _cfg(4, fused=False))
    rep_f = _run(dataset, tmp_path / "fused", _cfg(4, fused=True))
    _assert_identical_dirs(tmp_path / "discrete", tmp_path / "fused")
    assert rep_d.failed == rep_f.failed == []
    k = rep_f.overlap["kernels"]["fused_view"]
    assert k["launches"] == 2                   # 4-view batch + ragged 1
    assert rep_f.overlap["transfer_bytes_d2h"] > 0
    # discrete syncs the WHOLE slot stack; fused only the compact results
    assert rep_f.overlap["transfer_bytes_d2h"] < \
        rep_d.overlap["transfer_bytes_d2h"]


def test_fused_reconstruct_byte_identical_unsharded_ragged(dataset, tmp_path):
    """shard_views=False (single-device programs): bucket-boundary batches
    (2 + 2) plus the ragged 1-view tail, byte-identical."""
    rep_d = _run(dataset, tmp_path / "discrete",
                 _cfg(2, fused=False, shard=False))
    rep_f = _run(dataset, tmp_path / "fused",
                 _cfg(2, fused=True, shard=False))
    _assert_identical_dirs(tmp_path / "discrete", tmp_path / "fused")
    assert rep_f.overlap["kernels"]["fused_view"]["launches"] == 3
    assert rep_d.overlap["launches"] == rep_f.overlap["launches"] == 3


def test_fused_helpers_no_retrace_across_batches(dataset, tmp_path):
    """The fused gather/select helpers take the survivor count as a
    DYNAMIC argument: a rerun over the same bucket ladder adds no new jit
    cache entries (per-count retrace would leak one per distinct n)."""
    _run(dataset, tmp_path / "warm", _cfg(2, fused=True, shard=False))
    sizes = fused_view_mod._cache_sizes()
    _run(dataset, tmp_path / "again", _cfg(2, fused=True, shard=False))
    assert fused_view_mod._cache_sizes() == sizes, \
        "fused helpers retraced on a warm rerun"


# ---------------------------------------------------------------------------
# full pipeline: device clean + registrar handoff + transfer-byte ratio
# (slow-marked: tier-1's -m 'not slow' budget excludes it; the FUSED_SMOKE
# CI arm asserts the same parity + >=3x contract end-to-end every run)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fused_pipeline_clean_merge_identical_and_3x_fewer_bytes(
        dataset, tmp_path):
    """run_pipeline with the clean chain and the streamed merge: the fused
    arm must ship byte-identical view PLYs, merged PLY, and STL (clean
    runs on device; cleaned device buffers feed prep_view_device), while
    the cloud path moves >=3x fewer device<->host bytes than discrete."""
    cfg_d = _pipe_cfg(3, fused=False)
    cfg_f = _pipe_cfg(3, fused=True)
    cfg_d.pipeline.write_view_plys = True
    cfg_f.pipeline.write_view_plys = True
    rep_d = _pipeline(dataset, tmp_path / "discrete", cfg_d)
    rep_f = _pipeline(dataset, tmp_path / "fused", cfg_f)
    assert rep_d.failed == rep_f.failed == []
    with open(rep_d.merged_ply, "rb") as fa, open(rep_f.merged_ply,
                                                  "rb") as fb:
        assert fa.read() == fb.read(), "merged PLY differs"
    with open(rep_d.stl_path, "rb") as fa, open(rep_f.stl_path, "rb") as fb:
        assert fa.read() == fb.read(), "STL differs"
    _assert_identical_dirs(tmp_path / "discrete" / "views",
                           tmp_path / "fused" / "views")
    cb_d, cb_f = _cloud_bytes(rep_d.overlap), _cloud_bytes(rep_f.overlap)
    assert cb_f > 0
    assert cb_d / cb_f >= 3.0, (
        f"fused cloud path moved {cb_f} B vs discrete {cb_d} B — "
        f"ratio {cb_d / cb_f:.2f} < 3x")
    assert rep_f.overlap["kernels"]["fused_view"]["launches"] >= 1


# ---------------------------------------------------------------------------
# fault containment at the fused site
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fused_clean_permanent_fault_quarantines_only_victim(
        dataset, tmp_path):
    """A poisoned view inside a fused batch: the batch degrades to the
    per-view lane, where clean.fused re-fires per view — ONLY the victim
    quarantines; its batchmates ship bytes identical to a clean run.
    (Needs run_pipeline — the per-view fallback only re-enters the
    clean.fused site when the clean stage is active, and the degraded
    merge/mesh over the survivors is part of the contract.)"""
    victim = sorted(
        d for d in os.listdir(dataset)
        if os.path.isdir(os.path.join(dataset, d)))[1]
    cfg = _pipe_cfg(3, fused=True)
    cfg.pipeline.write_view_plys = True
    rep_clean = _pipeline(dataset, tmp_path / "clean", cfg)
    assert rep_clean.failed == []

    faults.configure(f"clean.fused~{victim}:permanent", seed=7)
    cfg2 = _pipe_cfg(3, fused=True)
    cfg2.pipeline.write_view_plys = True
    rep = _pipeline(dataset, tmp_path / "faulted", cfg2)
    assert len(rep.failed) == 1
    assert victim in rep.failed[0][0]
    assert rep.degraded
    assert rep.stl_path and os.path.getsize(rep.stl_path) > 0
    # the victim's batchmates ship bytes identical to the clean run
    clean_views = tmp_path / "clean" / "views"
    faulted_views = tmp_path / "faulted" / "views"
    names = sorted(os.listdir(faulted_views))
    assert len(names) == VIEWS - 1
    assert not any(victim in n for n in names)
    for n in names:
        assert (faulted_views / n).read_bytes() == \
            (clean_views / n).read_bytes(), f"{n}: batchmate bytes changed"


def test_fused_clean_transient_fault_retries_all_views_survive(
        dataset, tmp_path):
    victim = sorted(
        d for d in os.listdir(dataset)
        if os.path.isdir(os.path.join(dataset, d)))[2]
    faults.configure(f"clean.fused~{victim}:transient", seed=3)
    rep = _run(dataset, tmp_path / "out", _cfg(VIEWS, fused=True))
    assert rep.failed == []
    assert rep.retries >= 1


@pytest.mark.slow
def test_stall_at_fused_site_terminates_under_watchdog(dataset, tmp_path):
    """PR-7 contract at the new site: a seeded stall inside the fused
    drain terminates the run within its deadline envelope — DEGRADED or
    clean, never a hang."""
    import time as _time

    cfg = _pipe_cfg(VIEWS, fused=True)
    cfg.deadlines.drain_s = 1.0
    cfg.deadlines.soft_stall_s = 3.0
    cfg.deadlines.hard_stall_s = 10.0
    cfg.deadlines.watchdog_poll_s = 0.1
    faults.configure("clean.fused:stall(0.8)")
    t0 = _time.monotonic()
    rep = _pipeline(dataset, tmp_path / "out", cfg)
    wall = _time.monotonic() - t0
    assert wall < 120.0, f"stall at clean.fused cost {wall:.0f}s — unbounded?"
    assert rep.stl_path and os.path.getsize(rep.stl_path) > 0
