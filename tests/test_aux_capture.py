"""Webcam backend (O9 parity), calib rig plot (Calib Check tab parity), and
multi-host mesh helpers."""
import os

import numpy as np
import pytest


class _FakeCap:
    def __init__(self, device):
        self.device = device
        self.opened = True
        self.grabs = 0

    def isOpened(self):
        return True

    def set(self, *_):
        return True

    def grab(self):
        self.grabs += 1

    def read(self):
        frame = np.full((48, 64, 3), 90, np.uint8)
        frame[10:20, 10:20] = 200
        return True, frame

    def release(self):
        self.opened = False


def test_webcam_capture_contract(tmp_path, monkeypatch):
    import cv2

    from structured_light_for_3d_model_replication_tpu.acquire import webcam

    monkeypatch.setattr(cv2, "VideoCapture", _FakeCap)
    out = str(tmp_path / "cap.png")
    with webcam.WebcamCapture(device=0, warmup_frames=2) as cam:
        path = cam(out)
        assert cam.cap.grabs == 2  # AE settle frames consumed
    assert path == out and os.path.exists(out)
    assert not cam.cap.opened  # released on exit


def test_webcam_in_sequencer(tmp_path, monkeypatch):
    import cv2

    from structured_light_for_3d_model_replication_tpu.acquire import webcam
    from structured_light_for_3d_model_replication_tpu.acquire.projector import (
        VirtualProjector,
    )
    from structured_light_for_3d_model_replication_tpu.acquire.sequencer import (
        CaptureSequencer,
    )

    monkeypatch.setattr(cv2, "VideoCapture", _FakeCap)
    cam = webcam.WebcamCapture()
    seq = CaptureSequencer(VirtualProjector(), cam, proj_size=(64, 32),
                           log=lambda *a: None)
    paths = seq.capture_scan(str(tmp_path / "scan"))
    assert len(paths) == 24  # 2 + 2*(6+5) for 64x32
    assert all(os.path.exists(p) for p in paths)


def test_plot_rig_renders_png(tmp_path):
    from structured_light_for_3d_model_replication_tpu.calib import visualize
    from structured_light_for_3d_model_replication_tpu.utils import synthetic as syn

    calib = syn.default_rig().calibration()
    out = str(tmp_path / "rig.png")
    info = visualize.plot_rig(calib, out)
    assert os.path.exists(out) and os.path.getsize(out) > 10_000
    assert info["baseline_mm"] == pytest.approx(
        float(np.linalg.norm(np.asarray(calib["T"]))), rel=1e-6)


def test_multihost_single_process():
    import jax

    from structured_light_for_3d_model_replication_tpu.parallel import multihost

    assert multihost.is_multiprocess() is False
    assert multihost.initialize() is False  # no coordinator configured
    mesh = multihost.global_mesh()
    assert mesh.devices.size == len(jax.devices())
    s = multihost.process_summary()
    assert s["process_count"] == 1 and s["global_devices"] >= 1
