"""Operator web viewer: endpoints, artifact safety, stage recording."""
import json
import os
import urllib.request

import numpy as np

from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
    StageRecorder,
    ViewerServer,
)


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=10)


def test_viewer_serves_artifacts_and_progress(tmp_path, rng):
    rec = StageRecorder(str(tmp_path))
    pts = rng.normal(size=(500, 3)).astype(np.float32)
    cols = np.full((500, 3), 120, np.uint8)
    rec.merge_step(1, pts, cols)
    rec.autoscan_progress({"view": 2, "turns": 12, "angle": 30.0,
                           "elapsed_s": 10.0, "remaining_s": 50.0})

    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        page = _get(base, "/").read().decode()
        assert "slscan" in page and "parsePLY" in page
        lst = json.load(_get(base, "/api/list"))
        names = [a["name"] for a in lst["artifacts"]]
        assert "merge_step_01.ply" in names
        raw = _get(base, "/api/file?name=merge_step_01.ply").read()
        assert raw.startswith(b"ply")
        prog = json.load(_get(base, "/api/progress"))
        assert prog[0]["stage"] == "merge" and prog[0]["points"] == 500
        assert prog[1]["stage"] == "autoscan" and prog[1]["remaining_s"] == 50.0


def test_pose_review_roundtrip(tmp_path):
    """The in-viewer prune flow (gui.py:1211-1250 parity): publish errors ->
    GET shows pending review -> operator POSTs a keep list -> the waiting
    calibrate process receives it and the review clears."""
    import threading

    from structured_light_for_3d_model_replication_tpu.acquire import (
        viewer as vw,
    )

    errors = {"pose_1": (0.31, 0.62), "pose_2": (1.8, 2.4),
              "pose_3": (0.45, 0.71)}
    vw.publish_pose_review(str(tmp_path), errors)
    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        j = json.load(_get(base, "/api/poses"))
        assert j["status"] == "pending"
        assert j["poses"]["pose_2"]["cam_px"] == 1.8
        page = _get(base, "/").read().decode()
        assert "pose review" in page.lower()  # panel shipped with the page

        got: list = []
        waiter = threading.Thread(
            target=lambda: got.append(
                vw.await_pose_selection(str(tmp_path), timeout=20)))
        waiter.start()
        req = urllib.request.Request(
            base + "/api/poses",
            data=json.dumps({"keep": ["pose_1", "pose_3"]}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        resp = json.load(urllib.request.urlopen(req, timeout=10))
        assert resp == {"ok": True, "kept": 2}
        waiter.join(timeout=20)
        assert got == [["pose_1", "pose_3"]]
        # consumed: review cleared, a fresh GET reports none pending
        assert json.load(_get(base, "/api/poses"))["status"] == "none"


def test_pose_selection_rejected_without_pending_review(tmp_path):
    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        req = urllib.request.Request(
            base + "/api/poses", data=b'{"keep": []}',
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=10)
        except urllib.error.HTTPError as e:
            assert e.code == 409
        else:  # pragma: no cover
            raise AssertionError("expected 409 with no review pending")


def test_viewer_blocks_traversal_and_unknown(tmp_path):
    (tmp_path / "ok.ply").write_bytes(b"ply\nend_header\n")
    secret = tmp_path.parent / "secret.ply"
    secret.write_bytes(b"ply\nsecret")
    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        for bad in ("/api/file?name=../secret.ply",
                    "/api/file?name=%2e%2e%2fsecret.ply",
                    "/api/file?name=ok.txt"):
            code = None
            try:
                _get(base, bad)
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 400, bad
        try:
            _get(base, "/api/file?name=missing.ply")
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_stage_recorder_downsamples_large_steps(tmp_path):
    rec = StageRecorder(str(tmp_path), max_points_per_step=100)
    pts = np.zeros((1000, 3), np.float32)
    rec.merge_step(3, pts, np.zeros((1000, 3), np.uint8))
    from structured_light_for_3d_model_replication_tpu.io import ply

    d = ply.read_ply(str(tmp_path / "merge_step_03.ply"))
    assert len(d["points"]) == 100
