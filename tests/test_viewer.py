"""Operator web viewer: endpoints, artifact safety, stage recording."""
import json
import os
import urllib.request

import numpy as np

from structured_light_for_3d_model_replication_tpu.acquire.viewer import (
    StageRecorder,
    ViewerServer,
)


def _get(base, path):
    return urllib.request.urlopen(base + path, timeout=10)


def test_viewer_serves_artifacts_and_progress(tmp_path, rng):
    rec = StageRecorder(str(tmp_path))
    pts = rng.normal(size=(500, 3)).astype(np.float32)
    cols = np.full((500, 3), 120, np.uint8)
    rec.merge_step(1, pts, cols)
    rec.autoscan_progress({"view": 2, "turns": 12, "angle": 30.0,
                           "elapsed_s": 10.0, "remaining_s": 50.0})

    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        page = _get(base, "/").read().decode()
        assert "slscan" in page and "parsePLY" in page
        lst = json.load(_get(base, "/api/list"))
        names = [a["name"] for a in lst["artifacts"]]
        assert "merge_step_01.ply" in names
        raw = _get(base, "/api/file?name=merge_step_01.ply").read()
        assert raw.startswith(b"ply")
        prog = json.load(_get(base, "/api/progress"))
        assert prog[0]["stage"] == "merge" and prog[0]["points"] == 500
        assert prog[1]["stage"] == "autoscan" and prog[1]["remaining_s"] == 50.0


def test_viewer_blocks_traversal_and_unknown(tmp_path):
    (tmp_path / "ok.ply").write_bytes(b"ply\nend_header\n")
    secret = tmp_path.parent / "secret.ply"
    secret.write_bytes(b"ply\nsecret")
    with ViewerServer(str(tmp_path), host="127.0.0.1", port=0) as v:
        base = f"http://127.0.0.1:{v.port}"
        for bad in ("/api/file?name=../secret.ply",
                    "/api/file?name=%2e%2e%2fsecret.ply",
                    "/api/file?name=ok.txt"):
            code = None
            try:
                _get(base, bad)
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 400, bad
        try:
            _get(base, "/api/file?name=missing.ply")
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_stage_recorder_downsamples_large_steps(tmp_path):
    rec = StageRecorder(str(tmp_path), max_points_per_step=100)
    pts = np.zeros((1000, 3), np.float32)
    rec.merge_step(3, pts, np.zeros((1000, 3), np.uint8))
    from structured_light_for_3d_model_replication_tpu.io import ply

    d = ply.read_ply(str(tmp_path / "merge_step_03.ply"))
    assert len(d["points"]) == 100
