"""Flight recorder contract (ISSUE 6): journal schema round-trip, crash-safe
journals, OverlapStats<->trace cross-check, `sl3d report` on clean/degraded/
interrupted runs, the Perfetto export, and the zero-allocation disabled path.
"""
import json
import math
import os

import pytest

from structured_light_for_3d_model_replication_tpu.cli import main as cli_main
from structured_light_for_3d_model_replication_tpu.config import Config
from structured_light_for_3d_model_replication_tpu.pipeline import report as replib
from structured_light_for_3d_model_replication_tpu.pipeline import stages
from structured_light_for_3d_model_replication_tpu.utils import faults
from structured_light_for_3d_model_replication_tpu.utils import telemetry as tel

STEPS = ("statistical",)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("traceds"))
    rc = cli_main(["synth", root, "--views", "3",
                   "--cam", "160x120", "--proj", "128x64"])
    assert rc == 0
    return root


def _cfg(trace: bool = True) -> Config:
    cfg = Config()
    cfg.parallel.backend = "numpy"
    cfg.decode.n_cols, cfg.decode.n_rows = 128, 64
    cfg.decode.thresh_mode = "manual"
    cfg.merge.voxel_size = 4.0
    cfg.merge.ransac_trials = 512
    cfg.merge.icp_iters = 10
    cfg.mesh.depth = 5
    cfg.mesh.density_trim_quantile = 0.0
    cfg.observability.trace = trace
    return cfg


@pytest.fixture(scope="module")
def traced_run(dataset, tmp_path_factory):
    """One traced clean run shared by the schema/report/export tests."""
    out = str(tmp_path_factory.mktemp("traced"))
    calib = os.path.join(dataset, "calib.mat")
    rep = stages.run_pipeline(calib, dataset, out, cfg=_cfg(), steps=STEPS,
                              log=lambda m: None)
    assert rep.failed == []
    return out, rep


# ---------------------------------------------------------------------------
# journal schema + round trip
# ---------------------------------------------------------------------------

def test_journal_schema_roundtrip(traced_run):
    out, rep = traced_run
    journal = os.path.join(out, "trace.jsonl")
    assert os.path.exists(journal)
    # every line is standalone JSON (the append-only crash-safety contract)
    with open(journal) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines[0]["type"] == "meta"
    assert lines[0]["schema"] == tel.SCHEMA
    assert lines[0]["run_id"] == rep.run_id
    assert lines[-1]["type"] == "end"
    assert replib.validate_journal(journal) == []
    j = tel.read_journal(journal)
    assert j["truncated"] == 0
    kinds = {e["type"] for e in j["events"]}
    assert kinds >= {"span", "instant", "end"}
    # spans carry non-negative monotonic-clock offsets and durations
    for e in j["events"]:
        assert e["t"] >= -1e-6
        if e["type"] == "span":
            assert e["dur"] >= 0


def test_metrics_json_written_and_prometheus(traced_run):
    out, rep = traced_run
    with open(os.path.join(out, "metrics.json")) as f:
        m = json.load(f)
    assert m["run_id"] == rep.run_id
    names = {c["name"] for c in m["counters"]}
    assert "sl3d_cache_events_total" in names
    hists = {h["name"] for h in m["histograms"]}
    assert "sl3d_lane_seconds" in hists
    text = tel.prometheus_text(m)
    assert "# TYPE sl3d_lane_seconds histogram" in text
    assert "sl3d_lane_seconds_bucket" in text
    assert 'le="+Inf"' in text


def test_run_id_threads_through(traced_run):
    out, rep = traced_run
    meta = tel.read_journal(os.path.join(out, "trace.jsonl"))["meta"]
    with open(os.path.join(out, "metrics.json")) as f:
        m = json.load(f)
    assert rep.run_id == meta["run_id"] == m["run_id"]


# ---------------------------------------------------------------------------
# OverlapStats <-> journal cross-check (the can't-drift guarantee)
# ---------------------------------------------------------------------------

def test_lane_walls_reproduce_overlap_stats(traced_run):
    out, rep = traced_run
    a = replib.analyze_run(out)
    checked = 0
    for lane, wall in a.lane_walls.items():
        stat = rep.overlap.get(f"{lane}_s")
        if stat:
            assert math.isclose(wall, stat, rel_tol=0.01, abs_tol=1e-3), \
                (lane, wall, stat)
            checked += 1
    assert checked >= 2  # at least load + register on the fused run


# ---------------------------------------------------------------------------
# sl3d report: clean / degraded / interrupted
# ---------------------------------------------------------------------------

def test_report_clean_run(traced_run, capsys):
    out, rep = traced_run
    rc = cli_main(["report", out])
    assert rc == 0
    text = capsys.readouterr().out
    assert rep.run_id in text
    assert "lane timeline" in text
    assert "stage cache" in text
    assert "clean close" in text
    assert "fault ledger: clean" in text


def test_report_chrome_trace_lanes_overlap(traced_run, capsys):
    out, _rep = traced_run
    rc = cli_main(["report", out, "--chrome-trace"])
    assert rc == 0
    with open(os.path.join(out, "trace.json")) as f:
        payload = json.load(f)
    evs = payload["traceEvents"]
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    lanes = {n.split(" [")[0] for n in names}
    assert len(lanes) >= 4, lanes
    # the streaming schedule must show genuine overlap: some pair of spans
    # on DIFFERENT lanes intersects in time
    spans = [(e["ts"], e["ts"] + e["dur"], e["tid"]) for e in evs
             if e.get("ph") == "X"]
    tid_lane = {}
    for e in evs:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_lane[e["tid"]] = e["args"]["name"].split(" [")[0]
    overlapping = any(
        a0 < b1 and b0 < a1 and tid_lane.get(ta) != tid_lane.get(tb)
        for i, (a0, a1, ta) in enumerate(spans)
        for (b0, b1, tb) in spans[i + 1:])
    assert overlapping


def test_report_degraded_run(dataset, tmp_path, capsys):
    """A permanent compute fault quarantines one view; the report must show
    the fault ledger and failures.json must carry the run_id."""
    out = str(tmp_path / "degraded")
    calib = os.path.join(dataset, "calib.mat")
    faults.configure("compute.view~120deg:permanent", seed=0)
    try:
        rep = stages.run_pipeline(calib, dataset, out, cfg=_cfg(),
                                  steps=STEPS, log=lambda m: None)
    finally:
        faults.reset()
    assert rep.degraded and len(rep.failed) == 1
    with open(os.path.join(out, "failures.json")) as f:
        manifest = json.load(f)
    assert manifest["run_id"] == rep.run_id
    rc = cli_main(["report", out])
    assert rc == 0
    text = capsys.readouterr().out
    assert "DEGRADED" in text
    assert "fault ledger" in text
    assert "injected" in text          # fault.injected event made the ledger
    assert "quarantined view" in text
    assert replib.validate_journal(os.path.join(out, "trace.jsonl")) == []


def test_crash_mid_run_leaves_parseable_journal(dataset, tmp_path, capsys):
    """The PR-3 crash site at the merged-cloud write: the InjectedCrash
    escapes run_pipeline, yet the journal parses and `sl3d report` renders
    the interrupted run (no end marker -> INTERRUPTED verdict)."""
    out = str(tmp_path / "crashed")
    calib = os.path.join(dataset, "calib.mat")
    faults.configure("ply.write~merged:crash", seed=0)
    try:
        with pytest.raises(faults.InjectedCrash):
            stages.run_pipeline(calib, dataset, out, cfg=_cfg(),
                                steps=STEPS, log=lambda m: None)
    finally:
        faults.reset()
    assert tel.current() is None       # the finally released the tracer
    journal = os.path.join(out, "trace.jsonl")
    assert replib.validate_journal(journal) == []
    a = replib.analyze_run(out)
    assert a.lane_walls                # real events landed before the crash
    rc = cli_main(["report", out])
    assert rc == 0
    text = capsys.readouterr().out
    assert "injected" in text          # the crash is in the ledger


def test_truncated_tail_tolerated(traced_run, tmp_path):
    """A torn trailing line (kill mid-write) reads as truncated, never as a
    parse failure."""
    out, _rep = traced_run
    src = os.path.join(out, "trace.jsonl")
    dst = tmp_path / "torn.jsonl"
    data = open(src).read()
    dst.write_text(data + '{"type":"instant","ev":"torn","t":9.9')
    j = tel.read_journal(str(dst))
    assert j["truncated"] == 1
    assert j["meta"] is not None and j["events"]
    assert replib.validate_journal(str(dst)) == []


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_run_emits_nothing(dataset, tmp_path):
    out = str(tmp_path / "untraced")
    calib = os.path.join(dataset, "calib.mat")
    rep = stages.run_pipeline(calib, dataset, out, cfg=_cfg(trace=False),
                              steps=STEPS, log=lambda m: None)
    assert rep.failed == []
    assert not os.path.exists(os.path.join(out, "trace.jsonl"))
    assert not os.path.exists(os.path.join(out, "metrics.json"))
    assert rep.run_id  # the correlation id exists even untraced


def test_disabled_path_zero_allocation():
    """The disabled instrumentation point is `telemetry.current()` + a None
    check — steady state must allocate nothing (the <=1.02x overhead
    contract's mechanism)."""
    import tracemalloc

    assert tel.current() is None
    n = 10000
    sentinel = [None] * n              # preallocate the loop's iterable
    tracemalloc.start()
    try:
        base = tracemalloc.get_traced_memory()[0]
        for _ in sentinel:
            if tel.current() is not None:  # the exact guard the hot paths use
                raise AssertionError
        grown = tracemalloc.get_traced_memory()[0] - base
    finally:
        tracemalloc.stop()
    assert grown < 512, f"disabled path allocated {grown} bytes over {n} calls"


def test_env_flag_arms_tracing(monkeypatch):
    monkeypatch.setenv("SL3D_TRACE", "1")
    assert Config().observability.trace is True
    monkeypatch.delenv("SL3D_TRACE")
    assert Config().observability.trace is False


# ---------------------------------------------------------------------------
# registry unit
# ---------------------------------------------------------------------------

def test_metrics_registry_quantiles_and_text():
    reg = tel.MetricsRegistry()
    reg.inc("sl3d_events_total", ev="x")
    reg.inc("sl3d_events_total", ev="x")
    reg.set_gauge("sl3d_run_wall_seconds", 12.5)
    for v in (0.01, 0.02, 0.03, 0.04, 5.0):
        reg.observe("sl3d_lane_seconds", v, lane="load")
    d = reg.as_dict()
    h = d["histograms"][0]
    assert h["count"] == 5 and abs(h["sum"] - 5.1) < 1e-6
    assert h["min"] == 0.01 and h["max"] == 5.0
    assert 0.01 <= h["p50"] <= 0.05       # bucket-interpolated median
    assert h["p99"] <= 5.0
    assert reg.counter_value("sl3d_events_total", ev="x") == 2
    text = reg.to_prometheus()
    assert 'sl3d_events_total{ev="x"} 2.0' in text
    assert "sl3d_run_wall_seconds 12.5" in text
    # cumulative bucket counts end at the total
    assert f'le="+Inf"}} {h["count"]}' in text


def test_journal_segments_keep_history_scope_latest(tmp_path):
    """Reruns APPEND to the journal (crash evidence survives); readers see
    one segment per run and scope meta/events to the latest."""
    p = str(tmp_path / "t.jsonl")
    t1 = tel.Tracer(p)
    t1.lane("load", 0.5)
    t1.close()
    t2 = tel.Tracer(p)
    t2.lane("compute", 0.25)
    t2.close()
    j = tel.read_journal(p)
    assert j["runs"] == 2 and len(j["segments"]) == 2
    assert j["meta"]["run_id"] == t2.run_id       # latest run wins
    spans = [e for e in j["events"] if e["type"] == "span"]
    assert [s["lane"] for s in spans] == ["compute"]
    # the first run's evidence is intact in its own segment
    s0 = j["segments"][0]
    assert s0["meta"]["run_id"] == t1.run_id
    assert any(e.get("lane") == "load" for e in s0["events"])
    assert replib.validate_journal(p) == []


def test_tracer_activate_restores_previous(tmp_path):
    t1 = tel.Tracer(str(tmp_path / "a.jsonl"))
    prev = tel.activate(t1)
    try:
        assert tel.current() is t1
        t2 = tel.Tracer(str(tmp_path / "b.jsonl"))
        p2 = tel.activate(t2)
        assert p2 is t1 and tel.current() is t2
        tel.deactivate(p2)
        assert tel.current() is t1
        t2.close()
    finally:
        tel.deactivate(prev)
        t1.close()
    assert tel.current() is None
