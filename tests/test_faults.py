"""The deterministic fault-injection layer + retry/quarantine toolkit
(utils/faults.py): rule grammar, seeded plans, transient classification,
backoff schedule, and the structured FailureRecord contract."""
import urllib.error

import pytest

from structured_light_for_3d_model_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process-wide plan disarmed."""
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# rule grammar
# ---------------------------------------------------------------------------

def test_rule_parse_full_grammar():
    r = faults.FaultRule.parse("frame.load~072deg:transient@2x3%0.5")
    assert r.site == "frame.load" and r.kind == "transient"
    assert r.match == "072deg" and r.arm_at == 2
    assert r.times == 3 and r.prob == 0.5


def test_rule_parse_defaults():
    t = faults.FaultRule.parse("ply.write:transient")
    assert t.times == 1  # transient fires once by default
    p = faults.FaultRule.parse("compute.view:permanent")
    assert p.times == float("inf")  # permanent fires every time


@pytest.mark.parametrize("bad", ["nosep", "x:notakind", "a:transient@z"])
def test_rule_parse_rejects(bad):
    with pytest.raises(ValueError):
        faults.FaultRule.parse(bad)


# ---------------------------------------------------------------------------
# plan firing
# ---------------------------------------------------------------------------

def test_disarmed_fire_is_noop():
    faults.reset()
    for _ in range(100):
        faults.fire("frame.load", item="anything")  # must never raise


def test_transient_fires_once_then_stops():
    faults.configure("frame.load:transient")
    with pytest.raises(faults.TransientFault):
        faults.fire("frame.load", item="v0")
    faults.fire("frame.load", item="v0")  # budget spent: silent
    faults.fire("other.site")             # different site: never armed


def test_permanent_fires_every_matching_hit_and_respects_match():
    faults.configure("compute.view~bad:permanent")
    faults.fire("compute.view", item="good_view")  # substring miss
    for _ in range(3):
        with pytest.raises(faults.PermanentFault):
            faults.fire("compute.view", item="bad_view")


def test_arm_at_and_times_window():
    faults.configure("ply.write:transient@2x2")
    faults.fire("ply.write")                     # hit 1: not yet armed
    for _ in range(2):                           # hits 2,3 fire
        with pytest.raises(faults.TransientFault):
            faults.fire("ply.write")
    faults.fire("ply.write")                     # hit 4: budget spent


def test_probabilistic_rule_is_seed_deterministic():
    def fired_pattern(seed):
        plan = faults.FaultPlan.from_spec("cache.get:transientx1000%0.5",
                                          seed=seed)
        out = []
        for i in range(50):
            try:
                plan.fire("cache.get", item=i)
                out.append(False)
            except faults.TransientFault:
                out.append(True)
        return out

    a, b = fired_pattern(7), fired_pattern(7)
    assert a == b and any(a) and not all(a)
    assert fired_pattern(8) != a


def test_crash_kind_escapes_except_exception():
    faults.configure("ply.write~merged:crash")
    with pytest.raises(faults.InjectedCrash):
        try:
            faults.fire("ply.write", item="/out/merged.ply")
        except Exception:  # per-item tolerance must NOT swallow a crash
            pytest.fail("InjectedCrash was caught by `except Exception`")


def test_plan_counts_and_env_override(monkeypatch):
    faults.configure("a.b:transient,c.d:permanent")
    plan = faults.active_plan()
    with pytest.raises(faults.TransientFault):
        faults.fire("a.b")
    assert plan.counts() == {"a.b": 1}

    monkeypatch.setenv("SL3D_FAULTS", "x.y:permanent")
    monkeypatch.setenv("SL3D_FAULTS_SEED", "3")

    class Cfg:
        spec = "a.b:transient"
        seed = 0

    plan = faults.configure_from(Cfg())
    assert plan.rules[0].site == "x.y" and plan.seed == 3
    monkeypatch.delenv("SL3D_FAULTS")
    assert faults.configure_from(Cfg()).rules[0].site == "a.b"


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_is_transient_classification():
    assert faults.is_transient(faults.TransientFault("x"))
    assert not faults.is_transient(faults.PermanentFault("x"))
    assert faults.is_transient(ConnectionResetError())
    assert faults.is_transient(TimeoutError())
    assert faults.is_transient(urllib.error.URLError("dropped"))
    assert faults.is_transient(OSError(11, "EAGAIN"))
    assert not faults.is_transient(OSError(2, "ENOENT"))
    # unknown types default to permanent: retrying a deterministic failure
    # only delays the quarantine decision
    assert not faults.is_transient(ValueError("corrupt"))
    assert not faults.is_transient(KeyError("k"))


# ---------------------------------------------------------------------------
# retry + backoff
# ---------------------------------------------------------------------------

def test_retry_call_backoff_schedule_exact():
    """The acceptance-criteria contract: retries happen exactly per policy —
    doubling from backoff_base_s, capped at backoff_max_s, max_retries
    total, then the ORIGINAL exception with the true attempt count."""
    policy = faults.RetryPolicy(max_retries=3, backoff_base_s=0.1,
                                backoff_max_s=0.25)
    sleeps, retries = [], []
    calls = {"n": 0}

    def always_transient():
        calls["n"] += 1
        raise faults.TransientFault(f"attempt {calls['n']}")

    with pytest.raises(faults.TransientFault, match="attempt 4") as ei:
        faults.retry_call(always_transient, policy,
                          on_retry=lambda n, e: retries.append(n),
                          sleep=sleeps.append)
    assert calls["n"] == 4                    # 1 try + 3 retries
    assert sleeps == [0.1, 0.2, 0.25]         # doubling, capped
    assert retries == [1, 2, 3]
    assert ei.value._sl3d_attempts == 4


def test_retry_call_recovers_and_skips_permanent():
    policy = faults.RetryPolicy(max_retries=2, backoff_base_s=0.0)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 2:
            raise faults.TransientFault("blip")
        return "ok"

    assert faults.retry_call(flaky, policy, sleep=lambda s: None) == "ok"

    def perma():
        state["n"] += 1
        raise faults.PermanentFault("dead")

    state["n"] = 0
    with pytest.raises(faults.PermanentFault):
        faults.retry_call(perma, policy, sleep=lambda s: None)
    assert state["n"] == 1  # permanent: no retry at all

    with pytest.raises(faults.InjectedCrash):  # crashes are never retried
        faults.retry_call(
            lambda: (_ for _ in ()).throw(faults.InjectedCrash("kill")),
            policy, sleep=lambda s: None)


def test_zero_budget_policy_disables_retry():
    policy = faults.RetryPolicy(max_retries=0)
    calls = {"n": 0}

    def f():
        calls["n"] += 1
        raise faults.TransientFault("x")

    with pytest.raises(faults.TransientFault):
        faults.retry_call(f, policy, sleep=lambda s: None)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# failure records
# ---------------------------------------------------------------------------

def test_failure_record_from_annotated_exception():
    e = faults.annotate(faults.TransientFault("torn read"),
                        stage="load", attempts=3)
    rec = faults.FailureRecord.from_exception("compute", "scan_072deg", e)
    assert rec.stage == "load"          # annotation wins over the default
    assert rec.attempts == 3
    assert rec.view == "scan_072deg"
    assert rec.error_type == "TransientFault" and rec.transient
    d = rec.as_dict()
    assert d["stage"] == "load" and d["transient"] is True

    plain = faults.FailureRecord.from_exception("compute", "v",
                                                ValueError("bad"))
    assert plain.stage == "compute" and plain.attempts == 1
    assert not plain.transient
