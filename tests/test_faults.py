"""The deterministic fault-injection layer + retry/quarantine toolkit
(utils/faults.py): rule grammar, seeded plans, transient classification,
backoff schedule, and the structured FailureRecord contract."""
import urllib.error

import pytest

from structured_light_for_3d_model_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process-wide plan disarmed."""
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# rule grammar
# ---------------------------------------------------------------------------

def test_rule_parse_full_grammar():
    r = faults.FaultRule.parse("frame.load~072deg:transient@2x3%0.5")
    assert r.site == "frame.load" and r.kind == "transient"
    assert r.match == "072deg" and r.arm_at == 2
    assert r.times == 3 and r.prob == 0.5


def test_rule_parse_defaults():
    t = faults.FaultRule.parse("ply.write:transient")
    assert t.times == 1  # transient fires once by default
    p = faults.FaultRule.parse("compute.view:permanent")
    assert p.times == float("inf")  # permanent fires every time


@pytest.mark.parametrize("bad", ["nosep", "x:notakind", "a:transient@z"])
def test_rule_parse_rejects(bad):
    with pytest.raises(ValueError):
        faults.FaultRule.parse(bad)


# ---------------------------------------------------------------------------
# plan firing
# ---------------------------------------------------------------------------

def test_disarmed_fire_is_noop():
    faults.reset()
    for _ in range(100):
        faults.fire("frame.load", item="anything")  # must never raise


def test_transient_fires_once_then_stops():
    faults.configure("frame.load:transient")
    with pytest.raises(faults.TransientFault):
        faults.fire("frame.load", item="v0")
    faults.fire("frame.load", item="v0")  # budget spent: silent
    faults.fire("other.site")             # different site: never armed


def test_permanent_fires_every_matching_hit_and_respects_match():
    faults.configure("compute.view~bad:permanent")
    faults.fire("compute.view", item="good_view")  # substring miss
    for _ in range(3):
        with pytest.raises(faults.PermanentFault):
            faults.fire("compute.view", item="bad_view")


def test_arm_at_and_times_window():
    faults.configure("ply.write:transient@2x2")
    faults.fire("ply.write")                     # hit 1: not yet armed
    for _ in range(2):                           # hits 2,3 fire
        with pytest.raises(faults.TransientFault):
            faults.fire("ply.write")
    faults.fire("ply.write")                     # hit 4: budget spent


def test_probabilistic_rule_is_seed_deterministic():
    def fired_pattern(seed):
        plan = faults.FaultPlan.from_spec("cache.get:transientx1000%0.5",
                                          seed=seed)
        out = []
        for i in range(50):
            try:
                plan.fire("cache.get", item=i)
                out.append(False)
            except faults.TransientFault:
                out.append(True)
        return out

    a, b = fired_pattern(7), fired_pattern(7)
    assert a == b and any(a) and not all(a)
    assert fired_pattern(8) != a


def test_crash_kind_escapes_except_exception():
    faults.configure("ply.write~merged:crash")
    with pytest.raises(faults.InjectedCrash):
        try:
            faults.fire("ply.write", item="/out/merged.ply")
        except Exception:  # per-item tolerance must NOT swallow a crash
            pytest.fail("InjectedCrash was caught by `except Exception`")


def test_plan_counts_and_env_override(monkeypatch):
    faults.configure("a.b:transient,c.d:permanent")
    plan = faults.active_plan()
    with pytest.raises(faults.TransientFault):
        faults.fire("a.b")
    assert plan.counts() == {"a.b": 1}

    monkeypatch.setenv("SL3D_FAULTS", "x.y:permanent")
    monkeypatch.setenv("SL3D_FAULTS_SEED", "3")

    class Cfg:
        spec = "a.b:transient"
        seed = 0

    plan = faults.configure_from(Cfg())
    assert plan.rules[0].site == "x.y" and plan.seed == 3
    monkeypatch.delenv("SL3D_FAULTS")
    assert faults.configure_from(Cfg()).rules[0].site == "a.b"


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_is_transient_classification():
    assert faults.is_transient(faults.TransientFault("x"))
    assert not faults.is_transient(faults.PermanentFault("x"))
    assert faults.is_transient(ConnectionResetError())
    assert faults.is_transient(TimeoutError())
    assert faults.is_transient(urllib.error.URLError("dropped"))
    assert faults.is_transient(OSError(11, "EAGAIN"))
    assert not faults.is_transient(OSError(2, "ENOENT"))
    # unknown types default to permanent: retrying a deterministic failure
    # only delays the quarantine decision
    assert not faults.is_transient(ValueError("corrupt"))
    assert not faults.is_transient(KeyError("k"))


# ---------------------------------------------------------------------------
# retry + backoff
# ---------------------------------------------------------------------------

def test_retry_call_backoff_schedule_exact():
    """The acceptance-criteria contract: retries happen exactly per policy —
    doubling from backoff_base_s, capped at backoff_max_s, max_retries
    total, then the ORIGINAL exception with the true attempt count."""
    policy = faults.RetryPolicy(max_retries=3, backoff_base_s=0.1,
                                backoff_max_s=0.25)
    sleeps, retries = [], []
    calls = {"n": 0}

    def always_transient():
        calls["n"] += 1
        raise faults.TransientFault(f"attempt {calls['n']}")

    with pytest.raises(faults.TransientFault, match="attempt 4") as ei:
        faults.retry_call(always_transient, policy,
                          on_retry=lambda n, e: retries.append(n),
                          sleep=sleeps.append)
    assert calls["n"] == 4                    # 1 try + 3 retries
    assert sleeps == [0.1, 0.2, 0.25]         # doubling, capped
    assert retries == [1, 2, 3]
    assert ei.value._sl3d_attempts == 4


def test_retry_call_recovers_and_skips_permanent():
    policy = faults.RetryPolicy(max_retries=2, backoff_base_s=0.0)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 2:
            raise faults.TransientFault("blip")
        return "ok"

    assert faults.retry_call(flaky, policy, sleep=lambda s: None) == "ok"

    def perma():
        state["n"] += 1
        raise faults.PermanentFault("dead")

    state["n"] = 0
    with pytest.raises(faults.PermanentFault):
        faults.retry_call(perma, policy, sleep=lambda s: None)
    assert state["n"] == 1  # permanent: no retry at all

    with pytest.raises(faults.InjectedCrash):  # crashes are never retried
        faults.retry_call(
            lambda: (_ for _ in ()).throw(faults.InjectedCrash("kill")),
            policy, sleep=lambda s: None)


def test_zero_budget_policy_disables_retry():
    policy = faults.RetryPolicy(max_retries=0)
    calls = {"n": 0}

    def f():
        calls["n"] += 1
        raise faults.TransientFault("x")

    with pytest.raises(faults.TransientFault):
        faults.retry_call(f, policy, sleep=lambda s: None)
    assert calls["n"] == 1


# ---------------------------------------------------------------------------
# failure records
# ---------------------------------------------------------------------------

def test_failure_record_from_annotated_exception():
    e = faults.annotate(faults.TransientFault("torn read"),
                        stage="load", attempts=3)
    rec = faults.FailureRecord.from_exception("compute", "scan_072deg", e)
    assert rec.stage == "load"          # annotation wins over the default
    assert rec.attempts == 3
    assert rec.view == "scan_072deg"
    assert rec.error_type == "TransientFault" and rec.transient
    d = rec.as_dict()
    assert d["stage"] == "load" and d["transient"] is True

    plain = faults.FailureRecord.from_exception("compute", "v",
                                                ValueError("bad"))
    assert plain.stage == "compute" and plain.attempts == 1
    assert not plain.transient


# ---------------------------------------------------------------------------
# host-scope kinds (ISSUE 9): worker.kill / worker.preempt(T) /
# net.partition(T)
# ---------------------------------------------------------------------------

def test_host_kind_grammar_and_duration_defaults():
    k = faults.FaultRule.parse("worker.item~w0:worker.kill")
    assert k.kind == "worker.kill" and k.match == "w0"
    assert k.times == 1                      # host kinds fire once by default

    p = faults.FaultRule.parse("worker.item:worker.preempt")
    assert p.kind == "worker.preempt"
    assert p.block_s == faults.PREEMPT_GRACE_DEFAULT_S
    p2 = faults.FaultRule.parse("worker.item:worker.preempt(0.3)")
    assert p2.block_s == 0.3

    n = faults.FaultRule.parse("worker.item:net.partition")
    assert n.kind == "net.partition"
    assert n.block_s == faults.PARTITION_DEFAULT_S
    n2 = faults.FaultRule.parse("worker.item~w1:net.partition(2.5)@2")
    assert n2.block_s == 2.5 and n2.arm_at == 2 and n2.match == "w1"


def test_host_kind_exception_types_and_payloads():
    faults.configure("a:worker.kill,b:worker.preempt(0.7),"
                     "c:net.partition(1.2)")
    # worker.kill / worker.preempt simulate host death: InjectedCrash
    # (BaseException) so they escape `except Exception` fault barriers
    with pytest.raises(faults.WorkerKilled):
        faults.fire("a")
    assert issubclass(faults.WorkerKilled, faults.InjectedCrash)
    with pytest.raises(faults.WorkerPreempted) as ei:
        faults.fire("b")
    assert ei.value.grace_s == 0.7
    assert issubclass(faults.WorkerPreempted, faults.InjectedCrash)
    # a partition is connectivity loss, not death: transient by contract
    with pytest.raises(faults.NetPartition) as ei:
        faults.fire("c")
    assert ei.value.duration_s == 1.2
    assert isinstance(ei.value, faults.TransientFault)
    assert faults.is_transient(ei.value)


def test_host_kinds_fire_once_by_default():
    faults.configure("worker.item:worker.kill")
    with pytest.raises(faults.WorkerKilled):
        faults.fire("worker.item", item="w0:view:0")
    faults.fire("worker.item", item="w0:view:1")     # second hit: no-op


# ---------------------------------------------------------------------------
# retry backoff jitter (ISSUE 9 satellite): full jitter, seeded
# ---------------------------------------------------------------------------

def test_jitter_draws_are_seed_deterministic():
    """jitter=True draws each sleep uniformly from [0, exponential
    ceiling] using the plan's dedicated seeded stream: two runs with the
    same seed sleep IDENTICALLY; a different seed draws differently."""
    policy = faults.RetryPolicy(max_retries=3, backoff_base_s=0.1,
                                backoff_max_s=0.25, jitter=True)

    def draws(seed: int) -> list[float]:
        faults.configure("x:transient", seed=seed)  # arms the jitter rng
        sleeps = []
        with pytest.raises(faults.TransientFault):
            faults.retry_call(_always_transient, policy,
                              sleep=sleeps.append)
        faults.reset()
        return sleeps

    a, b, c = draws(7), draws(7), draws(8)
    assert a == b
    assert a != c
    assert len(a) == 3
    # full jitter: every draw within its deterministic ceiling
    for got, ceiling in zip(a, [0.1, 0.2, 0.25]):
        assert 0.0 <= got <= ceiling


def _always_transient():
    raise faults.TransientFault("again")


def test_jitter_off_keeps_exact_schedule():
    """The default (jitter=False) stays byte-for-byte the PR-3 schedule —
    existing deadline/backoff acceptance tests rely on it."""
    policy = faults.RetryPolicy(max_retries=3, backoff_base_s=0.1,
                                backoff_max_s=0.25)
    assert policy.jitter is False
    sleeps = []
    with pytest.raises(faults.TransientFault):
        faults.retry_call(_always_transient, policy, sleep=sleeps.append)
    assert sleeps == [0.1, 0.2, 0.25]


def test_jitter_does_not_shift_probabilistic_decisions():
    """Drawing jitter must come from a SEPARATE stream: a %p plan fires
    the same faults whether or not retries jitter."""
    def fired_sites(jitter: bool) -> list[int]:
        faults.configure("s:transientx100%0.5", seed=3)
        policy = faults.RetryPolicy(max_retries=2, backoff_base_s=0.01,
                                    backoff_max_s=0.02, jitter=jitter)
        hits = []
        for i in range(30):
            try:
                faults.fire("s", item=str(i))
            except faults.TransientFault:
                hits.append(i)
                # burn some jittered (or fixed) retry sleeps in between
                with pytest.raises(faults.TransientFault):
                    faults.retry_call(_always_transient, policy,
                                      sleep=lambda s: None)
        faults.reset()
        return hits

    assert fired_sites(jitter=False) == fired_sites(jitter=True)
